import asyncio
import inspect
import os
import sys

import pytest

# Multi-chip sharding tests run on a virtual 8-device CPU mesh; set the flags
# before any jax import (only the jax-marked tests import jax at all).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Minimal async test support (pytest-asyncio is not in this image): coroutine
# tests and async(-generator) fixtures run on a per-test event loop.
# ---------------------------------------------------------------------------


@pytest.fixture
def event_loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()
    asyncio.set_event_loop(None)


@pytest.hookimpl(tryfirst=True)
def pytest_fixture_setup(fixturedef, request):
    func = fixturedef.func
    if not (inspect.isasyncgenfunction(func) or inspect.iscoroutinefunction(func)):
        return None
    loop = request.getfixturevalue("event_loop")
    kwargs = {
        name: (request if name == "request" else request.getfixturevalue(name))
        for name in fixturedef.argnames
    }
    if inspect.isasyncgenfunction(func):
        agen = func(**kwargs)
        value = loop.run_until_complete(agen.__anext__())

        def _finalize():
            try:
                loop.run_until_complete(agen.__anext__())
            except StopAsyncIteration:
                pass

        fixturedef.addfinalizer(_finalize)
    else:
        value = loop.run_until_complete(func(**kwargs))
    fixturedef.cached_result = (value, fixturedef.cache_key(request), None)
    return value


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    func = pyfuncitem.obj
    if not inspect.iscoroutinefunction(func):
        return None
    loop = pyfuncitem._request.getfixturevalue("event_loop")
    sig_params = inspect.signature(func).parameters
    kwargs = {
        name: pyfuncitem.funcargs[name]
        for name in sig_params
        if name in pyfuncitem.funcargs
    }
    loop.run_until_complete(func(**kwargs))
    return True


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: asyncio-based test")
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from the tier-1 gate")
