"""Chaos wrapper around a ``StoreService``.

Only constructed when ``chana.mq.chaos.enabled`` is set — a plain broker
keeps the bare store object and pays literally nothing. The wrapper
classifies every store method into a read / write / delete site and
consults the active plan before delegating; the flush barrier gets its own
site so a "slow disk" rule can stall confirms without touching the
in-memory fast path.

Fire-and-forget helpers (``*_nowait``, ``mark``) and internal trackers
pass straight through — they have no awaitable seam to inject into; their
durability is already funneled through ``flush``, which is wrapped.
"""

from __future__ import annotations

from functools import wraps

# method-name -> chaos site classification for StoreService
_READ = frozenset({
    "select_message", "select_messages", "select_message_metas",
    "select_queue", "all_queues", "iter_queue_msgs",
    "select_stream_segment", "stream_segment_metas", "select_stream_cursors",
    "all_exchanges", "select_exchange", "all_vhosts",
})
_WRITE = frozenset({
    "insert_message", "update_message_refer_count", "insert_queue_meta",
    "insert_queue_msg", "insert_queue_unacks", "replace_queue_msgs",
    "replace_queue_unacks", "update_queue_last_consumed",
    "insert_stream_segment", "update_stream_cursor", "insert_exchange",
    "insert_bind", "insert_exchange_bind", "insert_vhost", "archive_queue",
})
_DELETE = frozenset({
    "delete_message", "delete_messages", "delete_queue_msg",
    "delete_queue_msgs_offsets", "delete_queue_unacks", "delete_queue",
    "purge_queue_msgs", "delete_stream_segments", "delete_stream_data",
    "delete_exchange", "delete_bind", "delete_queue_binds",
    "delete_exchange_bind", "delete_exchange_binds_dest", "delete_vhost",
})


def _site_for(name: str) -> str | None:
    if name in _READ:
        return "store.read"
    if name in _WRITE:
        return "store.write"
    if name in _DELETE:
        return "store.delete"
    return None


class ChaosStore:
    """Injection proxy over a real store. ``drop`` on a store site means
    "the operation silently did nothing" — reads return None, writes and
    deletes are swallowed — which is how a torn/failed disk op looks to
    the layers above."""

    def __init__(self, inner, runtime) -> None:
        self._inner = inner
        self._chaos = runtime

    def flush(self, intervals=None):
        inner_awaitable = self._inner.flush(intervals)

        async def _flushed():
            fault = await self._chaos.fire("store.flush")
            if fault is not None and fault.kind == "drop":
                return None  # flush "lost": confirms stall until the next one
            return await inner_awaitable

        return _flushed()

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        site = _site_for(name)
        if site is None or not callable(attr):
            return attr

        @wraps(attr)
        async def _injected(*args, **kwargs):
            fault = await self._chaos.fire(site)
            if fault is not None and fault.kind == "drop":
                return None
            return await attr(*args, **kwargs)

        # cache so __getattr__ runs once per method name per instance
        object.__setattr__(self, name, _injected)
        return _injected
