"""Service-level objectives over the broker's existing telemetry.

``SLOEngine`` (engine.py) is the pure burn-rate evaluator; this module
adds the impure edge: :class:`SLISampler` turns the broker's monotonic
counters into per-tick (good, bad) SLI samples, and ``engine_from_config``
builds the engine from the ``chana.mq.slo.*`` knobs. The telemetry tick
(telemetry/service.py) drives both — one ``sample()`` + one ``evaluate()``
per tick, off the message path — and every burn/clear transition feeds the
event bus (``slo.burn-rate.<name>`` / ``slo.cleared.<name>``), the metrics
registry (``slo_violations_total``) and the structured log.

Surfaces: ``GET /admin/slo`` (cluster-aggregated via the ``slo.pull``
control-plane RPC), ``POST /admin/slo/configure`` (replace the spec set at
runtime), ``chanamq_slo_{budget_remaining,burn_rate,violations_total}``
Prometheus series, and a compact stamp on the /admin/health payload.
"""

from __future__ import annotations

from typing import Optional

from .engine import (  # noqa: F401
    SLI_KINDS, SLOEngine, SLOSpec, default_slos, specs_from_json,
)


class SLISampler:
    """Derives per-tick (good, bad) SLI deltas from broker counters.

    Keeps the previous tick's counter snapshot; each ``sample()`` returns
    the deltas since then, keyed by SLI kind. Latency is judged from the
    publish->deliver histogram's *delta* buckets (this tick's
    observations only), so one slow burst cannot poison the p99 forever.
    """

    def __init__(self, broker, latency_threshold_ms: float = 250.0,
                 federation_lag_records: int = 1000) -> None:
        self.broker = broker
        self.latency_threshold_ms = latency_threshold_ms
        self.federation_lag_records = federation_lag_records
        self._prev: dict[str, float] = {}
        self._prev_buckets: dict[str, list[int]] = {}

    def _delta(self, name: str, value: float) -> float:
        prev = self._prev.get(name, value)
        self._prev[name] = value
        return max(0.0, value - prev)

    def _latency_sample(self, hist, key: str = "") -> tuple[float, float]:
        """(good, bad) for a latency SLI: one sample per tick that saw
        deliveries — good iff the tick's delta p99 is under threshold.
        ``key`` separates the node-wide histogram's previous-bucket state
        from each tenant's."""
        buckets = list(hist.buckets)
        prev = self._prev_buckets.get(key)
        self._prev_buckets[key] = buckets
        if prev is None:
            return (0.0, 0.0)
        delta = [b - p for b, p in zip(buckets, prev)]
        count = sum(delta)
        if count <= 0:
            return (0.0, 0.0)
        target = 0.99 * count
        seen = 0
        p99_us = float("inf")
        for i, n in enumerate(delta):
            seen += n
            if seen >= target:
                p99_us = (float(hist.BOUNDS[i]) if i < len(hist.BOUNDS)
                          else float("inf"))
                break
        if p99_us <= self.latency_threshold_ms * 1000.0:
            return (1.0, 0.0)
        return (0.0, 1.0)

    def sample(self, ready: bool) -> dict[str, tuple[float, float]]:
        m = self.broker.metrics
        published = self._delta("published", float(m.published_msgs))
        refused = self._delta("refused", float(m.flow_publishes_refused))
        returned = self._delta("returned", float(m.returned_msgs))
        delivered = self._delta("delivered", float(m.delivered_msgs))
        dead = self._delta("dead", float(m.dead_lettered_msgs))
        expired = self._delta("expired", float(m.expired_msgs))
        samples = {
            "publish-success": (published, refused + returned),
            "delivery-success": (delivered, dead + expired),
            "readiness": (1.0, 0.0) if ready else (0.0, 1.0),
            "delivery-latency": self._latency_sample(
                m.publish_to_deliver_us),
        }
        registry = getattr(self.broker, "tenancy", None)
        if registry is not None:
            # tenant-scoped streams, keyed "<sli>@<tenant>" (the sample key
            # a tenant-scoped SLOSpec reads). Publish bad-events are the
            # tenant's quota/ACL refusals; the latency stream exists only
            # for tenants whose delivery-latency SLO attached a histogram.
            for name in sorted(registry.tenants):
                tenant = registry.tenants[name]
                samples[f"publish-success@{name}"] = (
                    self._delta(f"published@{name}",
                                float(tenant.published_total())),
                    self._delta(f"refused@{name}", float(tenant.refused)))
                samples[f"delivery-success@{name}"] = (
                    self._delta(f"delivered@{name}",
                                float(tenant.delivered_total())), 0.0)
                samples[f"readiness@{name}"] = samples["readiness"]
                if tenant.latency_hist is not None:
                    samples[f"delivery-latency@{name}"] = (
                        self._latency_sample(tenant.latency_hist, name))
        federation = getattr(self.broker, "federation", None)
        if federation is not None:
            # per-link streams reuse the tenant scoping machinery: a spec
            # with tenant="<link-name>" reads "federation-lag@<link>"; the
            # node-wide stream is judged on the worst link. Good iff the
            # link is up and its record lag is within budget — a down link
            # burns the budget even before the lag number catches up.
            worst_bad = 0.0
            for link in federation.links:
                bad = (link.state != "up"
                       or link.total_lag() > self.federation_lag_records)
                samples[f"federation-lag@{link.name}"] = (
                    (0.0, 1.0) if bad else (1.0, 0.0))
                worst_bad = max(worst_bad, float(bad))
            if federation.links:
                samples["federation-lag"] = (1.0 - worst_bad, worst_bad)
        return samples


def engine_from_config(config, interval_s: float = 1.0) -> SLOEngine:
    """Build the engine from ``chana.mq.slo.*`` (specs override defaults)."""
    raw = config.get("chana.mq.slo.specs")
    if raw:
        specs = specs_from_json(raw, interval_s)
    else:
        specs = default_slos(
            interval_s,
            objective=float(config.get("chana.mq.slo.objective") or 0.999),
            latency_ms=float(config.get("chana.mq.slo.latency-ms") or 250.0),
            fast_burn=float(config.get("chana.mq.slo.fast-burn") or 14.4),
            slow_burn=float(config.get("chana.mq.slo.slow-burn") or 6.0),
        )
    return SLOEngine(specs)


def attach_tenant_latency(engine: SLOEngine, registry) -> None:
    """Allocate per-tenant publish->deliver histograms for every
    delivery-latency spec that names a tenant (the delivery hot path only
    observes into a tenant histogram that exists). Call after building or
    replacing an engine while tenancy is enabled."""
    if registry is None:
        return
    for spec in engine.specs:
        if spec.tenant and spec.sli == "delivery-latency":
            tenant = registry.tenants.get(spec.tenant)
            if tenant is not None:
                tenant.attach_latency()
