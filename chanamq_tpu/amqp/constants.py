"""AMQP 0-9-1 protocol constants.

Capability parity with the reference's frame/error model
(chana-mq-base .../model/Frame.scala:38-216, .../model/ErrorCodes.scala:3-113),
expressed from the public AMQP 0-9-1 specification rather than by translation.
"""

from __future__ import annotations

import enum

# The 8-byte protocol handshake header: "AMQP" + %d0 + major.minor.revision.
PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"

FRAME_END = 0xCE

# Frame header is type(1) + channel(2) + payload-size(4); +1 for the end octet.
FRAME_HEADER_SIZE = 7
FRAME_OVERHEAD = FRAME_HEADER_SIZE + 1

# Spec minimum frame size every peer must accept before tuning.
FRAME_MIN_SIZE = 4096

DEFAULT_PORT = 5672
DEFAULT_TLS_PORT = 5671


class FrameType(enum.IntEnum):
    METHOD = 1
    HEADER = 2
    BODY = 3
    HEARTBEAT = 8


class ClassId(enum.IntEnum):
    CONNECTION = 10
    CHANNEL = 20
    ACCESS = 30
    EXCHANGE = 40
    QUEUE = 50
    BASIC = 60
    CONFIRM = 85
    TX = 90


class ErrorCode(enum.IntEnum):
    """AMQP reply codes. 2xx success, 3xx soft channel errors, 4xx channel
    errors, 5xx connection errors."""

    REPLY_SUCCESS = 200

    CONTENT_TOO_LARGE = 311
    NO_ROUTE = 312
    NO_CONSUMERS = 313
    ACCESS_REFUSED = 403
    NOT_FOUND = 404
    RESOURCE_LOCKED = 405
    PRECONDITION_FAILED = 406

    CONNECTION_FORCED = 320
    INVALID_PATH = 402
    FRAME_ERROR = 501
    SYNTAX_ERROR = 502
    COMMAND_INVALID = 503
    CHANNEL_ERROR = 504
    UNEXPECTED_FRAME = 505
    RESOURCE_ERROR = 506
    NOT_ALLOWED = 530
    NOT_IMPLEMENTED = 540
    INTERNAL_ERROR = 541

    @property
    def is_hard_error(self) -> bool:
        """Connection-level (hard) errors close the whole connection."""
        return self in _HARD_ERRORS


_HARD_ERRORS = frozenset(
    {
        ErrorCode.CONNECTION_FORCED,
        ErrorCode.INVALID_PATH,
        ErrorCode.FRAME_ERROR,
        ErrorCode.SYNTAX_ERROR,
        ErrorCode.COMMAND_INVALID,
        ErrorCode.CHANNEL_ERROR,
        ErrorCode.UNEXPECTED_FRAME,
        ErrorCode.RESOURCE_ERROR,
        ErrorCode.NOT_ALLOWED,
        ErrorCode.NOT_IMPLEMENTED,
        ErrorCode.INTERNAL_ERROR,
    }
)


class ExchangeType(str, enum.Enum):
    DIRECT = "direct"
    FANOUT = "fanout"
    TOPIC = "topic"
    HEADERS = "headers"

    @classmethod
    def of(cls, name: str) -> "ExchangeType":
        try:
            return cls(name.lower())
        except ValueError:
            raise ValueError(f"unknown exchange type: {name!r}") from None
