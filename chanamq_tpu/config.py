"""Layered configuration tree.

Capability parity with the reference's Typesafe-HOCON settings system
(chana-mq-base Settings.scala:29-219 and the reference.conf trees,
chana-mq-server reference.conf:107-179): a typed accessor layer over layered
sources — built-in defaults <- config file (JSON) <- environment variables —
keeping the reference's knob names (dotted paths under ``chana.mq``) where
they exist, e.g.:

    chana.mq.amqp.interface / port / amqps.port      (listeners)
    chana.mq.amqp.connection.heartbeat / frame-max / channel-max
    chana.mq.internal.timeout                        (internal op timeout)
    chana.mq.message.inactive                        (passivation age)
    chana.mq.admin.port                              (localhost admin REST)
    chana.mq.vhost.separator / default
    chana.mq.store.path                              (sqlite file; absent =
                                                      in-memory transient)
    chana.mq.cluster.*                               (cluster layer)

Env override: dots/dashes become underscores, upper-cased, prefixed CHANAMQ_
(e.g. CHANAMQ_AMQP_PORT=5673 overrides chana.mq.amqp.port).

Durations accept int seconds or strings like "30s"/"500ms"/"infinite"
(the reference's "infinite"-aware parser, Settings.scala:60-77); sizes accept
int bytes or "128KiB"/"4MiB".
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Mapping, Optional

DEFAULTS: dict[str, Any] = {
    "chana.mq.amqp.interface": "0.0.0.0",
    "chana.mq.amqp.port": 5672,
    "chana.mq.amqp.amqps.enabled": False,
    "chana.mq.amqp.amqps.port": 5671,
    "chana.mq.amqp.amqps.certfile": None,
    "chana.mq.amqp.amqps.keyfile": None,
    "chana.mq.amqp.connection.heartbeat": "30s",
    "chana.mq.amqp.connection.frame-max": "128KiB",
    "chana.mq.amqp.connection.channel-max": 2047,
    # listener resource limits (reference: ServerSettings max-connections /
    # backlog, Settings.scala:141-219). Connections beyond max-connections
    # are refused at accept time with a TCP close; existing traffic is
    # unaffected. 0 disables the cap.
    "chana.mq.server.max-connections": 1024,
    "chana.mq.server.backlog": 128,
    # optional SASL PLAIN verification: {"user": "password", ...}. Empty
    # disables verification (the reference parses but never verifies,
    # SaslMechanism.scala:49-76); configuring users also refuses EXTERNAL.
    "chana.mq.auth.users": None,
    # optional per-user vhost allowlists: {"user": ["/", "tenant-a"], ...}.
    # Only consulted when users are configured; a user absent from the map
    # may open ANY vhost (allowlist opt-in per user).
    "chana.mq.auth.permissions": None,
    # delivery acknowledgement timeout (RabbitMQ consumer_timeout, same
    # 30-minute default): a delivery unacked past this closes its channel
    # with PRECONDITION_FAILED and requeues. "infinite" disables.
    "chana.mq.consumer.timeout": "30m",
    "chana.mq.internal.timeout": "20s",
    "chana.mq.message.inactive": "1h",
    "chana.mq.message.sweep-interval": "1s",
    # per-queue resident-message watermark: beyond this many queued messages,
    # durable+persistent bodies are paged out to the store and hydrated back
    # on demand (the reference's passivation knob chana.mq.message.inactive,
    # MessageEntity.scala:168-198, recast from age-based to depth-based).
    # 0 disables passivation.
    "chana.mq.queue.max-resident": 16384,
    # inbound publisher backpressure: above high-watermark resident message
    # bytes, publishing connections stop being read (and capable clients get
    # Connection.Blocked) until the gauge falls below low-watermark.
    # 0 / null disables the gate (per-queue passivation still bounds memory).
    "chana.mq.memory.high-watermark": "512MiB",
    "chana.mq.memory.low-watermark": None,  # default: 80% of high
    # flow-control ladder (chanamq_tpu/flow/): one MemoryAccountant sums
    # every accounted resident cost (queue bodies, parked publishes,
    # connection out-buffers, WAL memtable, data-plane buffers, stream
    # cache) and degrades gracefully through four stages, mildest first:
    #   1 page      > page-watermark:   page bodies to the store early
    #   2 throttle  > high-watermark:   Channel.Flow(false) + publish
    #                                   credit, then parked reads (the
    #                                   legacy memory gate, now staged)
    #   3 cluster   > cluster-watermark: shrink data-plane windows, stall
    #                                   inbound cluster push batches
    #   4 refuse    > refuse-watermark: refuse new publishes (406) while
    #                                   consumers drain; /admin/health
    #                                   goes not-ready
    # Each stage exits at (enter * low/high) — the same hysteresis the
    # binary gate had, so no stage can flap. The memory.high/low
    # watermarks above anchor the ladder; these knobs tune the rest
    # (None = derived defaults, shown beside each).
    "chana.mq.flow.page-watermark": None,     # default 60% of high
    "chana.mq.flow.cluster-watermark": None,  # default midway high->refuse
    "chana.mq.flow.refuse-watermark": None,   # default 90% of hard
    "chana.mq.flow.hard-limit": None,         # default 2x high
    # bytes a throttled connection may still publish before its reads
    # park (grace for clients honoring Channel.Flow); 0 = park at once
    "chana.mq.flow.publish-credit": "256KiB",
    # per-consumer delivery-buffer bound: a consumer whose unsent
    # rendered deliveries exceed this is skipped by dispatch (and counted
    # slow) until the connection's output buffer drains. 0 = unbounded.
    "chana.mq.flow.consumer-buffer": "4MiB",
    # per-connection parked-publish cap while the gate is closed
    # (overrides the built-in 256KiB when set)
    "chana.mq.flow.park-buffer": None,
    # resident-per-queue cap while the ladder is at/above the page stage
    # (tightens chana.mq.queue.max-resident under pressure)
    "chana.mq.flow.page-resident": 256,
    "chana.mq.admin.enabled": True,
    "chana.mq.admin.interface": "127.0.0.1",
    "chana.mq.admin.port": 15672,
    "chana.mq.vhost.default": "/",
    # declared-content-size cap per message: chunks buffer in the command
    # assembler before backpressure can account them (0 = unlimited)
    "chana.mq.message.max-size": "128MiB",
    "chana.mq.store.path": None,
    # sqlite PRAGMA synchronous: NORMAL survives process crashes (WAL
    # replay); FULL additionally fsyncs every group commit so confirmed
    # messages survive power loss, at a persistent-throughput cost
    "chana.mq.store.synchronous": "NORMAL",
    # write-ahead log engine (chanamq_tpu/wal/): when a store path is set,
    # durable mutations append to a per-shard segment log whose commit loop
    # batches ONE fsync across all channels/queues/subsystems per flush
    # window; SQLite becomes the read index, drained by a background
    # checkpointer. false = store-direct (PR 1-7 behavior).
    "chana.mq.wal.enabled": True,
    # group-commit window: an append waits at most this long for peers to
    # share its fsync (latency floor for awaited durable ops and confirms)
    "chana.mq.wal.flush-ms": 2,
    # cut the window early once this many bytes are buffered
    "chana.mq.wal.flush-bytes": "1MiB",
    # active segment seals at this size; sealed segments are truncated
    # whole once the checkpoint covers them
    "chana.mq.wal.segment-bytes": "64MiB",
    # durability tier: "fsync" survives power loss (fsync per group
    # commit + SQLite checkpoint fsync); "os" leaves commits in the OS
    # page cache — survives SIGKILL, not power loss — and skips both
    "chana.mq.wal.sync": "fsync",
    # checkpoint cadence: drain committed records into the SQLite index,
    # truncate covered segments, run stream-segment maintenance
    "chana.mq.wal.checkpoint-ms": 1000,
    # memtable cap: pending index ops (and their overlay blobs) drain
    # early once they outgrow this, bounding RAM between checkpoints
    "chana.mq.wal.memtable-bytes": "64MiB",
    # tiered offload: keep this many newest sealed stream segments hot in
    # SQLite; older blobs move to side files (index rows stay, reads
    # rehydrate). 0 disables offload.
    "chana.mq.wal.tier-keep-segments": 2,
    # key compaction for stream queues declared with x-stream-compact:
    # newest record per routing key survives in sealed segments
    "chana.mq.wal.compact-streams": True,
    # store-growth gate: when passivation/page-out absorbs a flood, RAM
    # stays flat but the store grows — above this live-data size the
    # publisher gate closes (like the memory watermark), reopening at 80%.
    # None/0 disables. Sampled each sweep tick.
    "chana.mq.store.max-bytes": None,
    # telemetry forecasting (models/service.py): sample broker metrics into
    # a ring each interval; train/predict the JAX forecaster off the event
    # loop every train-interval; serve GET /admin/forecast + Prometheus
    # gauges. Off by default — enabling spins an accelerator workload.
    "chana.mq.forecast.enabled": False,
    "chana.mq.forecast.interval": "1s",
    "chana.mq.forecast.train-interval": "30s",
    "chana.mq.forecast.window": 64,     # telemetry vectors per model input
    "chana.mq.forecast.history": 4096,  # ring capacity (vectors retained)
    # per-queue forecaster awareness: widen the feature vector with
    # (depth, publish_rate) of the K busiest queues from the per-entity
    # telemetry rings. 0 = node-total features only; >0 requires
    # chana.mq.telemetry.enabled.
    "chana.mq.forecast.queue-top-k": 0,
    # predictive control plane (control/): closes the forecast->actuation
    # loop. Each interval a ControlService snapshots flow-ladder state,
    # telemetry and (when fresh + trusted) the forecast, evaluates
    # off-loop, and emits hysteresis-guarded decisions: predictive
    # admission (pre-arm the stage-2 throttle + shrink publish credit
    # before the watermark), proactive queue rebalancing (holdership
    # handoff toward the cluster mean), and prefetch autotuning (nudge
    # the consume-credit window). Off by default; dry-run by default when
    # on — decisions are logged + counted but actuate nothing until
    # dry-run is lifted (the rollout path; also POST /admin/control).
    "chana.mq.control.enabled": False,
    "chana.mq.control.dry-run": True,
    "chana.mq.control.interval": "1s",
    "chana.mq.control.horizon": "5s",        # projection lookahead
    "chana.mq.control.arm-ticks": 2,         # consecutive trigger ticks
    "chana.mq.control.cooldown": "10s",      # per-kind decision spacing
    "chana.mq.control.admission.enabled": True,
    "chana.mq.control.admission.credit-factor": 0.5,
    "chana.mq.control.admission.credit-min": "4KB",
    "chana.mq.control.rebalance.enabled": True,
    "chana.mq.control.rebalance.ratio": 1.5,  # self vs cluster-mean load
    "chana.mq.control.rebalance.min-rate": "1KB",  # bytes/s floor
    "chana.mq.control.rebalance.cooldown": "30s",
    "chana.mq.control.prefetch.enabled": True,
    "chana.mq.control.prefetch.min": 8,
    "chana.mq.control.prefetch.max": 256,
    "chana.mq.control.log-size": 256,        # retained decisions
    "chana.mq.control.forecast-max-age": "10s",
    # trust gate: use the forecast only while its publish-bytes-rate MAE
    # stays under this fraction of the observed inflow; otherwise fall
    # back to the reactive trend
    "chana.mq.control.forecast-error-gate": 0.5,
    # per-entity telemetry (telemetry/): fixed-slot timeseries ring per
    # queue and per connection, sampled off the hot path each interval;
    # event-loop lag + sampler saturation probes; /admin/timeseries,
    # /admin/health (readiness with reasons), /admin/alerts
    "chana.mq.telemetry.enabled": False,
    "chana.mq.telemetry.interval": "1s",
    "chana.mq.telemetry.ring-ticks": 120,      # history per entity
    "chana.mq.telemetry.max-queues": 512,      # entity slots (fixed memory)
    "chana.mq.telemetry.max-connections": 256,
    "chana.mq.telemetry.top-k": 8,             # default top-K summary size
    # readiness thresholds (/admin/health flips 503 past these)
    "chana.mq.telemetry.ready-loop-lag-ms": 1000,
    "chana.mq.telemetry.ready-repl-lag": 10000,
    "chana.mq.telemetry.store-error-window": 30,  # ticks
    # declarative alert rules evaluated over the per-entity matrix each
    # tick (telemetry/alerts.py): thresholds for the four built-ins;
    # hysteresis is tick-counted inside the rules
    "chana.mq.alerts.enabled": True,   # gates evaluation, not sampling
    "chana.mq.alerts.backlog-growth": 100,   # ready msgs gained per window
    "chana.mq.alerts.backlog-window": 5,     # growth lookback, ticks
    "chana.mq.alerts.stall-ticks": 3,        # zero-deliver ticks -> stall
    "chana.mq.alerts.repl-lag": 1000,        # events behind
    "chana.mq.alerts.loop-lag-ms": 250,      # event-loop lag
    "chana.mq.alerts.memory-stage": 3.5,     # flow stage (fires at refuse)
    "chana.mq.cluster.enabled": False,
    "chana.mq.cluster.host": "127.0.0.1",
    "chana.mq.cluster.port": 25672,
    "chana.mq.cluster.seeds": [],
    "chana.mq.cluster.heartbeat-interval": "1s",
    "chana.mq.cluster.failure-timeout": "5s",
    "chana.mq.cluster.virtual-nodes": 64,
    # interconnect data plane (cluster/dataplane.py): parallel binary
    # streams per peer, per-stream pipelining window, and the adaptive
    # micro-batch flush window (cut early by the byte/count caps)
    "chana.mq.cluster.streams": 2,
    "chana.mq.cluster.stream-inflight": 32,
    "chana.mq.cluster.flush-window-us": 200,
    "chana.mq.cluster.flush-max-bytes": "1MiB",
    "chana.mq.cluster.flush-max-count": 512,
    "chana.mq.cluster.consume-credit": 1024,
    "chana.mq.cluster.call-timeout": "10s",
    # multi-process sharding (chanamq_tpu/shard/): count > 1 makes
    # `python -m chanamq_tpu.broker.server` run a supervisor that spawns
    # one worker process per shard; 0 = auto (os.cpu_count()); 1 = off.
    # Workers share the AMQP port via SO_REUSEPORT (or the fd-handoff
    # acceptor when reuse-port is unavailable) and talk to each other
    # over Unix sockets in shard.dir using the binary data plane.
    "chana.mq.shard.count": 1,
    "chana.mq.shard.dir": "",              # "" = <store dir or cwd>/shards
    "chana.mq.shard.reuse-port": True,     # False forces the fd handoff
    # intra-node membership runs much tighter than WAN defaults: sibling
    # death must re-hash ownership in well under a second
    "chana.mq.shard.heartbeat-interval": "200ms",
    "chana.mq.shard.failure-timeout": "1.5s",
    # supervisor restart throttle for crashed workers
    "chana.mq.shard.restart-backoff": "500ms",
    "chana.mq.shard.max-restarts": 16,     # per shard; then left down
    # queue replication (replicate/): each queue's mutations are log-shipped
    # to factor-1 follower nodes which keep a warm passive copy; on owner
    # death the highest-synced follower promotes. factor=1 disables.
    "chana.mq.replicate.factor": 1,
    # sync=true gates publisher confirms on follower acks (no confirmed
    # persistent message can be lost to a single node failure); sync=false
    # ships asynchronously (bounded loss window = replication lag).
    "chana.mq.replicate.sync": False,
    "chana.mq.replicate.batch-max": 256,   # events per shipped batch
    "chana.mq.replicate.ack-timeout-ms": 1000,
    # node lifecycle (cluster/lifecycle.py): graceful drain / decommission.
    # A draining node stops taking new holdership, evacuates every held
    # queue via handoff with bounded retry, then gossips `left`.
    "chana.mq.lifecycle.drain-retry-limit": 5,
    "chana.mq.lifecycle.drain-backoff": "100ms",      # first retry delay
    "chana.mq.lifecycle.drain-backoff-cap": "2s",     # retry delay ceiling
    # evacuation budget: past this the drain-stuck alert fires (the drain
    # itself keeps retrying as long as any pass still makes progress)
    "chana.mq.lifecycle.drain-budget": "30s",
    # stream queues (streams/): append-only segmented logs declared with
    # x-queue-type=stream. The active in-memory segment seals and spills
    # to the store at segment-bytes or segment-age, whichever first
    # (x-stream-max-segment-size-bytes overrides the size per queue).
    "chana.mq.stream.segment-bytes": "1MiB",
    "chana.mq.stream.segment-age": "10s",
    # sealed segments kept hot in RAM; replaying cursors reload evicted
    # blobs from the store one segment at a time
    "chana.mq.stream.cache-segments": 4,
    # records one cursor may take per coalesced dispatch pass (fairness
    # slice across cursors; prefetch credit still gates each delivery)
    "chana.mq.stream.delivery-batch": 128,
    # fault injection (chanamq_tpu/chaos/): disabled by default — the
    # broker's I/O seams stay no-op hooks unless this is set at boot
    "chana.mq.chaos.enabled": False,
    # RNG seed for the deterministic fault schedule (same seed = same run)
    "chana.mq.chaos.seed": 0,
    # optional path to a JSON fault-plan file installed at boot; empty =
    # chaos armed but idle until a plan arrives via POST /admin/chaos/install
    "chana.mq.chaos.plan": "",
    # message tracing (chanamq_tpu/trace/): disabled by default — every
    # hot-path seam stays a module-level `ACTIVE is None` check
    "chana.mq.trace.enabled": False,
    # fraction of publishes that mint a trace (0.0 .. 1.0); the sampling
    # RNG is seeded from the chaos seed so soak runs sample deterministically
    "chana.mq.trace.sample-rate": 0.01,
    # completed traces kept in the recent ring (slow/chaos-tagged traces
    # get a second ring of the same size so they survive churn)
    "chana.mq.trace.ring-size": 256,
    # traces slower end-to-end than this always land in the slow ring
    "chana.mq.trace.slow-ms": 250,
    # structured JSON log lines stamped with node id + active trace id
    "chana.mq.log.json": False,
    # OTLP span export (chanamq_tpu/otel/): drains completed traces into
    # OTLP/HTTP JSON batches. Requires chana.mq.trace.enabled to have
    # anything to export. With an empty endpoint the exporter runs in
    # collector-less mode: completed traces queue (bounded) for the pull
    # fallback GET /admin/otel/spans instead of being pushed.
    "chana.mq.otel.enabled": False,
    # OTLP/HTTP collector URL, e.g. http://127.0.0.1:4318/v1/traces
    "chana.mq.otel.endpoint": "",
    # push flush window (batches post at most this often)
    "chana.mq.otel.flush-ms": 1000,
    # max traces rendered into one OTLP/HTTP POST
    "chana.mq.otel.max-batch": 64,
    # bounded exporter queue; overflow (or flow stage >= 1) sheds with
    # the otel_spans_shed counter instead of growing memory
    "chana.mq.otel.queue-size": 1024,
    # data-parallel tensorized router (chanamq_tpu/router/): fused single
    # node publishes defer into a per-connection buffer and the whole read
    # batch routes through compiled binding tables in one kernel call.
    # The Python matchers stay as the always-available fallback (and the
    # parity oracle); disabling restores per-message routing everywhere.
    "chana.mq.router.enabled": True,
    # "jax" runs the match kernels under jax.jit; "python" runs the same
    # kernel body on plain numpy (runtime-selectable pure-Python fallback)
    "chana.mq.router.backend": "jax",
    # flushes smaller than this skip the kernel and walk the matcher —
    # below ~16 messages the per-call dispatch overhead beats the win
    "chana.mq.router.min-batch": 16,
    # caps on what compiles: an exchange with more wildcard topic patterns
    # (or headers bindings) than max-wildcards, or more kernel-routed
    # queues than max-queues, stays on the Python matcher. Exact-match
    # patterns are host dicts and don't count against either cap.
    "chana.mq.router.max-wildcards": 512,
    "chana.mq.router.max-queues": 4096,
    # cross-check every kernel batch against the Python oracle and prefer
    # the oracle on mismatch (router_parity_mismatches counts them) —
    # a debugging net, not for production throughput
    "chana.mq.router.verify": False,
    # advanced delivery semantics (chanamq_tpu/semantics/): atomic Tx
    # commits on the WAL scope, bind-time e2e cycle refusal, and x-delay
    # delayed delivery. Off removes the per-publish x-delay probe and the
    # cycle check; queue-argument features (x-max-priority ordering,
    # dead-lettering) are declared per queue and stay on either way.
    "chana.mq.semantics.enabled": True,
    # timer-wheel granularity for x-delay delayed delivery: fires land
    # within one tick after their delay elapses
    "chana.mq.semantics.delay-tick": "50ms",
    # native batch egress (native/chanamq_native.cpp): basic.deliver
    # records from a dispatch pass render in ONE chana_encode_deliveries
    # call into a pooled native buffer, and the connection writer drains
    # its buffer list with scatter-gather sendmsg. Off (or a missing /
    # stale native lib, or CHANAMQ_NATIVE=0) restores per-delivery Python
    # rendering; wire bytes are identical either way.
    "chana.mq.native.egress": True,
    # egress arena sizing: buffers x buffer-kb is the pooled memory the
    # process reserves (defaults: 16 x 256 KiB = 4 MiB); batches larger
    # than one buffer, or arriving while the pool is dry, fall back to a
    # fresh heap buffer (native_pool_exhausted counts the dry acquires)
    "chana.mq.native.pool-buffers": 16,
    "chana.mq.native.pool-buffer-kb": 256,
    # continuous profiling (chanamq_tpu/profile/): disabled by default —
    # every hot-path seam stays a module-level `ACTIVE is None` check.
    # Enabled, the per-message cost ledger accumulates per-stage CPU-ns
    # into fixed numpy vectors (batch-granular on the batched paths) and
    # serves GET /admin/profile + profile_stage_* Prometheus series
    "chana.mq.profile.enabled": False,
    # stack-sampling rate for the folded-stack profiler thread
    # (GET /admin/profile/stacks); 0 = sampler off, watchdog only
    "chana.mq.profile.sample-hz": 0,
    # event-loop callbacks stalling the loop longer than this are captured
    # (stack + duration) into the slow-callback ring, logged as structured
    # JSON, and counted in profile_slow_callbacks_total; 0 = watchdog off
    "chana.mq.profile.slow-callback-ms": 100,
    # bounded ring of recent slow-callback captures kept for /admin/profile
    "chana.mq.profile.ring-size": 64,
    # attribute collector pauses via gc.callbacks (the "gc" ledger stage)
    "chana.mq.profile.gc": True,
    # broker-native event bus (chanamq_tpu/events/): internal transitions
    # (alert.fired.<rule>, control.decision.<kind>, lifecycle.<state>,
    # flow.stage.<n>, chaos.fired.<rule>, profile.slow-callback,
    # connection.*, queue.*, shard.restarted, slo.burn-rate.<name>)
    # published as AMQP messages on the amq.chanamq.event topic exchange
    # of this vhost. Off = every emit seam is one `ACTIVE is None` check;
    # on with nothing bound = O(1) counted drop per event.
    "chana.mq.events.enabled": False,
    "chana.mq.events.vhost": "/",
    # firehose tracer: republish every publish/deliver into
    # amq.chanamq.trace (keys publish.<exchange> / deliver.<queue>),
    # shedding taps whenever the flow accountant leaves stage 0 so a slow
    # firehose consumer can never build unbounded memory. queue-filter
    # narrows the tap to queues whose name starts with the prefix.
    "chana.mq.firehose.enabled": False,
    "chana.mq.firehose.vhost": "/",
    "chana.mq.firehose.queue-filter": "",
    # tenant-filter sibling of queue-filter: when set, only taps whose
    # vhost belongs to the named tenant are republished (requires
    # chana.mq.tenant.enabled).
    "chana.mq.firehose.tenant": "",
    # multi-tenancy (chanamq_tpu/tenancy/): tenants map is
    # {"name": {"vhosts": [...], "users": {...}, "acls": {...},
    #  "quota": {"max-connections": N, ..., "memory-share": 0.25,
    #  "publish-rate": bytes/s, "publish-burst": bytes}} — see
    # tenancy.registry for the full spec. Tenants declared while
    # enabled=false are a boot error (fail closed, like auth knobs).
    "chana.mq.tenant.enabled": False,
    "chana.mq.tenant.tenants": None,
    # SLO engine (chanamq_tpu/slo/): burn-rate error budgets over the
    # telemetry tick (requires chana.mq.telemetry.enabled). Default specs
    # cover publish availability, delivery success, readiness, and
    # delivery p99 latency; replace them with chana.mq.slo.specs (a JSON
    # list, see slo.specs_from_json) or POST /admin/slo/configure.
    "chana.mq.slo.enabled": False,
    "chana.mq.slo.objective": 0.999,        # default success-ratio target
    "chana.mq.slo.latency-ms": 250,         # p99 bound for the latency SLO
    "chana.mq.slo.fast-burn": 14.4,         # 5m/1h pair burn threshold
    "chana.mq.slo.slow-burn": 6.0,          # 6h/3d pair burn threshold
    "chana.mq.slo.specs": None,
    # a federation-lag SLI tick is good while every link's record lag is
    # at or under this bound (slo/__init__.py samples it per link)
    "chana.mq.slo.federation-lag-records": 1000,
    # cross-cluster federation (chanamq_tpu/federation/): a dedicated
    # listener serves the fed.* handlers (mirror side); links is a JSON
    # array of {name, host, port, vhost, queues, exchanges, window}
    # specs naming the remotes this node ships to (shipper side).
    "chana.mq.federation.enabled": False,
    "chana.mq.federation.interface": "127.0.0.1",
    "chana.mq.federation.port": 0,          # 0 = ephemeral (tests/bench)
    "chana.mq.federation.links": None,
    "chana.mq.federation.window": 4,        # per-link in-flight sends
    "chana.mq.federation.retry": "500ms",   # down-link reconnect pace
    "chana.mq.federation.idle-tick": "200ms",  # pump tick with no wake
    # shared secret on the fed listener; "" = open (trusted network).
    # The listener sits outside the AMQP SASL/ACL path, so this token is
    # its whole admission control. Links present it outbound too (a
    # per-link `token` in the spec overrides for asymmetric pairs).
    "chana.mq.federation.auth-token": "",
}

_DURATION_RE = re.compile(r"^\s*([0-9.]+)\s*(ms|s|m|h|d)?\s*$")
_DURATION_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
_SIZE_RE = re.compile(r"^\s*([0-9.]+)\s*(B|KiB|KB|MiB|MB|GiB|GB)?\s*$", re.I)
_SIZE_UNITS = {
    "b": 1, "kib": 1024, "kb": 1000, "mib": 1024**2,
    "mb": 1000**2, "gib": 1024**3, "gb": 1000**3,
}


class ConfigError(ValueError):
    pass


def parse_duration_s(value: Any) -> Optional[float]:
    """'30s' -> 30.0; 'infinite'/'off'/None -> None (disabled)."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    text = str(value).strip().lower()
    if text in ("infinite", "inf", "off", "none"):
        return None
    match = _DURATION_RE.match(text)
    if not match:
        raise ConfigError(f"bad duration: {value!r}")
    return float(match.group(1)) * _DURATION_UNITS.get(match.group(2) or "s", 1.0)


def parse_size_bytes(value: Any) -> Optional[int]:
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return int(value)
    match = _SIZE_RE.match(str(value))
    if not match:
        raise ConfigError(f"bad size: {value!r}")
    return int(float(match.group(1)) * _SIZE_UNITS[(match.group(2) or "B").lower()])


def _env_key(path: str) -> str:
    # chana.mq.amqp.frame-max -> CHANAMQ_AMQP_FRAME_MAX
    trimmed = path[len("chana.mq."):] if path.startswith("chana.mq.") else path
    return "CHANAMQ_" + trimmed.replace(".", "_").replace("-", "_").upper()


# keys whose VALUE is a mapping: flattening stops here so a config file's
# {"auth": {"users": {...}}} arrives as one dict, not per-user leaf keys
_DICT_LEAF_KEYS = frozenset(
    {"chana.mq.auth.users", "chana.mq.auth.permissions",
     "chana.mq.tenant.tenants"})


def _flatten(tree: Mapping[str, Any], prefix: str = "") -> dict[str, Any]:
    flat: dict[str, Any] = {}
    for key, value in tree.items():
        path = f"{prefix}.{key}" if prefix else key
        full = path if path.startswith("chana.") else f"chana.mq.{path}"
        if isinstance(value, Mapping) and full not in _DICT_LEAF_KEYS:
            flat.update(_flatten(value, path))
        else:
            flat[path] = value
    return flat


class Config:
    """Layered key-value config with typed accessors."""

    def __init__(
        self,
        overrides: Optional[Mapping[str, Any]] = None,
        *,
        file: Optional[str] = None,
        env: Optional[Mapping[str, str]] = None,
    ) -> None:
        self._values = dict(DEFAULTS)
        if file:
            with open(file) as f:
                loaded = json.load(f)
            flat = _flatten(loaded)
            for key, value in flat.items():
                # accept both full paths and paths relative to chana.mq
                full = key if key.startswith("chana.") else f"chana.mq.{key}"
                self._values[full] = value
        env = os.environ if env is None else env
        for path in list(self._values):
            env_value = env.get(_env_key(path))
            if env_value is not None:
                if path in _DICT_LEAF_KEYS:
                    # dict-valued key from the environment: JSON only
                    # (e.g. CHANAMQ_AUTH_USERS='{"alice": "pw"}')
                    try:
                        parsed = json.loads(env_value)
                    except json.JSONDecodeError as exc:
                        raise ConfigError(
                            f"{_env_key(path)} must be a JSON object: {exc}"
                        ) from None
                    if not isinstance(parsed, dict):
                        raise ConfigError(
                            f"{_env_key(path)} must be a JSON object")
                    self._values[path] = parsed
                else:
                    self._values[path] = _coerce(env_value, self._values[path])
        if overrides:
            for key, value in overrides.items():
                full = key if key.startswith("chana.") else f"chana.mq.{key}"
                self._values[full] = value

    def get(self, path: str, default: Any = None) -> Any:
        return self._values.get(path, default)

    def str(self, path: str) -> str:
        return str(self._values[path])

    def int(self, path: str) -> int:
        return int(self._values[path])

    def bool(self, path: str) -> bool:
        value = self._values[path]
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)

    def duration_s(self, path: str) -> Optional[float]:
        return parse_duration_s(self._values[path])

    def size_bytes(self, path: str) -> Optional[int]:
        return parse_size_bytes(self._values[path])

    def list(self, path: str) -> list:
        value = self._values[path]
        if isinstance(value, str):
            return [part.strip() for part in value.split(",") if part.strip()]
        return list(value or [])

    def dump(self) -> dict[str, Any]:
        return dict(self._values)


def _coerce(text: str, previous: Any) -> Any:
    if isinstance(previous, bool):
        return text.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(previous, int) and not isinstance(previous, bool):
        try:
            return int(text)
        except ValueError:
            return text
    if isinstance(previous, float):
        try:
            return float(text)
        except ValueError:
            return text
    if isinstance(previous, list):
        return [part.strip() for part in text.split(",") if part.strip()]
    return text
