"""AMQP/AMQPS TCP listener.

Capability parity with the reference's transport extension + process entry
(chana-mq-base Amqp.scala:39-331 startServer/sslTlsStage; chana-mq-server
AMQPServer.scala:39-111): plain AMQP listener (5672), optional TLS listener
(5671), per-connection protocol engine instances, clean shutdown.

Run standalone:  python -m chanamq_tpu.broker.server [--port 5672]
"""

from __future__ import annotations

import asyncio
import logging
import os
import ssl
from typing import Optional

from ..store.api import StoreService
from .broker import Broker
from .connection import AMQPConnection

log = logging.getLogger("chanamq.server")


class BrokerServer:
    def __init__(
        self,
        broker: Optional[Broker] = None,
        host: str = "0.0.0.0",
        port: int = 5672,
        *,
        tls_port: Optional[int] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
        heartbeat_s: int = 30,
        frame_max: int = 131072,
        channel_max: int = 2047,
        store: Optional[StoreService] = None,
        max_connections: int = 0,
        backlog: int = 128,
        max_message_size: int = 128 * 1024 * 1024,
        users: "Optional[dict[str, str]]" = None,
        permissions: "Optional[dict[str, list[str]]]" = None,
        reuse_port: bool = False,
    ) -> None:
        self.broker = broker or Broker(store=store)
        self.host = host
        self.port = port
        self.tls_port = tls_port
        self.ssl_context = ssl_context
        self.heartbeat_s = heartbeat_s
        self.frame_max = frame_max
        self.channel_max = channel_max
        # listener resource limits (reference: ServerSettings
        # max-connections / backlog, Settings.scala:141-219); 0 = uncapped
        self.max_connections = max_connections
        self.backlog = backlog
        # optional SASL PLAIN verification: user -> password. None/empty
        # keeps the reference's behavior (parse but never verify,
        # SaslMechanism.scala:49-76); configuring users turns real
        # authentication on (EXCEEDS the reference, README "Status": auth
        # unimplemented there).
        self.users = users or None
        # per-user vhost allowlists (consulted only when users are set):
        # a user listed here may open ONLY those vhosts
        self.permissions = permissions or None
        self.max_message_size = max_message_size
        self.refused_connections = 0
        # sharded node (chanamq_tpu/shard/): sibling workers share one
        # AMQP port via SO_REUSEPORT; where that's unavailable the
        # supervisor accepts and ships fds to handoff_path instead
        self.reuse_port = reuse_port
        self.handoff_path: Optional[str] = None
        self._handoff = None
        self._servers: list[asyncio.AbstractServer] = []
        self._connections: set[AMQPConnection] = set()

    async def start(self, *, listen: bool = True) -> None:
        """Start the broker and (by default) open the listeners. Pass
        listen=False to defer the listeners until other layers are live —
        run_node starts the cluster first so no client ever connects to a
        half-clustered node."""
        await self.broker.start()
        if listen:
            await self.start_listeners()

    async def start_listeners(self) -> None:
        if self.handoff_path is not None:
            # reuse-port fallback: no TCP listener here — the shard
            # supervisor accepts and hands client sockets over Unix
            from ..shard.handoff import HandoffReceiver

            self._handoff = HandoffReceiver(self, self.handoff_path)
            await self._handoff.start()
            log.info("AMQP via fd handoff at %s", self.handoff_path)
            return
        kwargs: dict = {}
        if self.reuse_port:
            kwargs["reuse_port"] = True
        server = await asyncio.start_server(
            self._on_client, self.host, self.port, backlog=self.backlog,
            **kwargs)
        self._servers.append(server)
        log.info("AMQP listening on %s:%d%s", self.host, self.port,
                 " (reuse-port)" if self.reuse_port else "")
        if self.tls_port is not None and self.ssl_context is not None:
            tls_server = await asyncio.start_server(
                self._on_client, self.host, self.tls_port,
                ssl=self.ssl_context, backlog=self.backlog)
            self._servers.append(tls_server)
            log.info("AMQPS listening on %s:%d", self.host, self.tls_port)

    @property
    def bound_port(self) -> int:
        return self._servers[0].sockets[0].getsockname()[1]

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if (self.max_connections
                and len(self._connections) >= self.max_connections):
            # refuse at accept: a TCP close before the protocol header is
            # the one refusal every client library understands at this
            # stage (Connection.Close can't be sent pre-Start). Existing
            # connections are untouched.
            self.refused_connections += 1
            self.broker.metrics.connections_refused += 1
            log.warning(
                "refusing connection: %d live >= max-connections %d",
                len(self._connections), self.max_connections)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass
            return
        connection = AMQPConnection(
            self.broker, reader, writer,
            heartbeat_s=self.heartbeat_s, frame_max=self.frame_max,
            channel_max=self.channel_max,
            max_message_size=self.max_message_size,
            users=self.users,
            permissions=self.permissions,
        )
        self._connections.add(connection)
        try:
            await connection.serve()
        finally:
            self._connections.discard(connection)

    async def stop(self) -> None:
        if self._handoff is not None:
            await self._handoff.stop()
            self._handoff = None
        for server in self._servers:
            server.close()
        # kick live connections first: in py3.12 Server.wait_closed() waits
        # for all connection handlers, which only finish once clients drop
        for connection in list(self._connections):
            connection.closing = True
            try:
                connection.writer.close()
            except Exception:
                pass
        # explicitly await per-connection teardown: a handler parked at the
        # memory gate wakes on its next bounded wait and must finish before
        # the loop goes away (Server.wait_closed alone doesn't guarantee it)
        if self._connections:
            await asyncio.gather(
                *(c.closed for c in list(self._connections)),
                return_exceptions=True)
        for server in self._servers:
            await server.wait_closed()
        self._servers.clear()
        await self.broker.stop()

    async def serve_forever(self) -> None:
        await self.start()
        try:
            await asyncio.Event().wait()
        finally:
            await self.stop()

    @classmethod
    def from_config(cls, config) -> "BrokerServer":
        """Build a server (broker + listeners) from a Config tree."""
        from ..config import Config

        assert isinstance(config, Config)
        store: Optional[StoreService] = None
        store_path = config.get("chana.mq.store.path")
        if store_path:
            from ..store.sqlite import SqliteStore

            store = SqliteStore(
                store_path,
                synchronous=config.str("chana.mq.store.synchronous"))
            if config.bool("chana.mq.wal.enabled"):
                from ..wal import WalStore

                store = WalStore(
                    store,
                    flush_ms=float(config.get("chana.mq.wal.flush-ms")),
                    flush_bytes=config.size_bytes(
                        "chana.mq.wal.flush-bytes") or (1 << 20),
                    segment_bytes=config.size_bytes(
                        "chana.mq.wal.segment-bytes") or (64 << 20),
                    sync=config.str("chana.mq.wal.sync"),
                    checkpoint_ms=float(
                        config.get("chana.mq.wal.checkpoint-ms")),
                    memtable_bytes=config.size_bytes(
                        "chana.mq.wal.memtable-bytes") or (64 << 20),
                    tier_keep_segments=config.int(
                        "chana.mq.wal.tier-keep-segments"),
                    compact_streams=config.bool(
                        "chana.mq.wal.compact-streams"),
                )
        ssl_context = None
        tls_port = None
        if config.bool("chana.mq.amqp.amqps.enabled"):
            certfile = config.get("chana.mq.amqp.amqps.certfile")
            keyfile = config.get("chana.mq.amqp.amqps.keyfile")
            if not certfile:
                from ..config import ConfigError

                raise ConfigError(
                    "chana.mq.amqp.amqps.enabled is true but "
                    "chana.mq.amqp.amqps.certfile is not set")
            ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_context.load_cert_chain(certfile, keyfile)
            tls_port = config.int("chana.mq.amqp.amqps.port")
        users = cls._config_users(config)
        heartbeat = config.duration_s("chana.mq.amqp.connection.heartbeat")
        sweep = config.duration_s("chana.mq.message.sweep-interval")
        low = config.size_bytes("chana.mq.memory.low-watermark")
        ack_timeout = config.duration_s("chana.mq.consumer.timeout")
        broker = Broker(
            store=store,
            message_sweep_interval_s=sweep if sweep is not None else 0.0,
            queue_max_resident=config.int("chana.mq.queue.max-resident"),
            memory_high_watermark=config.size_bytes(
                "chana.mq.memory.high-watermark") or 0,
            memory_low_watermark=low,
            consumer_timeout_ms=(
                int(ack_timeout * 1000) if ack_timeout else 0),
            store_max_bytes=config.size_bytes("chana.mq.store.max-bytes")
            or 0,
            stream_segment_bytes=config.size_bytes(
                "chana.mq.stream.segment-bytes") or (1 << 20),
            stream_segment_age_s=config.duration_s(
                "chana.mq.stream.segment-age") or 0.0,
            stream_cache_segments=config.int(
                "chana.mq.stream.cache-segments"),
            stream_delivery_batch=config.int(
                "chana.mq.stream.delivery-batch") or 128,
            # flow-control ladder (chana.mq.flow.*): thresholds default
            # off the memory watermarks; None keeps the derived defaults
            flow_page_watermark=config.size_bytes(
                "chana.mq.flow.page-watermark"),
            flow_cluster_watermark=config.size_bytes(
                "chana.mq.flow.cluster-watermark"),
            flow_refuse_watermark=config.size_bytes(
                "chana.mq.flow.refuse-watermark"),
            flow_hard_limit=config.size_bytes("chana.mq.flow.hard-limit"),
            flow_publish_credit=config.size_bytes(
                "chana.mq.flow.publish-credit") or 0,
            flow_consumer_buffer=config.size_bytes(
                "chana.mq.flow.consumer-buffer") or 0,
            park_buffer=config.size_bytes("chana.mq.flow.park-buffer"),
            flow_page_resident=config.int("chana.mq.flow.page-resident")
            or 0,
            router_enabled=config.bool("chana.mq.router.enabled"),
            router_backend=config.str("chana.mq.router.backend") or "jax",
            router_min_batch=config.int("chana.mq.router.min-batch") or 16,
            router_max_wildcards=config.int(
                "chana.mq.router.max-wildcards") or 512,
            router_max_queues=config.int("chana.mq.router.max-queues")
            or 4096,
            router_verify=config.bool("chana.mq.router.verify"),
            semantics_enabled=config.bool("chana.mq.semantics.enabled"),
            delay_tick_ms=max(1, round((config.duration_s(
                "chana.mq.semantics.delay-tick") or 0.05) * 1000)),
            native_egress=config.bool("chana.mq.native.egress"),
            native_pool_buffers=config.int("chana.mq.native.pool-buffers")
            or 16,
            native_pool_buffer_kb=config.int("chana.mq.native.pool-buffer-kb")
            or 256,
        )
        if store is not None and hasattr(store, "metrics"):
            # the WAL engine's wal_* counters must land in the broker
            # registry (Prometheus / admin metrics), not a placeholder
            store.metrics = broker.metrics
        return cls(
            broker=broker,
            host=config.str("chana.mq.amqp.interface"),
            port=config.int("chana.mq.amqp.port"),
            tls_port=tls_port,
            ssl_context=ssl_context,
            # sub-second configs round up to 1s rather than silently disabling
            heartbeat_s=max(1, round(heartbeat)) if heartbeat else 0,
            frame_max=config.size_bytes("chana.mq.amqp.connection.frame-max"),
            channel_max=config.int("chana.mq.amqp.connection.channel-max"),
            max_connections=config.int("chana.mq.server.max-connections") or 0,
            backlog=config.int("chana.mq.server.backlog") or 128,
            max_message_size=config.size_bytes("chana.mq.message.max-size")
            or 0,
            users=users,
            permissions=cls._config_permissions(config, users),
        )

    @staticmethod
    def _config_users(config) -> Optional[dict]:
        """chana.mq.auth.users, validated fail-closed: a non-mapping value
        (malformed file/env) must error out, never silently disable auth."""
        users = config.get("chana.mq.auth.users")
        if users is None or users == {}:
            return None
        if not isinstance(users, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in users.items()
        ):
            from ..config import ConfigError

            raise ConfigError(
                "chana.mq.auth.users must map user names to passwords")
        return users

    @staticmethod
    def _config_permissions(config, users: Optional[dict]) -> Optional[dict]:
        """chana.mq.auth.permissions, validated fail-closed like users:
        allowlists without a user table (or naming unknown users) would be
        silently unenforced, so both are boot errors."""
        perms = config.get("chana.mq.auth.permissions")
        if perms is None or perms == {}:
            return None
        from ..config import ConfigError

        ok = isinstance(perms, dict) and all(
            isinstance(k, str) and isinstance(v, list)
            and all(isinstance(x, str) for x in v)
            for k, v in perms.items()
        )
        if not ok:
            raise ConfigError(
                "chana.mq.auth.permissions must map user names to vhost lists")
        if users is None:
            raise ConfigError(
                "chana.mq.auth.permissions requires chana.mq.auth.users")
        unknown = sorted(set(perms) - set(users))
        if unknown:
            raise ConfigError(
                f"chana.mq.auth.permissions names unknown users: {unknown}")
        return perms


async def run_node(config) -> None:
    """Boot a full node: broker + AMQP(+AMQPS) listeners + admin REST
    (the reference's AMQPServer.main composition, AMQPServer.scala:39-111).
    SIGTERM/SIGINT trigger a graceful drain: listeners close, live
    connections tear down (unacked requeue, store buffers flush), the
    group-commit queue drains, then the process exits 0 — the analogue of
    the reference's JVM shutdown hooks."""
    import signal as signal_module

    from ..rest.admin import AdminServer

    # multi-process sharding: with chana.mq.shard.count past 1 this
    # process becomes the supervisor (spawns one worker per shard and
    # returns when they're all down); workers carry CHANAMQ_SHARD_INDEX
    # and fall through to the normal boot below with shard wiring
    shard_index_env = os.environ.get("CHANAMQ_SHARD_INDEX")
    if shard_index_env is None:
        from ..shard import resolve_count

        if resolve_count(config) > 1:
            from ..shard.supervisor import run_supervisor

            await run_supervisor(config)
            return

    server = BrokerServer.from_config(config)
    shard_topo = None
    shard_index = 0
    if shard_index_env is not None:
        from ..shard import ShardTopology

        shard_index = int(shard_index_env)
        shard_topo = ShardTopology.from_env(config, shard_index)
        server.broker.shard_info = {
            "index": shard_index,
            "count": shard_topo.count,
            "name": shard_topo.name(shard_index),
        }
        server.broker.metrics.shard_restarts = int(
            os.environ.get("CHANAMQ_SHARD_RESTARTS", "0") or 0)
        if config.bool("chana.mq.shard.reuse-port"):
            server.reuse_port = True
        else:
            server.handoff_path = shard_topo.handoff_path(shard_index)
    if config.bool("chana.mq.log.json"):
        # swap formatters before any traffic so every line is one JSON
        # object stamped with node id + active trace id
        from ..utils import logjson

        logjson.install(server.broker)
    admin = None
    cluster = None
    forecaster = None
    telemetry = None
    control = None
    federation = None
    otel = None
    started = False
    stop_event = asyncio.Event()
    loop = asyncio.get_running_loop()

    def on_signal() -> None:
        if stop_event.is_set():
            # second signal while draining: the operator wants OUT now
            os._exit(130)
        stop_event.set()

    for sig in (signal_module.SIGTERM, signal_module.SIGINT):
        try:
            loop.add_signal_handler(sig, on_signal)
        except (NotImplementedError, RuntimeError, ValueError):  # pragma: no cover
            pass  # non-unix platform or non-main thread: KeyboardInterrupt
    try:
        # boot order matters: broker state, then the cluster layer, then
        # the AMQP listeners — a client accepted before the cluster is live
        # would see a node that mis-routes clustered queues
        await server.start(listen=False)
        started = True
        # chaos wiring before any traffic: wraps the store, marks the
        # broker chaos-capable, optionally installs a boot plan. With
        # chana.mq.chaos.enabled unset this is a single bool check and the
        # seams stay no-op module-attribute loads.
        if config.bool("chana.mq.chaos.enabled"):
            from .. import chaos as chaos_mod

            chaos_mod.enable_from_config(config, server.broker)
        # tracing next (same ACTIVE-gate idiom as chaos): installed before
        # the cluster starts so ClusterNode.start can rename the runtime's
        # node tag from "local" to host:port
        if config.bool("chana.mq.trace.enabled"):
            from .. import trace as trace_mod

            trace_mod.enable_from_config(config, server.broker)
        # OTLP span exporter: hooks trace completion, so it must come
        # after tracing is installed. Without an endpoint it still arms
        # the bounded queue behind GET /admin/otel/spans (pull mode).
        if config.bool("chana.mq.otel.enabled"):
            from ..otel.export import OtelExporter

            otel = OtelExporter(
                server.broker,
                endpoint=config.str("chana.mq.otel.endpoint"),
                flush_ms=config.int("chana.mq.otel.flush-ms"),
                max_batch=config.int("chana.mq.otel.max-batch"),
                queue_size=config.int("chana.mq.otel.queue-size"))
            await otel.start()
            server.broker.otel = otel
        # cost ledger + sampling profiler (third ACTIVE-gate subsystem):
        # armed before traffic so stage counters cover the whole run, and
        # before the cluster so cluster-push batches are attributed
        if config.bool("chana.mq.profile.enabled"):
            from .. import profile as profile_mod

            profile_mod.enable_from_config(config, server.broker)
        # event bus + firehose (fourth ACTIVE-gate subsystem): installed
        # before the cluster so lifecycle transitions and chaos fires are
        # observable from the first moment they can happen
        if (config.bool("chana.mq.events.enabled")
                or config.bool("chana.mq.firehose.enabled")):
            from .. import events as events_mod

            bus, _ = events_mod.enable_from_config(config, server.broker)
            if bus is not None:
                restarts = int(
                    os.environ.get("CHANAMQ_SHARD_RESTARTS", "0") or 0)
                if restarts > 0:
                    # this worker is a supervisor respawn: the one boot
                    # event a consumer can alert on
                    bus.emit("shard.restarted", {
                        "shard": shard_index, "restarts": restarts})
        # tenant registry (fifth ACTIVE-gate subsystem): installed before
        # the listeners open so the first handshake already authenticates
        # against tenant user tables and lands under quota enforcement.
        # Called unconditionally: the enable path itself fail-closes when
        # tenants are declared while chana.mq.tenant.enabled is false.
        from .. import tenancy as tenancy_mod

        tenancy_mod.enable_from_config(config, server.broker)
        if config.bool("chana.mq.cluster.enabled"):
            from ..cluster.node import ClusterNode

            cluster = ClusterNode(
                server.broker,
                host=config.str("chana.mq.cluster.host"),
                port=config.int("chana.mq.cluster.port"),
                seeds=config.list("chana.mq.cluster.seeds"),
                virtual_nodes=config.int("chana.mq.cluster.virtual-nodes"),
                heartbeat_interval_s=config.duration_s(
                    "chana.mq.cluster.heartbeat-interval") or 1.0,
                failure_timeout_s=config.duration_s(
                    "chana.mq.cluster.failure-timeout") or 5.0,
                replicate_factor=config.int("chana.mq.replicate.factor"),
                replicate_sync=config.bool("chana.mq.replicate.sync"),
                replicate_batch_max=config.int(
                    "chana.mq.replicate.batch-max"),
                replicate_ack_timeout_ms=config.int(
                    "chana.mq.replicate.ack-timeout-ms"),
                streams=config.int("chana.mq.cluster.streams"),
                stream_inflight=config.int("chana.mq.cluster.stream-inflight"),
                flush_window_us=config.int("chana.mq.cluster.flush-window-us"),
                flush_max_bytes=config.size_bytes(
                    "chana.mq.cluster.flush-max-bytes") or (1 << 20),
                flush_max_count=config.int("chana.mq.cluster.flush-max-count"),
                consume_credit=config.int("chana.mq.cluster.consume-credit"),
                call_timeout_s=config.duration_s(
                    "chana.mq.cluster.call-timeout") or 10.0,
                drain_retry_limit=config.int(
                    "chana.mq.lifecycle.drain-retry-limit"),
                drain_backoff_ms=int((config.duration_s(
                    "chana.mq.lifecycle.drain-backoff") or 0.1) * 1000),
                drain_backoff_cap_ms=int((config.duration_s(
                    "chana.mq.lifecycle.drain-backoff-cap") or 2.0) * 1000),
                drain_budget_s=config.duration_s(
                    "chana.mq.lifecycle.drain-budget") or 30.0,
                uds_path=(shard_topo.uds_path(shard_index)
                          if shard_topo is not None else None),
                uds_map=(shard_topo.uds_map_for(shard_index)
                         if shard_topo is not None else None),
            )
            await cluster.start()
        if stop_event.is_set():
            # signalled during boot (e.g. while the cluster joined its
            # seeds): don't open listeners just to tear clients down
            return
        await server.start_listeners()
        if config.bool("chana.mq.federation.enabled"):
            # cross-cluster federation (federation/): the fed.* listener
            # (mirror side) plus one shipping link per configured remote.
            # Boots after the listeners so an inbound fed.resume can
            # declare its mirror streams on a fully-started broker; with
            # no links configured the only steady-state cost is the idle
            # listener and `broker.federation is None` checks staying hot
            from ..federation import enable_from_config as federation_enable

            federation = await federation_enable(config, server.broker)
        if config.bool("chana.mq.telemetry.enabled"):
            # per-entity telemetry + health + alerts (telemetry/): started
            # after the cluster layer so the first tick already sees the
            # real node name and replication state
            from ..telemetry import TelemetryService, default_rules

            telemetry = TelemetryService(
                server.broker,
                interval_s=config.duration_s("chana.mq.telemetry.interval")
                or 1.0,
                ring_ticks=config.int("chana.mq.telemetry.ring-ticks"),
                max_queues=config.int("chana.mq.telemetry.max-queues"),
                max_connections=config.int(
                    "chana.mq.telemetry.max-connections"),
                top_k=config.int("chana.mq.telemetry.top-k"),
                rules=default_rules(
                    backlog_growth=float(
                        config.int("chana.mq.alerts.backlog-growth")),
                    backlog_window=config.int("chana.mq.alerts.backlog-window"),
                    stall_ticks=config.int("chana.mq.alerts.stall-ticks"),
                    repl_lag=float(config.int("chana.mq.alerts.repl-lag")),
                    loop_lag_ms=float(
                        config.int("chana.mq.alerts.loop-lag-ms")),
                    memory_stage=float(
                        config.get("chana.mq.alerts.memory-stage") or 3.5),
                ),
                alerts_enabled=config.bool("chana.mq.alerts.enabled"),
                loop_lag_ready_ms=float(
                    config.int("chana.mq.telemetry.ready-loop-lag-ms")),
                repl_lag_ready=config.int("chana.mq.telemetry.ready-repl-lag"),
                store_error_window=config.int(
                    "chana.mq.telemetry.store-error-window"),
                federation_lag_records=config.int(
                    "chana.mq.slo.federation-lag-records"),
            )
            if config.bool("chana.mq.slo.enabled"):
                # burn-rate SLOs ride the telemetry tick (slo/): specs
                # from chana.mq.slo.* or POST /admin/slo/configure
                from ..slo import attach_tenant_latency, engine_from_config

                engine = engine_from_config(
                    config,
                    config.duration_s("chana.mq.telemetry.interval") or 1.0)
                telemetry.set_slo(engine)
                # tenant-scoped delivery-latency SLOs need their per-tenant
                # histogram allocated before the first delivery
                attach_tenant_latency(engine, server.broker.tenancy)
            server.broker.telemetry = telemetry
            await telemetry.start()
        if config.bool("chana.mq.forecast.enabled"):
            # live-telemetry forecaster (SURVEY.md §7.1's JAX role): samples
            # metrics on the loop, trains/predicts on a worker thread,
            # serves GET /admin/forecast + chanamq_forecast_* gauges.
            # Fail fast on a core-only install: without the probe, a
            # missing jax would only surface as a traceback per train
            # round (worker thread), never as a boot error.
            try:
                import jax  # noqa: F401
                import numpy  # noqa: F401
            except ImportError as exc:
                from ..config import ConfigError

                raise ConfigError(
                    "chana.mq.forecast.enabled requires jax + numpy "
                    "(pip install 'chanamq-tpu[forecast]'); "
                    f"import failed: {exc}") from None
            from ..models.service import ForecastService

            forecaster = ForecastService(
                server.broker,
                interval_s=config.duration_s("chana.mq.forecast.interval")
                or 1.0,
                train_interval_s=config.duration_s(
                    "chana.mq.forecast.train-interval") or 30.0,
                seq_len=config.int("chana.mq.forecast.window"),
                history=config.int("chana.mq.forecast.history"),
                queue_top_k=(
                    config.int("chana.mq.forecast.queue-top-k")
                    if telemetry is not None else 0),
            )
            await forecaster.start()
        if config.bool("chana.mq.control.enabled"):
            # predictive control plane (control/): forecast/trend-driven
            # admission pre-arm, queue rebalancing and prefetch
            # autotuning. Boots after telemetry + forecaster (its inputs)
            # and works degraded without either — trend-only admission
            # against the flow ladder. Dry-run by default.
            from ..control import ControlService

            control = ControlService(
                server.broker,
                interval_s=config.duration_s("chana.mq.control.interval")
                or 1.0,
                dry_run=config.bool("chana.mq.control.dry-run"),
                admission=config.bool("chana.mq.control.admission.enabled"),
                rebalance=config.bool("chana.mq.control.rebalance.enabled"),
                prefetch=config.bool("chana.mq.control.prefetch.enabled"),
                horizon_s=config.duration_s("chana.mq.control.horizon")
                or 5.0,
                arm_ticks=config.int("chana.mq.control.arm-ticks"),
                cooldown_s=config.duration_s("chana.mq.control.cooldown")
                or 10.0,
                rebalance_cooldown_s=config.duration_s(
                    "chana.mq.control.rebalance.cooldown") or 30.0,
                credit_factor=float(config.get(
                    "chana.mq.control.admission.credit-factor") or 0.5),
                credit_min=config.size_bytes(
                    "chana.mq.control.admission.credit-min") or 4096,
                rebalance_ratio=float(config.get(
                    "chana.mq.control.rebalance.ratio") or 1.5),
                rebalance_min_rate=float(config.size_bytes(
                    "chana.mq.control.rebalance.min-rate") or 1024),
                prefetch_min=config.int("chana.mq.control.prefetch.min"),
                prefetch_max=config.int("chana.mq.control.prefetch.max"),
                log_size=config.int("chana.mq.control.log-size"),
                forecast_max_age_s=config.duration_s(
                    "chana.mq.control.forecast-max-age") or 10.0,
                forecast_error_gate=float(config.get(
                    "chana.mq.control.forecast-error-gate") or 0.5),
            )
            await control.start()
        if config.bool("chana.mq.admin.enabled"):
            admin = AdminServer(
                server.broker,
                host=config.str("chana.mq.admin.interface"),
                port=config.int("chana.mq.admin.port"),
            )
            await admin.start()
        await stop_event.wait()
        # readiness flips 503 the moment the drain starts — the admin
        # server is still up below, so a load balancer polling
        # /admin/health stops routing to this node before connections
        # actually tear down
        server.broker.draining = True
        log.info("shutdown signal received; draining")
    finally:
        server.broker.draining = True
        if admin:
            await admin.stop()
        if control:
            await control.stop()
        if telemetry:
            await telemetry.stop()
        if forecaster:
            await forecaster.stop()
        if federation:
            await federation.stop()
        if otel:
            await otel.stop()
        if cluster:
            await cluster.stop()
        if started:
            await server.stop()


def main() -> None:
    import argparse

    from ..config import Config

    parser = argparse.ArgumentParser(description="chanamq-tpu AMQP broker")
    parser.add_argument("--config", default=None, help="JSON config file")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--admin-port", type=int, default=None)
    parser.add_argument("--no-admin", action="store_true")
    parser.add_argument("--store", default=None,
                        help="sqlite db path (default: in-memory transient)")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()
    logging.basicConfig(
        level=args.log_level,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")

    overrides: dict = {}
    if args.host is not None:
        overrides["chana.mq.amqp.interface"] = args.host
    if args.port is not None:
        overrides["chana.mq.amqp.port"] = args.port
    if args.admin_port is not None:
        overrides["chana.mq.admin.port"] = args.admin_port
    if args.no_admin:
        overrides["chana.mq.admin.enabled"] = False
    if args.store is not None:
        overrides["chana.mq.store.path"] = args.store
    config = Config(overrides, file=args.config)
    try:
        asyncio.run(run_node(config))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
