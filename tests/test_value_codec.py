"""Field-table value codec tests, including golden byte vectors."""

import decimal
from io import BytesIO

import pytest

from chanamq_tpu.amqp import value_codec as vc


def roundtrip_table(table):
    return vc.decode_table(vc.encode_table(table))


def test_empty_table_golden():
    assert vc.encode_table({}) == b"\x00\x00\x00\x00"
    assert vc.encode_table(None) == b"\x00\x00\x00\x00"


def test_longstr_value_golden():
    # key "a" -> longstr "hi": len=1,'a','S',len=2,'h','i'
    assert vc.encode_table({"a": "hi"}) == (
        b"\x00\x00\x00\x09" b"\x01a" b"S" b"\x00\x00\x00\x02hi"
    )


def test_int_value_golden():
    assert vc.encode_table({"n": 5}) == (b"\x00\x00\x00\x07" b"\x01n" b"I" b"\x00\x00\x00\x05")


def test_bool_and_void_golden():
    assert vc.encode_table({"t": True}) == b"\x00\x00\x00\x04\x01tt\x01"
    assert vc.encode_table({"v": None}) == b"\x00\x00\x00\x03\x01vV"


def test_roundtrip_all_types():
    table = {
        "str": "hello",
        "int": 42,
        "neg": -7,
        "big": 1 << 40,
        "bool_t": True,
        "bool_f": False,
        "float": 3.5,
        "bytes": b"\x00\x01\x02",
        "void": None,
        "dec": decimal.Decimal("3.14"),
        "ts": vc.Timestamp(1700000000),
        "nested": {"inner": "x", "deep": {"n": 1}},
        "arr": ["a", 1, True, None, {"k": "v"}],
    }
    out = roundtrip_table(table)
    assert out["str"] == "hello"
    assert out["int"] == 42
    assert out["neg"] == -7
    assert out["big"] == 1 << 40
    assert out["bool_t"] is True
    assert out["bool_f"] is False
    assert out["float"] == 3.5
    assert out["bytes"] == b"\x00\x01\x02"
    assert out["void"] is None
    assert out["dec"] == decimal.Decimal("3.14")
    assert out["ts"] == 1700000000
    assert isinstance(out["ts"], vc.Timestamp)
    assert out["nested"] == {"inner": "x", "deep": {"n": 1}}
    assert out["arr"] == ["a", 1, True, None, {"k": "v"}]


def test_read_signed_small_types():
    # 'b' int8, 's' int16, 'f' float32, 'l' int64 written directly
    stream = BytesIO()
    vc.write_shortstr(stream, "k")
    payload = stream.getvalue()
    body = payload + b"b\xff"  # -1 as int8
    data = len(body).to_bytes(4, "big") + body
    assert vc.decode_table(data) == {"k": -1}


def test_int32_boundary_uses_longlong():
    enc = vc.encode_table({"x": (1 << 31)})
    assert b"l" in enc
    assert roundtrip_table({"x": (1 << 31)})["x"] == 1 << 31


def test_shortstr_too_long_raises():
    with pytest.raises(vc.CodecError):
        vc.write_shortstr(BytesIO(), "x" * 256)


def test_truncated_table_raises():
    data = vc.encode_table({"a": "hello"})
    with pytest.raises(vc.CodecError):
        vc.decode_table(data[:-2] )


def test_unknown_tag_raises():
    body = b"\x01kZ"
    data = len(body).to_bytes(4, "big") + body
    with pytest.raises(vc.CodecError):
        vc.decode_table(data)


def test_roundtrip_randomized_nested():
    """Seeded fuzz: random deeply-nested tables/arrays of every supported
    value shape must round-trip exactly (the cluster RPC layer ships
    arbitrary payloads through this codec — queue.push_many batches carry
    lists of tables with bytes values)."""
    import random
    from io import BytesIO

    rng = random.Random(0xF1E1D)

    def rand_value(depth):
        kinds = ["int", "str", "bytes", "bool", "none", "float"]
        if depth < 3:
            kinds += ["table", "array"]
        kind = rng.choice(kinds)
        if kind == "int":
            return rng.randrange(-2**40, 2**40)
        if kind == "str":
            return "".join(rng.choice("abčé.💬x") for _ in range(rng.randrange(6)))
        if kind == "bytes":
            return bytes(rng.randrange(256) for _ in range(rng.randrange(8)))
        if kind == "bool":
            return rng.random() < 0.5
        if kind == "none":
            return None
        if kind == "float":
            return rng.randrange(-1000, 1000) / 8  # exact in binary
        if kind == "table":
            return {f"k{i}": rand_value(depth + 1)
                    for i in range(rng.randrange(4))}
        return [rand_value(depth + 1) for i in range(rng.randrange(4))]

    for trial in range(200):
        table = {f"key{i}": rand_value(0) for i in range(rng.randrange(6))}
        out = BytesIO()
        vc.write_table(out, table)
        back = vc.read_table(BytesIO(out.getvalue()))
        # bytes values come back as bytes; str as str — exact equality
        assert back == table, (trial, table, back)
