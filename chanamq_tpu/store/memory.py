"""In-memory StoreService — transient mode and test double.

Functionally complete (everything select_queue / recovery needs works), just
not durable across process restarts. Useful for unit tests and as the broker
default when no store is configured.

Write methods take effect at CALL time and return an already-completed
awaitable, mirroring SqliteStore._submit's enqueue-at-call-time property:
program order == store order regardless of when (or whether) the caller
awaits. This matters for correctness, not just symmetry — the broker pages
message bodies out via fire-and-forget store_bg(insert_message(...)) and a
pipelined basic.get may read the blob back with zero event-loop yields in
between; a lazily-run write task would make that read miss a just-paged
message.
"""

from __future__ import annotations

import copy
from typing import Optional

from .api import (
    StoredExchange, StoredMessage, StoredQueue, StoreService,
    is_replica_vhost,
)


class _Done:
    """Already-completed awaitable returned by eager write methods."""

    __slots__ = ()

    def __await__(self):
        return iter(())


_DONE = _Done()


class MemoryStore(StoreService):
    def __init__(self) -> None:
        self.messages: dict[int, StoredMessage] = {}
        self.queues: dict[tuple[str, str], StoredQueue] = {}
        self.exchanges: dict[tuple[str, str], StoredExchange] = {}
        self.vhosts: dict[str, bool] = {}
        self.archived: dict[tuple[str, str], StoredQueue] = {}
        # stream log: (vhost, queue) -> {base_offset: (meta..., blob)}
        # where meta is (base, last, first_ts_ms, last_ts_ms, size_bytes)
        self.stream_segments: dict[
            tuple[str, str], dict[int, tuple[int, int, int, int, int, bytes]]
        ] = {}
        # (vhost, queue) -> {cursor name: committed offset}
        self.stream_cursors: dict[tuple[str, str], dict[str, int]] = {}
        self._next_worker_id = 0
        self._data_bytes = 0  # running sum of stored body bytes

    async def open(self) -> None:
        pass

    async def close(self) -> None:
        pass

    async def approx_data_bytes(self) -> int:
        # message blobs dominate; metadata rows are noise next to bodies.
        # Running counter (maintained by insert/delete): the sweep samples
        # this each tick, so an O(n) sum would stall the loop at scale.
        return self._data_bytes

    # -- messages ---------------------------------------------------------

    def insert_message(self, msg: StoredMessage):
        old = self.messages.get(msg.id)
        if old is not None:
            self._data_bytes -= len(old.body)
        self._data_bytes += len(msg.body)
        self.messages[msg.id] = copy.copy(msg)
        return _DONE

    async def select_message(self, msg_id: int) -> Optional[StoredMessage]:
        msg = self.messages.get(msg_id)
        return copy.copy(msg) if msg else None

    def delete_message(self, msg_id: int):
        old = self.messages.pop(msg_id, None)
        if old is not None:
            self._data_bytes -= len(old.body)
        return _DONE

    def delete_messages(self, msg_ids):
        for msg_id in msg_ids:
            old = self.messages.pop(msg_id, None)
            if old is not None:
                self._data_bytes -= len(old.body)
        return _DONE

    def update_message_refer_count(self, msg_id: int, count: int):
        msg = self.messages.get(msg_id)
        if msg:
            msg.refer_count = count
        return _DONE

    # -- queue meta -------------------------------------------------------

    def insert_queue_meta(self, q: StoredQueue):
        existing = self.queues.get((q.vhost, q.name))
        stored = copy.deepcopy(q)
        if existing:
            stored.msgs = existing.msgs
            stored.unacks = existing.unacks
        self.queues[(q.vhost, q.name)] = stored
        return _DONE

    async def select_queue(self, vhost: str, name: str) -> Optional[StoredQueue]:
        q = self.queues.get((vhost, name))
        return copy.deepcopy(q) if q else None

    async def all_queues(self, vhost: Optional[str] = None) -> list[StoredQueue]:
        return [
            copy.deepcopy(q)
            for (vh, _), q in self.queues.items()
            if not is_replica_vhost(vh) and (vhost is None or vh == vhost)
        ]

    # -- queue log --------------------------------------------------------

    def insert_queue_msg(self, vhost, queue, offset, msg_id, body_size, expire_at_ms):
        q = self.queues.get((vhost, queue))
        if q:
            q.msgs.append((offset, msg_id, body_size, expire_at_ms))
        return _DONE

    def delete_queue_msg(self, vhost, queue, offset):
        q = self.queues.get((vhost, queue))
        if q:
            q.msgs = [m for m in q.msgs if m[0] != offset]
        return _DONE

    # -- watermark + unacks ------------------------------------------------

    def update_queue_last_consumed(self, vhost, queue, last_consumed):
        q = self.queues.get((vhost, queue))
        if q:
            q.last_consumed = last_consumed
            q.msgs = [m for m in q.msgs if m[0] > last_consumed]
        return _DONE

    def insert_queue_unacks(self, vhost, queue, unacks):
        q = self.queues.get((vhost, queue))
        if q:
            for msg_id, offset, body_size, expire_at_ms in unacks:
                q.unacks[msg_id] = (offset, body_size, expire_at_ms)
        return _DONE

    def delete_queue_msgs_offsets(self, vhost, queue, offsets):
        q = self.queues.get((vhost, queue))
        if q:
            drop = set(offsets)
            q.msgs = [m for m in q.msgs if m[0] not in drop]
        return _DONE

    def delete_queue_unacks(self, vhost, queue, msg_ids):
        q = self.queues.get((vhost, queue))
        if q:
            for msg_id in msg_ids:
                q.unacks.pop(msg_id, None)
        return _DONE

    def replace_queue_msgs(self, vhost, queue, msgs):
        q = self.queues.get((vhost, queue))
        if q:
            q.msgs = [tuple(m) for m in msgs]
        return _DONE

    def replace_queue_unacks(self, vhost, queue, unacks):
        q = self.queues.get((vhost, queue))
        if q:
            q.unacks = {
                msg_id: (offset, body_size, expire_at_ms)
                for msg_id, offset, body_size, expire_at_ms in unacks
            }
        return _DONE

    # -- fire-and-forget fast paths: writes already apply at call time, so
    #    the nowait variants just drop the _DONE handle -------------------

    def insert_message_nowait(self, msg: StoredMessage) -> None:
        self.insert_message(msg)

    def insert_queue_msg_nowait(
            self, vhost, queue, offset, msg_id, body_size, expire_at_ms) -> None:
        self.insert_queue_msg(vhost, queue, offset, msg_id, body_size, expire_at_ms)

    def insert_queue_unacks_nowait(self, vhost, queue, unacks) -> None:
        self.insert_queue_unacks(vhost, queue, unacks)

    # -- delete/archive ----------------------------------------------------

    def archive_queue(self, vhost, queue):
        q = self.queues.get((vhost, queue))
        if q:
            self.archived[(vhost, queue)] = copy.deepcopy(q)
        return _DONE

    def delete_queue(self, vhost, queue):
        self.queues.pop((vhost, queue), None)
        return _DONE

    def purge_queue_msgs(self, vhost, queue):
        q = self.queues.get((vhost, queue))
        if q:
            q.msgs = []
        return _DONE

    # -- stream segments + cursors -----------------------------------------

    def insert_stream_segment(self, vhost, queue, base_offset, last_offset,
                              first_ts_ms, last_ts_ms, size_bytes, blob):
        segs = self.stream_segments.setdefault((vhost, queue), {})
        old = segs.get(base_offset)
        if old is not None:
            self._data_bytes -= len(old[5])
        segs[base_offset] = (base_offset, last_offset, first_ts_ms,
                             last_ts_ms, size_bytes, blob)
        self._data_bytes += len(blob)
        return _DONE

    async def select_stream_segment(self, vhost, queue, base_offset):
        seg = self.stream_segments.get((vhost, queue), {}).get(base_offset)
        return seg[5] if seg else None

    async def stream_segment_metas(self, vhost, queue):
        segs = self.stream_segments.get((vhost, queue), {})
        return [seg[:5] for _, seg in sorted(segs.items())]

    def delete_stream_segments(self, vhost, queue, base_offsets):
        segs = self.stream_segments.get((vhost, queue))
        if segs:
            for base in base_offsets:
                old = segs.pop(base, None)
                if old is not None:
                    self._data_bytes -= len(old[5])
        return _DONE

    def update_stream_cursor(self, vhost, queue, name, committed_offset):
        self.stream_cursors.setdefault(
            (vhost, queue), {})[name] = committed_offset
        return _DONE

    async def select_stream_cursors(self, vhost, queue):
        return dict(self.stream_cursors.get((vhost, queue), {}))

    def delete_stream_data(self, vhost, queue):
        segs = self.stream_segments.pop((vhost, queue), None)
        if segs:
            for seg in segs.values():
                self._data_bytes -= len(seg[5])
        self.stream_cursors.pop((vhost, queue), None)
        return _DONE

    # -- exchanges + binds -------------------------------------------------

    def insert_exchange(self, ex: StoredExchange):
        existing = self.exchanges.get((ex.vhost, ex.name))
        stored = copy.deepcopy(ex)
        if existing:
            stored.binds = existing.binds
            stored.ex_binds = existing.ex_binds
        self.exchanges[(ex.vhost, ex.name)] = stored
        return _DONE

    async def select_exchange(self, vhost, name) -> Optional[StoredExchange]:
        ex = self.exchanges.get((vhost, name))
        return copy.deepcopy(ex) if ex else None

    async def all_exchanges(self, vhost: Optional[str] = None) -> list[StoredExchange]:
        return [
            copy.deepcopy(ex)
            for (vh, _), ex in self.exchanges.items()
            if vhost is None or vh == vhost
        ]

    def delete_exchange(self, vhost, name):
        self.exchanges.pop((vhost, name), None)
        return _DONE

    def insert_bind(self, vhost, exchange, queue, routing_key, arguments):
        ex = self.exchanges.get((vhost, exchange))
        if ex is not None:
            entry = (routing_key, queue, arguments)
            if entry not in ex.binds:
                ex.binds.append(entry)
        return _DONE

    def delete_bind(self, vhost, exchange, queue, routing_key):
        ex = self.exchanges.get((vhost, exchange))
        if ex is not None:
            ex.binds = [
                b for b in ex.binds if not (b[0] == routing_key and b[1] == queue)
            ]
        return _DONE

    def delete_queue_binds(self, vhost, queue):
        for (vh, _), ex in self.exchanges.items():
            if vh == vhost:
                ex.binds = [b for b in ex.binds if b[1] != queue]
        return _DONE

    def insert_exchange_bind(self, vhost, source, destination, routing_key, arguments):
        ex = self.exchanges.get((vhost, source))
        if ex is not None:
            entry = (routing_key, destination, arguments)
            if entry not in ex.ex_binds:
                ex.ex_binds.append(entry)
        return _DONE

    def delete_exchange_bind(self, vhost, source, destination, routing_key):
        ex = self.exchanges.get((vhost, source))
        if ex is not None:
            ex.ex_binds = [
                b for b in ex.ex_binds
                if not (b[0] == routing_key and b[1] == destination)
            ]
        return _DONE

    def delete_exchange_binds_dest(self, vhost, destination):
        for (vh, _), ex in self.exchanges.items():
            if vh == vhost:
                ex.ex_binds = [b for b in ex.ex_binds if b[1] != destination]
        return _DONE

    async def allocate_worker_id(self) -> int:
        self._next_worker_id += 1
        return self._next_worker_id

    # -- vhosts ------------------------------------------------------------

    def insert_vhost(self, name: str, active: bool = True):
        self.vhosts[name] = active
        return _DONE

    async def all_vhosts(self) -> list[tuple[str, bool]]:
        return list(self.vhosts.items())

    def delete_vhost(self, name: str):
        self.vhosts.pop(name, None)
        return _DONE
