"""AMQCommand (method [+ header + body]) rendering and reassembly.

Capability parity with the reference's AMQCommand.render
(chana-mq-base .../model/AMQCommand.scala:29-65) and CommandAssembler state
machine (.../engine/CommandAssembler.scala:44-131): a command is one METHOD
frame, optionally followed by one HEADER frame and zero or more BODY frames;
rendering fragments the body into <= (frame_max - overhead) chunks; assembly
is an incremental state machine fed complete frames.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

from .constants import FRAME_OVERHEAD, ErrorCode, FrameType
from .frame import Frame, FrameError
from .methods import Method, MethodDecodeError, decode_method
from .properties import BasicProperties


@dataclass(slots=True)
class AMQCommand:
    """A fully-assembled AMQP command on one channel."""

    channel: int
    method: Method
    properties: Optional[BasicProperties] = None
    body: bytes = b""
    # Raw HEADER-frame payload as received off the wire (class-id + weight +
    # body-size + property flags/values). Kept so re-rendering the same
    # content (delivery of a just-published message, mandatory returns,
    # persistence) skips the property re-encode — the bytes are identical.
    header_raw: Optional[bytes] = None

    def render_frames(self, frame_max: int) -> list[Frame]:
        if frame_max and frame_max <= FRAME_OVERHEAD:
            raise ValueError(f"frame_max {frame_max} leaves no room for payload")
        frames = [Frame.method(self.channel, self.method.encode())]
        if self.method.HAS_CONTENT:
            header_payload = self.header_raw
            if header_payload is None:
                props = self.properties or BasicProperties()
                header_payload = props.encode_header(len(self.body))
            frames.append(Frame.header(self.channel, header_payload))
            body = self.body
            max_payload = (frame_max - FRAME_OVERHEAD) if frame_max else max(len(body), 1)
            for off in range(0, len(body), max_payload):
                frames.append(Frame.body(self.channel, body[off : off + max_payload]))
        return frames

    def render(self, frame_max: int) -> bytes:
        return b"".join(f.to_bytes() for f in self.render_frames(frame_max))


class CommandAssembler:
    """Reassembles frames into commands for one connection (all channels).

    Feed it complete frames; it yields `AMQCommand` or `FrameError`.
    Heartbeat frames are not handled here — filter them before feeding.
    """

    __slots__ = ("_partial",)

    def __init__(self) -> None:
        # channel id -> in-flight (command, expected_body_size, received_size)
        self._partial: dict[int, _Partial] = {}

    def feed_one(self, frame: Frame) -> "AMQCommand | FrameError | None":
        """Feed one frame; returns the completed command, a protocol error,
        or None while content is still pending. The hot-loop shape (plain
        call, no generator per frame): every frame produces at most one
        result by construction."""
        channel = frame.channel
        partial = self._partial.get(channel)
        if frame.type == FrameType.METHOD:
            if partial is not None:
                return FrameError(
                    ErrorCode.UNEXPECTED_FRAME,
                    f"method frame while content pending on channel {channel}",
                )
            try:
                method = decode_method(frame.payload)
            except MethodDecodeError as exc:
                return FrameError(ErrorCode.COMMAND_INVALID, str(exc))
            except Exception as exc:
                return FrameError(ErrorCode.SYNTAX_ERROR, f"bad method arguments: {exc}")
            if method.HAS_CONTENT:
                self._partial[channel] = _Partial(AMQCommand(channel, method))
                return None
            return AMQCommand(channel, method)
        elif frame.type == FrameType.BODY:
            if partial is None or partial.expected_size is None:
                return FrameError(
                    ErrorCode.UNEXPECTED_FRAME,
                    f"unexpected body frame on channel {channel}",
                )
            partial.chunks.append(frame.payload)
            partial.received += len(frame.payload)
            if partial.received > partial.expected_size:
                del self._partial[channel]
                return FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"body overflows declared size on channel {channel}",
                )
            if partial.received == partial.expected_size:
                partial.command.body = b"".join(partial.chunks)
                del self._partial[channel]
                return partial.command
            return None
        elif frame.type == FrameType.HEADER:
            if partial is None or partial.expected_size is not None:
                return FrameError(
                    ErrorCode.UNEXPECTED_FRAME,
                    f"unexpected header frame on channel {channel}",
                )
            try:
                _class_id, body_size, props = BasicProperties.decode_header(frame.payload)
            except Exception as exc:
                return FrameError(ErrorCode.SYNTAX_ERROR, f"bad content header: {exc}")
            partial.command.properties = props
            partial.command.header_raw = frame.payload
            partial.expected_size = body_size
            if body_size == 0:
                del self._partial[channel]
                return partial.command
            return None
        else:
            return FrameError(ErrorCode.UNEXPECTED_FRAME, f"frame type {frame.type}")

    def feed(self, frame: Frame) -> Iterator["AMQCommand | FrameError"]:
        result = self.feed_one(frame)
        if result is not None:
            yield result

    def abort_channel(self, channel: int) -> None:
        """Drop any in-flight content on a channel (e.g. on channel close)."""
        self._partial.pop(channel, None)


@dataclass(slots=True)
class _Partial:
    command: AMQCommand
    expected_size: Optional[int] = None
    received: int = 0
    chunks: list[bytes] = field(default_factory=list)
