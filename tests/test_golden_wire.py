"""Golden-wire conformance: byte-exact AMQP 0-9-1 fixtures replayed against
a live server socket.

INDEPENDENCE GUARANTEE: nothing in this file imports or calls
``chanamq_tpu.amqp``. Every client->server byte below is hand-assembled by
the tiny spec-rule builders in this file (struct.pack over the framing rules
of the AMQP 0-9-1 specification: frame = type octet, channel short, size
long, payload, 0xCE end; method payload = class short + method short + args;
shortstr = len octet + bytes; longstr/table = len long + bytes; bit fields
pack LSB-first into octets; content header = class short, weight short,
body-size longlong, 15-bit property flags, property list). Server responses
are asserted byte-for-byte against expectations assembled the same way —
only genuinely server-generated values (the Connection.Start server
properties table, Tune limits) are parsed structurally instead.

This is the analogue of the reference's de-facto conformance gate: driving
the broker with the official RabbitMQ Java client
(chana-mq-test/src/main/scala/chana/mq/test/SimplePublisher.scala:24-58).
No external AMQP client exists in this environment, so the fixtures below
are the spec-derived stand-in: a symmetric encode/decode bug in the repo's
own codec cannot hide here, because these bytes never touch that codec.
"""

import asyncio
import struct

import pytest

from chanamq_tpu.broker.server import BrokerServer

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# spec-rule builders (this file's own, NOT chanamq_tpu.amqp)
# ---------------------------------------------------------------------------

def shortstr(s: str) -> bytes:
    b = s.encode()
    assert len(b) < 256
    return bytes([len(b)]) + b


def longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def table(entries: bytes = b"") -> bytes:
    """Field table: long byte-count prefix."""
    return struct.pack(">I", len(entries)) + entries


def table_longstr_entry(key: str, value: bytes) -> bytes:
    return shortstr(key) + b"S" + longstr(value)


def frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return struct.pack(">BHI", ftype, channel, len(payload)) + payload + b"\xce"


def method_frame(channel: int, class_id: int, method_id: int, args: bytes) -> bytes:
    return frame(1, channel, struct.pack(">HH", class_id, method_id) + args)


def content_header_frame(
    channel: int, body_size: int, flags: int, props: bytes
) -> bytes:
    payload = struct.pack(">HHQH", 60, 0, body_size, flags) + props
    return frame(2, channel, payload)


def body_frame(channel: int, body: bytes) -> bytes:
    return frame(3, channel, body)


# ---------------------------------------------------------------------------
# the canonical session's property set: all 14 basic properties
# ---------------------------------------------------------------------------

BODY = b'{"x":1}'
TIMESTAMP = 1700000000

# property presence flags, spec bit positions 15..2 (bit 0 = continuation)
ALL_14_FLAGS = 0xFFFC

ALL_14_PROPS = (
    shortstr("application/json")        # content-type    (bit 15)
    + shortstr("utf-8")                 # content-encoding (bit 14)
    + table(table_longstr_entry("k", b"v"))  # headers     (bit 13)
    + bytes([2])                        # delivery-mode   (bit 12)
    + bytes([5])                        # priority        (bit 11)
    + shortstr("corr-1")                # correlation-id  (bit 10)
    + shortstr("reply.q")               # reply-to        (bit 9)
    + shortstr("60000")                 # expiration      (bit 8)
    + shortstr("msg-1")                 # message-id      (bit 7)
    + struct.pack(">Q", TIMESTAMP)      # timestamp       (bit 6)
    + shortstr("t.ev")                  # type            (bit 5)
    + shortstr("guest")                 # user-id         (bit 4)
    + shortstr("gw")                    # app-id          (bit 3)
    + shortstr("cl")                    # cluster-id      (bit 2)
)


# ---------------------------------------------------------------------------
# socket helpers
# ---------------------------------------------------------------------------

async def read_frame(reader: asyncio.StreamReader) -> tuple[int, int, bytes]:
    """Read one frame with this file's own framing rules; returns
    (type, channel, payload) after asserting the 0xCE end octet."""
    header = await asyncio.wait_for(reader.readexactly(7), 10)
    ftype, channel, size = struct.unpack(">BHI", header)
    rest = await asyncio.wait_for(reader.readexactly(size + 1), 10)
    assert rest[-1] == 0xCE, f"missing frame-end octet, got {rest[-1]:#x}"
    return ftype, channel, rest[:-1]


async def expect_bytes(reader: asyncio.StreamReader, expected: bytes, what: str):
    got = await asyncio.wait_for(reader.readexactly(len(expected)), 10)
    assert got == expected, (
        f"{what}: wire bytes differ\n  expected {expected.hex()}\n  got      {got.hex()}"
    )


async def handshake(reader, writer, *, heartbeat: int = 0,
                    open_channel: bool = True) -> tuple[int, int]:
    """Non-golden handshake setup (the canonical-session test asserts these
    bytes; tests focused elsewhere reuse this): protocol header -> StartOk
    -> Tune -> TuneOk -> Connection.Open -> OpenOk [-> Channel.Open(1)]."""
    writer.write(b"AMQP\x00\x00\x09\x01")
    await read_frame(reader)  # Connection.Start
    writer.write(method_frame(0, 10, 11,
        table() + shortstr("PLAIN") + longstr(b"\x00guest\x00guest")
        + shortstr("en_US")))
    _, _, payload = await read_frame(reader)  # Connection.Tune
    channel_max, frame_max, _ = struct.unpack(">HIH", payload[4:12])
    writer.write(method_frame(0, 10, 31,
        struct.pack(">HIH", channel_max, frame_max, heartbeat)))
    writer.write(method_frame(0, 10, 40,
        shortstr("/") + shortstr("") + b"\x00"))
    await read_frame(reader)  # Connection.OpenOk
    if open_channel:
        writer.write(method_frame(1, 20, 10, shortstr("")))
        await read_frame(reader)  # Channel.OpenOk
    return channel_max, frame_max


# ---------------------------------------------------------------------------
# the test
# ---------------------------------------------------------------------------

async def test_golden_wire_canonical_session():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    reader, writer = await asyncio.open_connection("127.0.0.1", srv.bound_port)
    try:
        # -- protocol header ------------------------------------------------
        writer.write(b"AMQP\x00\x00\x09\x01")

        # -- Connection.Start (server-generated content: parse structurally)
        ftype, channel, payload = await read_frame(reader)
        assert (ftype, channel) == (1, 0)
        class_id, method_id = struct.unpack(">HH", payload[:4])
        assert (class_id, method_id) == (10, 10)  # connection.start
        assert payload[4:6] == b"\x00\x09"  # version-major 0, version-minor 9
        # server-properties table: skip by its long length prefix
        (tbl_len,) = struct.unpack(">I", payload[6:10])
        rest = payload[10 + tbl_len:]
        (mech_len,) = struct.unpack(">I", rest[:4])
        mechanisms = rest[4:4 + mech_len]
        assert b"PLAIN" in mechanisms
        (loc_len,) = struct.unpack(">I", rest[4 + mech_len:8 + mech_len])
        locales = rest[8 + mech_len:8 + mech_len + loc_len]
        assert b"en_US" in locales
        assert rest[8 + mech_len + loc_len:] == b""  # args end exactly here

        # -- Connection.StartOk --------------------------------------------
        writer.write(method_frame(0, 10, 11,
            table()                                  # client-properties
            + shortstr("PLAIN")                      # mechanism
            + longstr(b"\x00guest\x00guest")         # response
            + shortstr("en_US")))                    # locale

        # -- Connection.Tune (server limits: structural) --------------------
        ftype, channel, payload = await read_frame(reader)
        assert (ftype, channel) == (1, 0)
        assert payload[:4] == struct.pack(">HH", 10, 30)
        channel_max, frame_max, heartbeat = struct.unpack(">HIH", payload[4:12])
        assert len(payload) == 12
        assert channel_max >= 1 and frame_max >= 4096
        assert heartbeat == 0  # server configured with heartbeat off

        # -- Connection.TuneOk + Connection.Open ---------------------------
        writer.write(method_frame(0, 10, 31,
            struct.pack(">HIH", channel_max, frame_max, 0)))
        writer.write(method_frame(0, 10, 40,
            shortstr("/")        # virtual-host
            + shortstr("")       # reserved-1 (capabilities)
            + b"\x00"))          # reserved-2 bit

        # -- Connection.OpenOk: byte-exact ---------------------------------
        await expect_bytes(reader,
            method_frame(0, 10, 41, shortstr("")), "connection.open-ok")

        # -- Channel.Open(1) -> Channel.OpenOk byte-exact -------------------
        writer.write(method_frame(1, 20, 10, shortstr("")))  # reserved-1
        await expect_bytes(reader,
            method_frame(1, 20, 11, longstr(b"")), "channel.open-ok")

        # -- Exchange.Declare durable direct -> DeclareOk byte-exact --------
        writer.write(method_frame(1, 40, 10,
            struct.pack(">H", 0)     # reserved-1 (ticket)
            + shortstr("gw.ex")
            + shortstr("direct")
            + b"\x02"                # bits: passive=0 durable=1 auto-delete=0
                                     #       internal=0 no-wait=0
            + table()))
        await expect_bytes(reader,
            method_frame(1, 40, 11, b""), "exchange.declare-ok")

        # -- Queue.Declare durable with x-message-ttl -> DeclareOk ----------
        # (the reference smoke test declares with x-message-ttl=60000:
        #  SimplePublisher.scala:36-41). 'I' = long-int field value.
        ttl_entry = shortstr("x-message-ttl") + b"I" + struct.pack(">i", 60000)
        writer.write(method_frame(1, 50, 10,
            struct.pack(">H", 0)
            + shortstr("gw.q")
            + b"\x02"                # bits: passive=0 durable=1 excl=0
                                     #       auto-delete=0 no-wait=0
            + table(ttl_entry)))
        await expect_bytes(reader,
            method_frame(1, 50, 11,
                shortstr("gw.q") + struct.pack(">II", 0, 0)),
            "queue.declare-ok")

        # -- Queue.Bind -> BindOk byte-exact --------------------------------
        writer.write(method_frame(1, 50, 20,
            struct.pack(">H", 0)
            + shortstr("gw.q") + shortstr("gw.ex") + shortstr("quote")
            + b"\x00"                # no-wait=0
            + table()))
        await expect_bytes(reader,
            method_frame(1, 50, 21, b""), "queue.bind-ok")

        # -- Basic.Publish with all 14 properties ---------------------------
        writer.write(
            method_frame(1, 60, 40,
                struct.pack(">H", 0)
                + shortstr("gw.ex") + shortstr("quote")
                + b"\x00")           # mandatory=0 immediate=0
            + content_header_frame(1, len(BODY), ALL_14_FLAGS, ALL_14_PROPS)
            + body_frame(1, BODY))

        # -- Basic.Get -> GetOk + header + body, all byte-exact -------------
        writer.write(method_frame(1, 60, 70,
            struct.pack(">H", 0) + shortstr("gw.q") + b"\x00"))  # no-ack=0
        await expect_bytes(reader,
            method_frame(1, 60, 71,
                struct.pack(">Q", 1)          # delivery-tag 1
                + b"\x00"                     # redelivered=0
                + shortstr("gw.ex") + shortstr("quote")
                + struct.pack(">I", 0)),      # message-count after this get
            "basic.get-ok")
        # the content header must echo every property byte-for-byte
        await expect_bytes(reader,
            content_header_frame(1, len(BODY), ALL_14_FLAGS, ALL_14_PROPS),
            "content header (14 properties)")
        await expect_bytes(reader, body_frame(1, BODY), "body")

        # -- Basic.Ack ------------------------------------------------------
        writer.write(method_frame(1, 60, 80,
            struct.pack(">Q", 1) + b"\x00"))  # delivery-tag 1, multiple=0

        # -- Basic.Get on the now-empty queue -> GetEmpty byte-exact --------
        writer.write(method_frame(1, 60, 70,
            struct.pack(">H", 0) + shortstr("gw.q") + b"\x00"))
        await expect_bytes(reader,
            method_frame(1, 60, 72, shortstr("")),  # reserved cluster-id
            "basic.get-empty")

        # -- push delivery path: publish again, consume, expect Deliver -----
        writer.write(
            method_frame(1, 60, 40,
                struct.pack(">H", 0)
                + shortstr("gw.ex") + shortstr("quote")
                + b"\x00")
            + content_header_frame(1, len(BODY), ALL_14_FLAGS, ALL_14_PROPS)
            + body_frame(1, BODY))
        writer.write(method_frame(1, 60, 20,      # basic.consume
            struct.pack(">H", 0)
            + shortstr("gw.q")
            + shortstr("gold-tag")                # consumer-tag
            + b"\x00"                             # bits: no-local=0 no-ack=0
                                                  #       exclusive=0 no-wait=0
            + table()))
        await expect_bytes(reader,
            method_frame(1, 60, 21, shortstr("gold-tag")), "basic.consume-ok")
        await expect_bytes(reader,
            method_frame(1, 60, 60,               # basic.deliver
                shortstr("gold-tag")
                + struct.pack(">Q", 2)            # delivery-tag 2
                + b"\x00"                         # redelivered=0
                + shortstr("gw.ex") + shortstr("quote")),
            "basic.deliver")
        await expect_bytes(reader,
            content_header_frame(1, len(BODY), ALL_14_FLAGS, ALL_14_PROPS),
            "deliver content header")
        await expect_bytes(reader, body_frame(1, BODY), "deliver body")
        writer.write(method_frame(1, 60, 80,
            struct.pack(">Q", 2) + b"\x00"))      # ack the delivery
        # basic.cancel -> cancel-ok byte-exact
        writer.write(method_frame(1, 60, 30,
            shortstr("gold-tag") + b"\x00"))      # no-wait=0
        await expect_bytes(reader,
            method_frame(1, 60, 31, shortstr("gold-tag")), "basic.cancel-ok")

        # -- Channel.Close -> CloseOk byte-exact ----------------------------
        writer.write(method_frame(1, 20, 40,
            struct.pack(">H", 200) + shortstr("bye")
            + struct.pack(">HH", 0, 0)))
        await expect_bytes(reader,
            method_frame(1, 20, 41, b""), "channel.close-ok")

        # -- Connection.Close -> CloseOk byte-exact -------------------------
        writer.write(method_frame(0, 10, 50,
            struct.pack(">H", 200) + shortstr("bye")
            + struct.pack(">HH", 0, 0)))
        await expect_bytes(reader,
            method_frame(0, 10, 51, b""), "connection.close-ok")
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
        await srv.stop()


async def test_golden_wire_heartbeat_and_bad_header():
    """Two framing edges straight from the spec: (a) a wrong protocol header
    is answered with the server's own header and a hangup; (b) a heartbeat
    frame is type 8, channel 0, empty payload."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=1)
    await srv.start()
    try:
        # (a) wrong protocol header (exactly 8 bytes: the server reads just
        # the header before closing; unread residue would turn FIN into RST)
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.bound_port)
        writer.write(b"HTTP/1.1")
        got = await asyncio.wait_for(reader.readexactly(8), 10)
        assert got == b"AMQP\x00\x00\x09\x01"
        assert await asyncio.wait_for(reader.read(1), 10) == b""  # closed
        writer.close()

        # (b) negotiate a 1s heartbeat, then sit idle and expect the server's
        # heartbeat frame: exactly 08 0000 00000000 CE
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.bound_port)
        await handshake(reader, writer, heartbeat=1, open_channel=False)
        await expect_bytes(reader, b"\x08\x00\x00\x00\x00\x00\x00\xce",
                           "heartbeat frame")
        writer.close()
    finally:
        await srv.stop()


async def test_golden_wire_confirms_and_mandatory_return():
    """Publisher-confirm and mandatory-return wire shapes: confirm.select ->
    select-ok; a pipelined burst of publishes is confirmed with ONE
    Basic.Ack(multiple=1) carrying the batch's highest seq (the server's
    documented coalescing, mirroring the reference's run-length confirm
    logic, FrameStage.scala:571-596); a mandatory publish to an unroutable
    key comes back as Basic.Return + the untouched header and body."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    reader, writer = await asyncio.open_connection("127.0.0.1", srv.bound_port)
    try:
        await handshake(reader, writer)
        writer.write(method_frame(1, 50, 10,    # queue.declare default-bound
            struct.pack(">H", 0) + shortstr("cf.q") + b"\x00" + table()))
        await read_frame(reader)  # DeclareOk

        # confirm.select -> select-ok byte-exact (class 85, methods 10/11)
        writer.write(method_frame(1, 85, 10, b"\x00"))  # no-wait=0
        await expect_bytes(reader,
            method_frame(1, 85, 11, b""), "confirm.select-ok")

        # three pipelined publishes to the default exchange -> ONE coalesced
        # Basic.Ack with delivery-tag 3, multiple=1
        publish = (
            method_frame(1, 60, 40,
                struct.pack(">H", 0) + shortstr("") + shortstr("cf.q")
                + b"\x00")
            + content_header_frame(1, len(BODY), 0x1000, bytes([1]))
            + body_frame(1, BODY))
        writer.write(publish * 3)
        await expect_bytes(reader,
            method_frame(1, 60, 80, struct.pack(">Q", 3) + b"\x01"),
            "coalesced publisher confirm (tag 3, multiple)")

        # mandatory publish to an unroutable key: Basic.Return 312 NO_ROUTE
        # + the header and body echoed byte-for-byte, then its own confirm
        writer.write(
            method_frame(1, 60, 40,
                struct.pack(">H", 0) + shortstr("") + shortstr("no.such.q")
                + b"\x01")           # mandatory=1
            + content_header_frame(1, len(BODY), ALL_14_FLAGS, ALL_14_PROPS)
            + body_frame(1, BODY))
        await expect_bytes(reader,
            method_frame(1, 60, 50,
                struct.pack(">H", 312) + shortstr("NO_ROUTE")
                + shortstr("") + shortstr("no.such.q")),
            "basic.return")
        await expect_bytes(reader,
            content_header_frame(1, len(BODY), ALL_14_FLAGS, ALL_14_PROPS),
            "returned content header")
        await expect_bytes(reader, body_frame(1, BODY), "returned body")
        await expect_bytes(reader,
            method_frame(1, 60, 80, struct.pack(">Q", 4) + b"\x01"),
            "confirm for the returned publish")
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
        await srv.stop()


async def test_golden_wire_tx_and_exchange_bind():
    """tx and exchange-to-exchange-bind wire shapes, spec-rule bytes only:
    tx.select/commit/rollback-ok frames (class 90), exchange.bind-ok
    (40,31) and the spec-quirk exchange.unbind-ok method id 51 (not 41),
    commit visibility through an e2e hop via byte-exact basic.get-ok /
    get-empty responses."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    reader, writer = await asyncio.open_connection("127.0.0.1", srv.bound_port)
    try:
        await handshake(reader, writer)
        no_bits = b"\x00"
        # exchange.declare src(direct) + dst(fanout); queue gx.q bound to dst
        writer.write(method_frame(1, 40, 10,
            struct.pack(">H", 0) + shortstr("gx.src") + shortstr("direct")
            + no_bits + table()))
        await expect_bytes(reader, method_frame(1, 40, 11, b""),
                           "exchange.declare-ok (src)")
        writer.write(method_frame(1, 40, 10,
            struct.pack(">H", 0) + shortstr("gx.dst") + shortstr("fanout")
            + no_bits + table()))
        await expect_bytes(reader, method_frame(1, 40, 11, b""),
                           "exchange.declare-ok (dst)")
        writer.write(method_frame(1, 50, 10,
            struct.pack(">H", 0) + shortstr("gx.q") + no_bits + table()))
        await read_frame(reader)  # queue.declare-ok (counts vary)
        writer.write(method_frame(1, 50, 20,
            struct.pack(">H", 0) + shortstr("gx.q") + shortstr("gx.dst")
            + shortstr("") + no_bits + table()))
        await expect_bytes(reader, method_frame(1, 50, 21, b""),
                           "queue.bind-ok")

        # exchange.bind dst <- src on key "k" -> bind-ok (40,31) byte-exact
        writer.write(method_frame(1, 40, 30,
            struct.pack(">H", 0) + shortstr("gx.dst") + shortstr("gx.src")
            + shortstr("k") + no_bits + table()))
        await expect_bytes(reader, method_frame(1, 40, 31, b""),
                           "exchange.bind-ok")

        # tx.select -> select-ok (90,10 -> 90,11)
        writer.write(method_frame(1, 90, 10, b""))
        await expect_bytes(reader, method_frame(1, 90, 11, b""),
                           "tx.select-ok")

        # a buffered publish is invisible before commit: get-empty
        publish = (
            method_frame(1, 60, 40,
                struct.pack(">H", 0) + shortstr("gx.src") + shortstr("k")
                + no_bits)
            + content_header_frame(1, len(BODY), 0x1000, bytes([1]))
            + body_frame(1, BODY))
        writer.write(publish)
        get = method_frame(1, 60, 70,
                           struct.pack(">H", 0) + shortstr("gx.q") + b"\x01")
        writer.write(get)
        await expect_bytes(reader,
            method_frame(1, 60, 72, shortstr("")), "get-empty before commit")

        # commit -> commit-ok, then the message is visible through the e2e
        # hop: get-ok with server tag 1, exchange gx.src, key k, 0 remaining
        writer.write(method_frame(1, 90, 20, b""))
        await expect_bytes(reader, method_frame(1, 90, 21, b""),
                           "tx.commit-ok")
        writer.write(get)
        await expect_bytes(reader,
            method_frame(1, 60, 71,
                struct.pack(">Q", 1) + b"\x00" + shortstr("gx.src")
                + shortstr("k") + struct.pack(">I", 0)),
            "get-ok after commit")
        await expect_bytes(reader,
            content_header_frame(1, len(BODY), 0x1000, bytes([1])),
            "got content header")
        await expect_bytes(reader, body_frame(1, BODY), "got body")

        # rollback discards: publish, rollback -> rollback-ok, get-empty
        writer.write(publish)
        writer.write(method_frame(1, 90, 30, b""))
        await expect_bytes(reader, method_frame(1, 90, 31, b""),
                           "tx.rollback-ok")
        writer.write(get)
        await expect_bytes(reader,
            method_frame(1, 60, 72, shortstr("")), "get-empty after rollback")

        # exchange.unbind -> unbind-ok with the spec-quirk method id 51
        writer.write(method_frame(1, 40, 40,
            struct.pack(">H", 0) + shortstr("gx.dst") + shortstr("gx.src")
            + shortstr("k") + no_bits + table()))
        await expect_bytes(reader, method_frame(1, 40, 51, b""),
                           "exchange.unbind-ok (method id 51)")
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
        await srv.stop()
