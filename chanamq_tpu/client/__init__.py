"""Conformance/bench AMQP client.

The reference relied on the official RabbitMQ Java client for its manual
conformance tests (chana-mq-test SimplePublisher/SimpleConsumer,
Build.scala:105-107). No third-party AMQP client exists in this environment,
so the framework ships its own asyncio client — it doubles as the public
client API and as the conformance/bench driver (tests/, bench.py).
"""

from .client import AMQPClient, ClientChannel, DeliveredMessage

__all__ = ["AMQPClient", "ClientChannel", "DeliveredMessage"]
