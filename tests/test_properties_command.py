"""BasicProperties presence-flag codec + AMQCommand render/assemble tests."""

import pytest

from chanamq_tpu.amqp.command import AMQCommand, CommandAssembler
from chanamq_tpu.amqp.constants import ErrorCode, FrameType
from chanamq_tpu.amqp.frame import Frame, FrameError, FrameParser
from chanamq_tpu.amqp import methods as m
from chanamq_tpu.amqp.properties import BasicProperties


def test_empty_properties_golden():
    props = BasicProperties()
    payload = props.encode_header(0)
    # class 60, weight 0, size 0, flags 0
    assert payload == b"\x00\x3c\x00\x00" + b"\x00" * 8 + b"\x00\x00"


def test_properties_roundtrip_full():
    props = BasicProperties(
        content_type="application/json",
        content_encoding="utf-8",
        headers={"x-key": "val", "n": 3},
        delivery_mode=2,
        priority=5,
        correlation_id="corr-1",
        reply_to="reply.q",
        expiration="60000",
        message_id="msg-42",
        timestamp=1700000000,
        type="event",
        user_id="guest",
        app_id="test-app",
        cluster_id="c1",
    )
    payload = props.encode_header(1234)
    class_id, body_size, dec = BasicProperties.decode_header(payload)
    assert class_id == 60
    assert body_size == 1234
    assert dec == props
    assert dec.is_persistent
    assert dec.expiration_ms() == 60000


def test_properties_partial_roundtrip():
    props = BasicProperties(delivery_mode=1, expiration="100")
    _, _, dec = BasicProperties.decode_header(props.encode_header(0))
    assert dec.delivery_mode == 1
    assert dec.expiration == "100"
    assert dec.content_type is None
    assert not dec.is_persistent


def assemble_all(frames):
    asm = CommandAssembler()
    out = []
    for f in frames:
        out.extend(asm.feed(f))
    return out


def test_command_no_content_roundtrip():
    cmd = AMQCommand(5, m.Queue.Purge(queue="q"))
    frames = cmd.render_frames(4096)
    assert len(frames) == 1
    out = assemble_all(frames)
    assert out == [cmd]


def test_command_with_content_roundtrip():
    body = b"x" * 10
    cmd = AMQCommand(
        3,
        m.Basic.Publish(exchange="e", routing_key="k"),
        BasicProperties(delivery_mode=2),
        body,
    )
    out = assemble_all(cmd.render_frames(4096))
    assert len(out) == 1
    got = out[0]
    assert got.method == cmd.method
    assert got.body == body
    assert got.properties.delivery_mode == 2


def test_body_fragmentation_by_frame_max():
    body = bytes(range(256)) * 10  # 2560 bytes
    frame_max = 128  # payload max = 120
    cmd = AMQCommand(1, m.Basic.Publish(exchange="e"), BasicProperties(), body)
    frames = cmd.render_frames(frame_max)
    body_frames = [f for f in frames if f.type == FrameType.BODY]
    assert all(len(f.payload) <= frame_max - 8 for f in body_frames)
    assert b"".join(f.payload for f in body_frames) == body
    # wire roundtrip through the parser too
    parser = FrameParser(frame_max=frame_max)
    reparsed = list(parser.feed(cmd.render(frame_max)))
    out = assemble_all(reparsed)
    assert out[0].body == body


def test_zero_length_body():
    cmd = AMQCommand(1, m.Basic.Publish(exchange="e"), BasicProperties(), b"")
    frames = cmd.render_frames(4096)
    assert [f.type for f in frames] == [FrameType.METHOD, FrameType.HEADER]
    out = assemble_all(frames)
    assert out[0].body == b""


def test_interleaved_channels():
    c1 = AMQCommand(1, m.Basic.Publish(exchange="a"), BasicProperties(), b"one")
    c2 = AMQCommand(2, m.Basic.Publish(exchange="b"), BasicProperties(), b"two")
    f1, f2 = c1.render_frames(4096), c2.render_frames(4096)
    # interleave: m1 m2 h1 h2 b1 b2
    frames = [f1[0], f2[0], f1[1], f2[1], f1[2], f2[2]]
    out = assemble_all(frames)
    assert {cmd.channel for cmd in out} == {1, 2}
    assert {cmd.body for cmd in out} == {b"one", b"two"}


def test_unexpected_header_frame_is_error():
    props = BasicProperties()
    out = assemble_all([Frame.header(1, props.encode_header(0))])
    assert isinstance(out[0], FrameError)
    assert out[0].code == ErrorCode.UNEXPECTED_FRAME


def test_method_while_content_pending_is_error():
    cmd = AMQCommand(1, m.Basic.Publish(exchange="e"), BasicProperties(), b"xy")
    frames = cmd.render_frames(4096)
    out = assemble_all([frames[0], Frame.method(1, m.Basic.Ack(delivery_tag=1).encode())])
    assert any(isinstance(o, FrameError) for o in out)


def test_body_overflow_is_error():
    method = Frame.method(1, m.Basic.Publish(exchange="e").encode())
    header = Frame.header(1, BasicProperties().encode_header(2))
    body = Frame.body(1, b"toolong")
    out = assemble_all([method, header, body])
    assert isinstance(out[-1], FrameError)
