"""Exchange routing matchers: direct, fanout, topic (wildcard trie), headers.

Capability parity with the reference's QueueMatcher hierarchy
(chana-mq-server .../engine/QueueMatcher.scala:11-66 for direct/fanout,
:140-601 for the topic trie). The reference's trie is a lock-free CAS
concurrent trie supporting only the ``*`` wildcard; this rebuild's topic
matcher is a plain dict-based trie (single-threaded asyncio owns each vhost's
routing table, so CAS machinery buys nothing here) and implements the full
AMQP topic grammar: ``*`` matches exactly one word, ``#`` matches zero or
more words — the reference lacks ``#`` (SURVEY.md §7.2 item 2 flags this
fidelity-vs-spec decision; we choose the spec).

A binding maps a routing pattern to a set of (queue, binding-arguments)
destinations. The headers matcher implements x-match=all/any over binding
arguments vs message headers.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional


class Matcher:
    """Binding table for one exchange."""

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        """Add a binding; returns True if it did not exist before."""
        raise NotImplementedError

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        """Remove a binding; returns True if it existed."""
        raise NotImplementedError

    def unbind_queue(self, queue: str) -> int:
        """Remove every binding to a queue (queue deleted); returns count."""
        raise NotImplementedError

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        """Queues a message with this routing key / headers routes to."""
        raise NotImplementedError

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        """All (key, queue, arguments) bindings, for introspection/recovery."""
        raise NotImplementedError

    def is_empty(self) -> bool:
        """Subclasses override with an O(1) probe."""
        return not self.bindings()

    # Subclasses also expose ``binding_table``: an alias of the live
    # binding collection, identity-stable for the matcher's lifetime and
    # only ever mutated in place — truthy iff any binding exists. The
    # firehose caches it so its per-message hot-path gate is a plain
    # attribute load + bool test, no method call, no trie walk.
    binding_table: "dict | set" = {}


class DirectMatcher(Matcher):
    """Exact routing-key match (reference: DirectMatcher, QueueMatcher.scala:29-48)."""

    def __init__(self) -> None:
        self._bindings: dict[str, set[str]] = {}
        self.binding_table = self._bindings

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        queues = self._bindings.setdefault(key, set())
        if queue in queues:
            return False
        queues.add(queue)
        return True

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        queues = self._bindings.get(key)
        if not queues or queue not in queues:
            return False
        queues.discard(queue)
        if not queues:
            del self._bindings[key]
        return True

    def unbind_queue(self, queue: str) -> int:
        removed = 0
        for key in list(self._bindings):
            if self.unbind(key, queue):
                removed += 1
        return removed

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        return set(self._bindings.get(key, ()))

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        return [(k, q, None) for k, qs in self._bindings.items() for q in sorted(qs)]

    def is_empty(self) -> bool:
        return not self._bindings


class FanoutMatcher(Matcher):
    """Routing key ignored; all bound queues match (reference: FanoutMatcher)."""

    def __init__(self) -> None:
        self._queues: dict[str, int] = {}  # queue -> bind count (distinct keys)
        self._keys: set[tuple[str, str]] = set()
        self.binding_table = self._keys

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if (key, queue) in self._keys:
            return False
        self._keys.add((key, queue))
        self._queues[queue] = self._queues.get(queue, 0) + 1
        return True

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if (key, queue) not in self._keys:
            return False
        self._keys.discard((key, queue))
        n = self._queues.get(queue, 0) - 1
        if n <= 0:
            self._queues.pop(queue, None)
        else:
            self._queues[queue] = n
        return True

    def unbind_queue(self, queue: str) -> int:
        keys = [kq for kq in self._keys if kq[1] == queue]
        for kq in keys:
            self._keys.discard(kq)
        self._queues.pop(queue, None)
        return len(keys)

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        return set(self._queues)

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        return [(k, q, None) for (k, q) in sorted(self._keys)]

    def is_empty(self) -> bool:
        return not self._keys


class _TrieNode:
    __slots__ = ("children", "queues")

    def __init__(self) -> None:
        self.children: dict[str, _TrieNode] = {}
        self.queues: set[str] = set()


class TopicMatcher(Matcher):
    """Topic-pattern trie over '.'-separated words.

    ``*`` matches exactly one word; ``#`` matches zero or more words.
    The reference's trie (QueueMatcher.scala:140-601) supports only ``*``;
    this one implements the full topic grammar.
    """

    def __init__(self) -> None:
        self._root = _TrieNode()
        self._patterns: dict[tuple[str, str], int] = {}  # (key, queue) marker
        self.binding_table = self._patterns

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if (key, queue) in self._patterns:
            return False
        self._patterns[(key, queue)] = 1
        node = self._root
        for word in key.split("."):
            node = node.children.setdefault(word, _TrieNode())
        node.queues.add(queue)
        return True

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if self._patterns.pop((key, queue), None) is None:
            return False
        path: list[tuple[_TrieNode, str]] = []
        node = self._root
        for word in key.split("."):
            nxt = node.children.get(word)
            if nxt is None:
                return True  # trie already pruned; marker was authoritative
            path.append((node, word))
            node = nxt
        node.queues.discard(queue)
        # prune empty branches bottom-up (the reference's tomb/contract step)
        for parent, word in reversed(path):
            child = parent.children[word]
            if child.queues or child.children:
                break
            del parent.children[word]
        return True

    def unbind_queue(self, queue: str) -> int:
        keys = [k for (k, q) in self._patterns if q == queue]
        for key in keys:
            self.unbind(key, queue)
        return len(keys)

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        words = key.split(".") if key else [""]
        result: set[str] = set()
        self._walk(self._root, words, 0, result)
        return result

    def _walk(self, node: _TrieNode, words: list[str], i: int, out: set[str]) -> None:
        if i == len(words):
            out.update(node.queues)
            # trailing '#' branches match zero remaining words
            tail = node.children.get("#")
            while tail is not None:
                out.update(tail.queues)
                tail = tail.children.get("#")
            return
        word = words[i]
        child = node.children.get(word)
        if child is not None:
            self._walk(child, words, i + 1, out)
        star = node.children.get("*")
        if star is not None:
            self._walk(star, words, i + 1, out)
        hash_ = node.children.get("#")
        if hash_ is not None:
            # '#' consumes zero or more words
            for j in range(i, len(words) + 1):
                self._walk(hash_, words, j, out)

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        return [(k, q, None) for (k, q) in sorted(self._patterns)]

    def is_empty(self) -> bool:
        return not self._patterns


_EMPTY_SET: frozenset = frozenset()


class HeadersMatcher(Matcher):
    """Routes on message headers vs binding arguments (x-match=all|any).

    The reference declares the headers exchange type but never implements a
    matcher for it (AMQP.scala:33-47 lists HEADERS; no HeadersMatcher exists);
    this rebuild completes the capability.

    Routing is index-driven, not a scan of every binding: each binding is
    keyed in an inverted (header, value) index — every pair for x-match=any
    (one hit IS a match), one representative pair for x-match=all (a
    necessary condition; candidates are then fully verified). Only bindings
    with unhashable values (field-table arrays/tables) fall back to the
    always-verified bucket, and empty all-bindings match everything by
    definition. Route cost is O(message headers + candidates), independent
    of the total binding count.
    """

    def __init__(self) -> None:
        # (queue, frozen-args-key) -> (x_match_all, {header: value})
        self._bindings: dict[tuple[str, str], tuple[bool, dict]] = {}
        self.binding_table = self._bindings
        # inverted indexes: (header, value) -> binding keys
        self._any_index: dict[tuple, set] = {}
        self._all_index: dict[tuple, set] = {}
        self._unindexed: set = set()  # unhashable-valued bindings: always verify
        self._empty_all: set = set()  # empty all-bindings: match everything
        # bkey -> index keys used, for O(1) unbind
        self._placement: dict[tuple[str, str], tuple[str, list]] = {}

    @staticmethod
    def _args_key(arguments: Optional[dict]) -> str:
        return repr(sorted((arguments or {}).items(), key=lambda kv: kv[0]))

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        args = dict(arguments or {})
        x_match_all = str(args.pop("x-match", "all")).lower() != "any"
        bkey = (queue, self._args_key(arguments))
        if bkey in self._bindings:
            return False
        self._bindings[bkey] = (x_match_all, args)
        self._place(bkey, x_match_all, args)
        return True

    def _place(self, bkey, x_match_all: bool, args: dict) -> None:
        if not args:
            if x_match_all:
                self._empty_all.add(bkey)
                self._placement[bkey] = ("empty_all", [])
            else:
                # empty any-binding can never match: keep it registered but
                # reachable by no route
                self._placement[bkey] = ("never", [])
            return
        hashable = []
        unhashable = False
        for h, v in args.items():
            try:
                hash(v)
                hashable.append((h, v))
            except TypeError:
                unhashable = True
        if x_match_all:
            if hashable:
                k = hashable[0]
                self._all_index.setdefault(k, set()).add(bkey)
                self._placement[bkey] = ("all", [k])
            else:
                self._unindexed.add(bkey)
                self._placement[bkey] = ("unindexed", [])
        else:
            if unhashable:
                # a message could match via the unhashable pair alone
                self._unindexed.add(bkey)
                self._placement[bkey] = ("unindexed", [])
            else:
                for k in hashable:
                    self._any_index.setdefault(k, set()).add(bkey)
                self._placement[bkey] = ("any", hashable)

    def _unplace(self, bkey) -> None:
        kind, keys = self._placement.pop(bkey, ("never", []))
        if kind == "empty_all":
            self._empty_all.discard(bkey)
        elif kind == "unindexed":
            self._unindexed.discard(bkey)
        elif kind == "all":
            for k in keys:
                bucket = self._all_index.get(k)
                if bucket is not None:
                    bucket.discard(bkey)
                    if not bucket:
                        del self._all_index[k]
        elif kind == "any":
            for k in keys:
                bucket = self._any_index.get(k)
                if bucket is not None:
                    bucket.discard(bkey)
                    if not bucket:
                        del self._any_index[k]

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        bkey = (queue, self._args_key(arguments))
        if self._bindings.pop(bkey, None) is None:
            return False
        self._unplace(bkey)
        return True

    def unbind_queue(self, queue: str) -> int:
        keys = [bk for bk in self._bindings if bk[0] == queue]
        for bk in keys:
            del self._bindings[bk]
            self._unplace(bk)
        return len(keys)

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        headers = headers or {}
        matched: set[str] = {queue for (queue, _) in self._empty_all}
        candidates: set = set(self._unindexed)
        if headers and (self._any_index or self._all_index):
            for h, v in headers.items():
                try:
                    k = (h, v)
                    candidates |= self._any_index.get(k, _EMPTY_SET)
                    candidates |= self._all_index.get(k, _EMPTY_SET)
                except TypeError:
                    # unhashable header value: indexed binding values are all
                    # hashable and can't equal it (list/dict vs scalar)
                    continue
        for bkey in candidates:
            queue = bkey[0]
            if queue in matched:
                continue
            x_match_all, required = self._bindings[bkey]
            checks = (
                h in headers and headers[h] == v for h, v in required.items()
            )
            if all(checks) if x_match_all else any(checks):
                matched.add(queue)
        return matched

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        out = []
        for (queue, _), (x_match_all, args) in self._bindings.items():
            full = dict(args)
            full["x-match"] = "all" if x_match_all else "any"
            out.append(("", queue, full))
        return out

    def is_empty(self) -> bool:
        return not self._bindings


def matcher_for(exchange_type: str) -> Matcher:
    t = exchange_type.lower()
    if t == "direct":
        return DirectMatcher()
    if t == "fanout":
        return FanoutMatcher()
    if t == "topic":
        # the C++ trie is the routing fast path when the native lib is built
        # (chanamq_tpu.native_ext); same semantics, Python trie as fallback
        from .. import native_ext

        if native_ext.available():
            return native_ext.NativeTopicMatcher()
        return TopicMatcher()
    if t == "headers":
        return HeadersMatcher()
    raise ValueError(f"unknown exchange type {exchange_type!r}")
