"""Method registry + generic codec tests for all 8 AMQP classes."""

import pytest

from chanamq_tpu.amqp import methods as m


def roundtrip(method):
    return m.decode_method(method.encode())


def test_registry_covers_all_classes():
    # connection 12, channel 6, access 2, exchange 8, queue 10, basic 18,
    # confirm 2, tx 6 = 64 methods
    assert m.registry_size() == 64


def test_connection_start_golden_prefix():
    start = m.Connection.Start(
        version_major=0, version_minor=9,
        server_properties={"product": "chanamq-tpu"},
        mechanisms=b"PLAIN EXTERNAL", locales=b"en_US",
    )
    enc = start.encode()
    # class 10, method 10, major 0, minor 9
    assert enc[:6] == b"\x00\x0a\x00\x0a\x00\x09"
    dec = roundtrip(start)
    assert dec == start
    assert dec.server_properties == {"product": "chanamq-tpu"}
    assert dec.mechanisms == b"PLAIN EXTERNAL"


def test_basic_publish_bits_golden():
    pub = m.Basic.Publish(exchange="ex", routing_key="rk", mandatory=True, immediate=False)
    enc = pub.encode()
    # class 60 method 40, ticket 0, "ex", "rk", bits=0b01
    assert enc == b"\x00\x3c\x00\x28\x00\x00\x02ex\x02rk\x01"
    assert roundtrip(pub) == pub


def test_bit_packing_shares_one_octet():
    d = m.Queue.Declare(queue="q", passive=False, durable=True,
                        exclusive=False, auto_delete=True, nowait=False)
    enc = d.encode()
    # bits durable(1)+auto_delete(3) -> 0b01010 = 0x0a, one octet before table
    assert enc == b"\x00\x32\x00\x0a\x00\x00\x01q\x0a\x00\x00\x00\x00"
    dec = roundtrip(d)
    assert dec.durable is True and dec.auto_delete is True
    assert dec.passive is False and dec.exclusive is False


def test_access_request_five_bits():
    r = m.Access.Request(realm="/data", exclusive=True, passive=False,
                         active=True, write=False, read=True)
    dec = roundtrip(r)
    assert (dec.exclusive, dec.passive, dec.active, dec.write, dec.read) == (
        True, False, True, False, True)


def test_all_methods_roundtrip_defaults():
    from chanamq_tpu.amqp.methods import _registry
    for (cid, mid), cls in _registry.items():
        inst = cls()
        dec = roundtrip(inst)
        assert dec == inst, cls.NAME
        assert (dec.CLASS_ID, dec.METHOD_ID) == (cid, mid)


def test_exchange_unbind_ok_is_51():
    assert m.Exchange.UnbindOk.METHOD_ID == 51


def test_basic_nack_roundtrip():
    n = m.Basic.Nack(delivery_tag=123456789, multiple=True, requeue=True)
    dec = roundtrip(n)
    assert dec.delivery_tag == 123456789
    assert dec.multiple and dec.requeue


def test_content_flags():
    assert m.Basic.Publish.HAS_CONTENT
    assert m.Basic.Deliver.HAS_CONTENT
    assert m.Basic.Return.HAS_CONTENT
    assert m.Basic.GetOk.HAS_CONTENT
    assert not m.Basic.Ack.HAS_CONTENT
    assert not m.Queue.Declare.HAS_CONTENT


def test_unknown_method_raises():
    with pytest.raises(m.MethodDecodeError):
        m.decode_method(b"\x00\x63\x00\x63")


def test_unexpected_field_raises():
    with pytest.raises(TypeError):
        m.Basic.Publish(nope=1)
