"""OTLP-shaped span export: render + background shipper.

The render maps one completed :class:`~chanamq_tpu.trace.Trace` to an
OTLP span tree — a root ``broker`` span covering the trace bounds
(parented to the client's span when a W3C context was propagated) plus
one child span per populated stage slot. Everything serializes as
OTLP/HTTP **JSON** (``ResourceSpans``), so a stock collector ingests it
on ``/v1/traces`` and the pull fallback ``GET /admin/otel/spans`` serves
the identical document for scrape-style collection.

The :class:`OtelExporter` drains completed traces through a bounded
queue: the trace runtime's finish hook enqueues (shedding — with a
counter — when the overload ladder is at stage >= 1 or the queue is
full), and a timer task flushes batches to the configured endpoint,
dialing through the cluster layer's :class:`ReconnectBackoff` so a dead
collector costs one fast failure per window, not a connect timeout per
batch.

Timestamps: trace spans stamp ``time.perf_counter_ns()``; OTLP wants
epoch nanoseconds. One offset (``time_ns - perf_counter_ns``) is
computed per render so all spans in a document share a consistent clock
mapping.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import deque
from typing import TYPE_CHECKING, Iterable, Optional
from urllib.parse import urlsplit

from .. import trace as trace_mod
from ..cluster.rpc import ReconnectBackoff, RpcError
from .context import derive_span_id, derive_trace_id

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker
    from ..trace.runtime import Trace

log = logging.getLogger("chanamq.otel")

_SCOPE = {"name": "chanamq-tpu.trace", "version": "1"}
_KIND_SERVER = 2
_KIND_INTERNAL = 1


def _attr(key: str, value) -> dict:
    if isinstance(value, bool):
        wrapped = {"boolValue": value}
    elif isinstance(value, int):
        wrapped = {"intValue": str(value)}  # OTLP JSON: int64 as string
    elif isinstance(value, float):
        wrapped = {"doubleValue": value}
    else:
        wrapped = {"stringValue": str(value)}
    return {"key": key, "value": wrapped}


def clock_offset_ns() -> int:
    """perf_counter timeline -> unix-epoch nanoseconds."""
    return time.time_ns() - time.perf_counter_ns()


def otlp_ids(tr: "Trace") -> "tuple[str, str, str]":
    """``(trace_id, root_span_id, root_parent_span_id)`` for a trace.

    A propagated context supplies all three (parent = the client's
    span); a seeded sample derives a stable trace id from its internal
    ``node#seq`` id and exports its root with no parent."""
    w3c = tr.w3c
    if w3c is not None:
        return w3c.trace_id, w3c.root_span_id, w3c.parent_span_id
    trace_id = derive_trace_id(tr.trace_id)
    return trace_id, derive_span_id(trace_id, "broker", tr.origin), ""


def trace_spans(tr: "Trace", offset_ns: int) -> list:
    """One OTLP span per populated stage slot, under a root broker span."""
    bounds = tr.bounds_ns()
    if bounds is None:
        return []
    trace_id, root_id, root_parent = otlp_ids(tr)
    attrs = [_attr("chanamq.trace_id", tr.trace_id),
             _attr("chanamq.origin", tr.origin)]
    for key, value in (tr.attrs or {}).items():
        attrs.append(_attr(f"chanamq.{key}", value))
    if tr.chaos_rules:
        attrs.append(_attr("chanamq.chaos_rules", ",".join(tr.chaos_rules)))
    root = {
        "traceId": trace_id,
        "spanId": root_id,
        "name": "broker",
        "kind": _KIND_SERVER,
        "startTimeUnixNano": str(bounds[0] + offset_ns),
        "endTimeUnixNano": str(bounds[1] + offset_ns),
        "attributes": attrs,
    }
    if root_parent:
        root["parentSpanId"] = root_parent
    spans = [root]
    stages = trace_mod.STAGES
    for i, slot in enumerate(tr.slots):
        if slot is None:
            continue
        t0, t1, node = slot
        spans.append({
            "traceId": trace_id,
            "spanId": derive_span_id(trace_id, stages[i], node, str(i)),
            "parentSpanId": root_id,
            "name": stages[i],
            "kind": _KIND_INTERNAL,
            "startTimeUnixNano": str(t0 + offset_ns),
            "endTimeUnixNano": str(max(t0, t1) + offset_ns),
            "attributes": [_attr("chanamq.node", node)],
        })
    return spans


def default_resource(broker) -> dict:
    res = {
        "service.name": "chanamq-tpu",
        "chanamq.node": getattr(broker, "trace_node", None) or "local",
    }
    shard = getattr(broker, "shard_info", None)
    if shard:
        res["chanamq.shard"] = shard.get("index")
    return res


def resource_spans(traces: Iterable["Trace"], resource: dict,
                   offset_ns: Optional[int] = None) -> dict:
    """The full OTLP/HTTP JSON document for a batch of traces."""
    if offset_ns is None:
        offset_ns = clock_offset_ns()
    spans: list = []
    for tr in traces:
        spans.extend(trace_spans(tr, offset_ns))
    return {"resourceSpans": [{
        "resource": {
            "attributes": [_attr(k, v) for k, v in resource.items()
                           if v is not None]},
        "scopeSpans": [{"scope": dict(_SCOPE), "spans": spans}],
    }]}


def span_count(doc: dict) -> int:
    return sum(len(scope.get("spans") or ())
               for rs in doc.get("resourceSpans") or ()
               for scope in rs.get("scopeSpans") or ())


class OtelExporter:
    """Background drain of completed traces into OTLP/HTTP JSON batches.

    With an endpoint configured a flush task posts batches every
    ``flush_ms``; without one (collector-less mode) completed traces
    queue for the pull fallback ``GET /admin/otel/spans`` and the
    bounded queue simply sheds the oldest overflow."""

    def __init__(self, broker: "Broker", *, endpoint: str = "",
                 flush_ms: int = 1000, max_batch: int = 64,
                 queue_size: int = 1024) -> None:
        self.broker = broker
        self.metrics = broker.metrics
        self.endpoint = endpoint
        self.flush_ms = max(10, int(flush_ms))
        self.max_batch = max(1, int(max_batch))
        self.queue_size = max(1, int(queue_size))
        self._queue: deque = deque()
        self._task: Optional[asyncio.Task] = None
        self._backoff = ReconnectBackoff()

    # -- intake (called from TraceRuntime.finish) --------------------------

    def on_trace(self, tr: "Trace") -> None:
        """Enqueue a completed trace; shed-and-count under pressure.

        Sheds when the overload ladder is at stage >= 1 (exporting is the
        first observability luxury to go) or when the queue is full (a
        down collector must not grow memory without bound)."""
        flow = self.broker.flow
        if (flow is not None and flow.stage >= 1) \
                or len(self._queue) >= self.queue_size:
            self.metrics.otel_spans_shed += 1
            return
        self._queue.append(tr)

    def queue_depth(self) -> int:
        return len(self._queue)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        rt = trace_mod.ACTIVE
        if rt is not None:
            rt.export_hook = self.on_trace
        if self.endpoint:
            self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        rt = trace_mod.ACTIVE
        # == not `is`: a bound-method attribute access mints a fresh
        # object every time, so identity would never match and a stopped
        # exporter would keep receiving (and leaking) finished traces
        if rt is not None and rt.export_hook == self.on_trace:
            rt.export_hook = None
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    def status(self) -> dict:
        return {
            "endpoint": self.endpoint or None,
            "queue_depth": len(self._queue),
            "queue_size": self.queue_size,
            "flush_ms": self.flush_ms,
            "max_batch": self.max_batch,
            "backoff": self._backoff.state(),
        }

    # -- pull fallback -----------------------------------------------------

    def pull(self, limit: Optional[int] = None) -> dict:
        """Drain up to ``limit`` queued traces as one OTLP document (the
        collector-less mode: a scraper owns delivery instead of a push
        pipeline, so a pull consumes what it takes)."""
        n = len(self._queue)
        if limit is not None:
            n = min(n, max(0, limit))
        batch = [self._queue.popleft() for _ in range(n)]
        doc = resource_spans(batch, default_resource(self.broker))
        self.metrics.otel_spans_exported += span_count(doc)
        self.metrics.otel_pull_served += 1
        return doc

    # -- push loop ---------------------------------------------------------

    async def _run(self) -> None:
        url = urlsplit(self.endpoint)
        while True:
            await asyncio.sleep(self.flush_ms / 1000.0)
            while self._queue:
                batch = [self._queue.popleft() for _ in range(
                    min(self.max_batch, len(self._queue)))]
                doc = resource_spans(batch, default_resource(self.broker))
                if await self._post(url, json.dumps(doc).encode()):
                    self.metrics.otel_spans_exported += span_count(doc)
                    self.metrics.otel_batches_sent += 1
                else:
                    # requeue at the head and wait for the next window:
                    # the bounded queue (+ shed counter) caps what a dead
                    # collector can accumulate
                    self.metrics.otel_export_errors += 1
                    self._queue.extendleft(reversed(batch))
                    break

    async def _post(self, url, payload: bytes) -> bool:
        try:
            self._backoff.check()
        except RpcError:
            return False
        host = url.hostname or "127.0.0.1"
        port = url.port or 4318
        path = url.path or "/v1/traces"
        writer = None
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), 5)
        except (OSError, asyncio.TimeoutError):
            self._backoff.failed()
            return False
        try:
            head = (f"POST {path} HTTP/1.1\r\nHost: {host}\r\n"
                    "Content-Type: application/json\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    "Connection: close\r\n\r\n")
            writer.write(head.encode() + payload)
            await writer.drain()
            status = await asyncio.wait_for(reader.readline(), 10)
            parts = status.split()
            ok = len(parts) >= 2 and parts[1].startswith(b"2")
            if ok:
                self._backoff.succeeded()
                self._backoff.note_clean()
            else:
                log.warning("otel export rejected: %s",
                            status.decode("ascii", "replace").strip())
            return ok
        except (OSError, asyncio.TimeoutError):
            self._backoff.failed()
            return False
        finally:
            if writer is not None:
                writer.close()
