"""Frame model + incremental parser tests (golden bytes from the 0-9-1 spec)."""

import pytest

from chanamq_tpu.amqp.constants import ErrorCode, FrameType, PROTOCOL_HEADER
from chanamq_tpu.amqp.frame import (
    Frame,
    FrameError,
    FrameParser,
    HEARTBEAT_BYTES,
    HEARTBEAT_FRAME,
)


def test_protocol_header_bytes():
    assert PROTOCOL_HEADER == b"AMQP\x00\x00\x09\x01"


def test_heartbeat_frame_golden_bytes():
    # type=8, channel=0, size=0, end=0xCE
    assert HEARTBEAT_BYTES == b"\x08\x00\x00\x00\x00\x00\x00\xce"


def test_frame_roundtrip():
    f = Frame(FrameType.METHOD, 7, b"\x00\x0a\x00\x0a payload")
    raw = f.to_bytes()
    parser = FrameParser()
    out = list(parser.feed(raw))
    assert out == [f]


def test_parser_handles_arbitrary_chunking():
    frames = [
        Frame(FrameType.METHOD, 1, b"abc"),
        HEARTBEAT_FRAME,
        Frame(FrameType.BODY, 2, bytes(range(100))),
    ]
    raw = b"".join(f.to_bytes() for f in frames)
    for chunk_size in (1, 2, 3, 7, 8, 9, len(raw)):
        parser = FrameParser()
        out = []
        for i in range(0, len(raw), chunk_size):
            out.extend(parser.feed(raw[i : i + chunk_size]))
        assert out == frames, f"chunk_size={chunk_size}"


def test_parser_rejects_bad_end_octet():
    raw = bytearray(Frame(FrameType.METHOD, 0, b"xy").to_bytes())
    raw[-1] = 0x00
    out = list(FrameParser().feed(bytes(raw)))
    assert len(out) == 1
    assert isinstance(out[0], FrameError)
    assert out[0].code == ErrorCode.FRAME_ERROR


def test_parser_rejects_unknown_frame_type():
    raw = Frame(9, 0, b"").to_bytes()
    out = list(FrameParser().feed(raw))
    assert isinstance(out[0], FrameError)


def test_parser_enforces_frame_max():
    parser = FrameParser(frame_max=16)
    raw = Frame(FrameType.BODY, 1, b"x" * 64).to_bytes()
    out = list(parser.feed(raw))
    assert isinstance(out[0], FrameError)
    assert out[0].code == ErrorCode.FRAME_ERROR
    # dead parser consumes nothing further
    assert list(parser.feed(HEARTBEAT_BYTES)) == []


def test_parser_stops_after_error():
    raw = bytearray(Frame(FrameType.METHOD, 0, b"a").to_bytes())
    raw[-1] = 0x13
    parser = FrameParser()
    assert isinstance(list(parser.feed(bytes(raw)))[0], FrameError)
    assert list(parser.feed(HEARTBEAT_BYTES)) == []
