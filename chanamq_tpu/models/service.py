"""Forecast service: live telemetry -> off-path JAX train/predict -> admin.

Closes the loop models/forecaster.py:1-16 promises (SURVEY.md §7.1's one
honest JAX role — batch analytics over broker metrics, never on the message
path):

- a sampler task on the broker's event loop appends one telemetry vector
  per tick to a TelemetryRing (models/telemetry.py) — numpy only, O(#queues)
  per tick, no JAX on the loop;
- every train-interval, a single worker thread (run_in_executor) takes a
  copy of the ring, z-scores it, runs a few train steps of the causal
  transformer on sampled (window -> next-vector) pairs, then forwards the
  newest window to produce the next-tick forecast — denormalized back to
  real units. The event loop never blocks: JAX compilation and execution
  happen entirely on the worker thread, and at most one round is in
  flight;
- the latest forecast is served by the admin API at GET /admin/forecast
  and as chanamq_forecast_* Prometheus gauges (rest/admin.py).

Enable with chana.mq.forecast.enabled (off by default: a broker should not
spin an accelerator workload unless the operator asks for capacity
forecasting).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import time
from typing import TYPE_CHECKING, Any, Optional

import numpy as np

from .telemetry import (
    FEATURES, TelemetryRing, TopKSlots, counter_state, normalization,
    sample, training_batch,
)

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker

log = logging.getLogger("chanamq.forecast")


class ForecastService:
    """Samples broker telemetry and maintains a next-tick forecast."""

    def __init__(
        self,
        broker: "Broker",
        *,
        interval_s: float = 1.0,
        train_interval_s: float = 30.0,
        seq_len: int = 64,
        history: int = 4096,
        batch: int = 16,
        steps_per_round: int = 20,
        lr: float = 1e-3,
        queue_top_k: int = 0,
        model_kwargs: Optional[dict[str, Any]] = None,
    ) -> None:
        self.broker = broker
        self.interval_s = interval_s
        self.train_interval_s = train_interval_s
        self.seq_len = seq_len
        self.batch = batch
        # per-queue awareness: widen each sample with (depth, publish_rate)
        # of the K busiest queues from the per-entity telemetry rings
        # (broker.telemetry). Slot columns are PINNED to queue identity
        # (TopKSlots): a slot keeps tracking the same queue while it stays
        # in the top-K set, with explicit eviction + a one-tick zero reset
        # on reassignment, so a training window never splices two queues'
        # series into one column. Zeros when telemetry is off.
        self.queue_top_k = queue_top_k
        self.topk = TopKSlots(queue_top_k)
        self.feature_names: tuple[str, ...] = FEATURES + tuple(
            name
            for i in range(queue_top_k)
            for name in (f"top{i}_depth", f"top{i}_publish_rate"))
        self.n_features = len(self.feature_names)
        self.steps_per_round = steps_per_round
        self.lr = lr
        # compact model by default: 8 features need nowhere near the
        # flagship dims, and the worker thread shares cores with the broker
        self.model_kwargs = dict(model_kwargs or {})
        self.model_kwargs.setdefault("d_model", 64)
        self.model_kwargs.setdefault("n_heads", 4)
        self.model_kwargs.setdefault("d_ff", 256)
        self.model_kwargs.setdefault("n_layers", 2)
        if history < seq_len + 1:
            # the train gate needs seq_len+1 retained vectors; a smaller
            # ring would silently never train
            raise ValueError(
                f"forecast history ({history}) must exceed window "
                f"({seq_len}) — the ring must hold window+1 vectors")
        self.ring = TelemetryRing(history, width=self.n_features)
        self._task: Optional[asyncio.Task] = None
        # one worker: params live on this thread, rounds never overlap
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="chanamq-forecast")
        self._round_inflight = False
        self._stopping = False  # cooperative cancel for an in-flight round
        self._np_rng = np.random.default_rng(0)
        # lazily-built JAX state (worker thread only)
        self._jax_state: Optional[dict[str, Any]] = None
        # latest results (event loop writes, anyone reads)
        self.forecast: Optional[dict[str, float]] = None
        self.loss: Optional[float] = None
        self.trained_steps = 0
        self.rounds = 0
        self.updated_at: Optional[float] = None
        self.last_error: Optional[str] = None
        # forecast accuracy: each realized tick is scored against the
        # forecast that predicted it (per-feature absolute error; running
        # MAE). The control plane gates actuation on this, and operators
        # see it at GET /admin/forecast + chanamq_forecast_error_* gauges.
        self._pending_forecast: Optional[np.ndarray] = None
        self.error_scored = 0
        self.error_last: Optional[np.ndarray] = None
        self.error_mae: Optional[np.ndarray] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self.broker.forecaster = self
        self._task = asyncio.get_event_loop().create_task(self._run())
        self._task.add_done_callback(self._on_run_done)
        log.info(
            "forecast service on: interval=%.3gs train-interval=%.3gs "
            "window=%d model=%s", self.interval_s, self.train_interval_s,
            self.seq_len, self.model_kwargs)

    async def stop(self) -> None:
        # cooperative cancel: concurrent.futures joins worker threads at
        # interpreter exit regardless of shutdown(wait=False), so an
        # in-flight round must notice and bail between train steps
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self._executor.shutdown(wait=False, cancel_futures=True)
        if getattr(self.broker, "forecaster", None) is self:
            self.broker.forecaster = None

    # -- sampling loop (event loop; numpy only) ----------------------------

    async def _run(self) -> None:
        counters = counter_state(self.broker)
        last = time.monotonic()
        next_train = last + self.train_interval_s
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                now = time.monotonic()
                vec, counters = sample(self.broker, counters, now - last)
                last = now
                if self.queue_top_k:
                    telemetry = getattr(self.broker, "telemetry", None)
                    extra = (
                        self.topk.update(*telemetry.queues.latest_matrix())
                        if telemetry is not None
                        else np.zeros(2 * self.queue_top_k, dtype=np.float32))
                    vec = np.concatenate([vec, extra])
                self.score_tick(vec)
                self.ring.push(vec)
                if (now >= next_train and not self._round_inflight
                        and len(self.ring) >= self.seq_len + 1):
                    next_train = now + self.train_interval_s
                    self._round_inflight = True
                    history = self.ring.history()  # copy: worker never sees the ring
                    loop = asyncio.get_event_loop()
                    loop.run_in_executor(
                        self._executor, self._round, history
                    ).add_done_callback(self._on_round_done)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — a bad sample tick
                # must not kill forecasting forever; record and keep sampling
                self.last_error = repr(exc)
                log.exception("forecast sample tick failed")

    def _on_run_done(self, task: "asyncio.Task") -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self.last_error = repr(exc)
            log.error("forecast sampler task died", exc_info=exc)

    def _on_round_done(self, fut: "asyncio.Future") -> None:
        self._round_inflight = False
        try:
            result = fut.result()
        except Exception as exc:  # noqa: BLE001 — survives a bad round
            self.last_error = repr(exc)
            log.exception("forecast round failed")
            return
        steps, loss, forecast = result
        self.trained_steps += steps
        if forecast is None:
            return  # round bailed early (service stopping)
        self.rounds += 1
        self.loss = loss
        self.forecast = forecast
        self.updated_at = time.time()
        self.last_error = None
        # the next realized tick scores this forecast (score_tick)
        self._pending_forecast = np.array(
            [forecast[name] for name in self.feature_names],
            dtype=np.float32)

    # -- forecast accuracy (event loop; numpy only) ------------------------

    def score_tick(self, vec: np.ndarray) -> None:
        """Score the pending next-tick forecast against the realized
        vector: per-feature absolute error, folded into a running MAE.
        A forecast is consumed by the first tick that follows it."""
        pending = self._pending_forecast
        if pending is None or len(pending) != len(vec):
            return
        self._pending_forecast = None
        err = np.abs(np.asarray(vec, dtype=np.float32) - pending)
        self.error_last = err
        self.error_scored += 1
        if self.error_mae is None:
            self.error_mae = err.copy()
        else:
            self.error_mae += (err - self.error_mae) / self.error_scored
        # NaN/inf can only come from a poisoned forecast; drop the stats
        # rather than serving non-finite gauges
        if not np.isfinite(err).all():
            self.error_last = None
            self.error_mae = None
            self.error_scored = 0

    def accuracy(self) -> Optional[dict[str, Any]]:
        if not self.error_scored or self.error_mae is None:
            return None
        return {
            "scored": self.error_scored,
            "mae": {name: float(v) for name, v in
                    zip(self.feature_names, self.error_mae)},
            "last_abs_error": (
                {name: float(v) for name, v in
                 zip(self.feature_names, self.error_last)}
                if self.error_last is not None else None),
        }

    def slot_queues(self) -> list:
        """Queue identity pinned to each top-K feature slot (None=free);
        lets the control plane map top{i}_* forecasts back to queues."""
        return self.topk.slot_queues()

    # -- train/predict round (worker thread; owns all JAX state) -----------

    def _jax_setup(self) -> dict[str, Any]:
        import jax

        from .forecaster import (
            ForecasterConfig, forward, init_momentum, init_params,
            make_train_step,
        )

        cfg = ForecasterConfig(
            n_features=self.n_features, seq_len=self.seq_len,
            **self.model_kwargs)
        params = init_params(jax.random.PRNGKey(0), cfg)
        state = {
            "cfg": cfg,
            "params": params,
            "momentum": init_momentum(params),
            "step": jax.jit(make_train_step(cfg, lr=self.lr)),
            "forward": jax.jit(lambda p, x: forward(p, x, cfg)),
        }
        return state

    def _round(
        self, history: np.ndarray
    ) -> tuple[int, Optional[float], Optional[dict[str, float]]]:
        """One off-path round: K train steps + next-tick forecast."""
        if self._jax_state is None:
            self._jax_state = self._jax_setup()
        state = self._jax_state
        mean, std = normalization(history)
        normed = (history - mean) / std
        pairs = training_batch(normed, self.seq_len, self.batch, self._np_rng)
        steps = 0
        loss = None
        if pairs is not None:
            for _ in range(self.steps_per_round):
                if self._stopping:
                    return steps, loss, None
                state["params"], state["momentum"], loss_arr = state["step"](
                    state["params"], state["momentum"], pairs)
                steps += 1
            if steps:  # steps_per_round == 0 leaves loss_arr unbound
                loss = float(loss_arr)
        if self._stopping:
            return steps, loss, None
        window = normed[-self.seq_len:][None, ...].astype(np.float32)
        pred = np.asarray(state["forward"](state["params"], window))[0]
        if (loss is not None and not np.isfinite(loss)) \
                or not np.isfinite(pred).all():
            # diverged despite clipping: drop the poisoned params and start
            # clean next round rather than serving NaN gauges
            self._jax_state = None
            raise RuntimeError(
                f"forecaster diverged (loss={loss}); reinitializing")
        real = pred * std + mean
        # rates/gauges cannot be negative; the model can briefly overshoot
        real = np.maximum(real, 0.0)
        forecast = {name: float(v)
                    for name, v in zip(self.feature_names, real)}
        return steps, loss, forecast

    # -- introspection (admin API) -----------------------------------------

    def snapshot(self) -> dict[str, Any]:
        observed = self.ring.latest()
        return {
            "enabled": True,
            "samples": self.ring.count,
            "interval_s": self.interval_s,
            "window": self.seq_len,
            "rounds": self.rounds,
            "trained_steps": self.trained_steps,
            "loss": self.loss,
            "queue_top_k": self.queue_top_k,
            "observed": (
                {name: float(v)
                 for name, v in zip(self.feature_names, observed)}
                if observed is not None else None),
            "forecast": self.forecast,
            "accuracy": self.accuracy(),
            "slot_queues": [
                list(key) if key is not None else None
                for key in self.topk.slot_queues()],
            "updated_at": self.updated_at,
            "error": self.last_error,
        }
