"""Per-entity telemetry: rings, alert engine, health surface, admin routes.

Covers the PR-6 observability subsystem end to end: fixed-slot entity
rings, deterministic alert evaluation with hysteresis, the incremental
broker gauges vs an explicit walk after a mixed workload, readiness
flipping 503 on drain, admin GET/405/404 conventions for the new routes,
opaque 500s, and the 2-node cluster aggregation that lets either node
serve the whole-cluster timeseries view.
"""

import asyncio
import json

import numpy as np
import pytest

from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.telemetry import (
    AlertEngine, AlertRule, EntityRings, QUEUE_FIELDS, TelemetryService,
    default_rules,
)

pytestmark = pytest.mark.asyncio


async def http_req(port: int, path: str, method: str = "GET") -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 20), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(body) if body else {}


# ---------------------------------------------------------------------------
# EntityRings
# ---------------------------------------------------------------------------


def test_entity_rings_lease_retire_drop():
    rings = EntityRings(2, 4, ("a", "b"))
    s1 = rings.lease("q1")
    s2 = rings.lease("q2")
    assert s1 != s2 and len(rings) == 2
    # full: a third entity is dropped (counted), not resized
    assert rings.lease("q3") is None
    assert rings.dropped == 1
    # retire recycles the slot for the next newcomer
    rings.retire("q1")
    assert rings.evicted == 1
    s3 = rings.lease("q3")
    assert s3 == s1 and len(rings) == 2
    # retire_absent sweeps everything not in the live set
    rings.retire_absent({"q3"})
    assert rings.keys() == ["q3"]


def test_entity_rings_series_and_matrices():
    rings = EntityRings(4, 4, ("x", "y"))
    slot = rings.lease("q")
    for i in range(6):  # wraps the 4-tick ring
        rings.push(slot, np.array([i, 10 * i], dtype=np.float32))
    series = rings.series("q", 10)
    # only the newest 4 retained, oldest first
    assert series[:, 0].tolist() == [2.0, 3.0, 4.0, 5.0]
    assert rings.series("q", 2)[:, 0].tolist() == [4.0, 5.0]
    assert rings.series("ghost", 4) is None
    keys, latest = rings.latest_matrix()
    assert keys == ["q"] and latest[0].tolist() == [5.0, 50.0]
    # growth over 2 ticks: 5 - 3
    _, delta = rings.delta_matrix(2)
    assert delta[0, 0] == 2.0
    # single-sample entity reports zero growth, not garbage
    s2 = rings.lease("fresh")
    rings.push(s2, np.array([7.0, 7.0], dtype=np.float32))
    keys, delta = rings.delta_matrix(3)
    assert delta[keys.index("fresh")].tolist() == [0.0, 0.0]


# ---------------------------------------------------------------------------
# alert engine: hysteresis + determinism
# ---------------------------------------------------------------------------


def _drive(engine, series):
    """Feed a synthetic per-tick depth series for one queue; returns the
    flattened event stream."""
    events = []
    rings = EntityRings(4, 16, QUEUE_FIELDS)
    slot = rings.lease(("/", "q"))
    for tick, depth in enumerate(series, start=1):
        vec = np.zeros(len(QUEUE_FIELDS), dtype=np.float32)
        vec[QUEUE_FIELDS.index("depth")] = depth
        rings.push(slot, vec)
        keys, latest = rings.latest_matrix()
        events.extend(engine.evaluate(
            tick, keys, latest, lambda w: rings.delta_matrix(w)[1],
            "node", {}))
    return events


def test_alert_hysteresis_for_and_clear_ticks():
    rule = AlertRule(name="deep", scope="queue", metric="depth",
                     threshold=100.0, for_ticks=3, clear_ticks=2)
    engine = AlertEngine([rule])
    # 2 breach ticks < for_ticks: no fire
    assert _drive(engine, [200, 200, 0, 0]) == []
    # 3 straight breaches fire once; 1 OK tick is not enough to resolve,
    # the second is
    engine = AlertEngine([rule])
    events = _drive(engine, [200, 200, 200, 200, 0, 200, 0, 0])
    kinds = [e["event"] for e in events]
    assert kinds == ["fired", "resolved"]
    assert events[0]["rule"] == "deep" and events[0]["entity"] == "//q"
    assert engine.fired_total == 1 and engine.resolved_total == 1


def test_alert_engine_deterministic_over_same_series():
    series = [0, 50, 300, 300, 300, 0, 0, 0, 120, 400, 400, 0, 0, 0]
    runs = []
    for _ in range(2):
        engine = AlertEngine(default_rules(backlog_growth=100.0))
        runs.append(_drive(engine, series))
    assert runs[0] == runs[1]
    assert any(e["event"] == "fired" for e in runs[0])


def test_alert_engine_rejects_unknown_metric():
    with pytest.raises(ValueError):
        AlertEngine([AlertRule(name="bad", scope="queue",
                               metric="nope", threshold=1.0)])


def test_node_scope_rules_use_probes():
    rule = AlertRule(name="lag", scope="node", metric="loop_lag_ms",
                     threshold=250.0, for_ticks=2, clear_ticks=1)
    engine = AlertEngine([rule])
    events = []
    for tick, lag in enumerate([300, 300, 300, 10], start=1):
        events.extend(engine.evaluate(
            tick, [], np.zeros((0, len(QUEUE_FIELDS)), dtype=np.float32),
            lambda w: np.zeros((0, len(QUEUE_FIELDS)), dtype=np.float32),
            "n1", {"loop_lag_ms": lag}))
    assert [e["event"] for e in events] == ["fired", "resolved"]
    assert events[0]["entity"] == "n1"


# ---------------------------------------------------------------------------
# incremental gauges == explicit walk, after a mixed workload
# ---------------------------------------------------------------------------


def _walk(broker):
    depth = unacked = consumers = 0
    for vhost in broker.vhosts.values():
        for queue in vhost.queues.values():
            depth += len(queue.messages)
            unacked += len(queue.outstanding)
            consumers += len(queue.consumers)
    return depth, unacked, consumers


async def test_incremental_gauges_match_walk():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    try:
        broker = server.broker
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("g1")
        await ch.queue_declare("g2")
        for i in range(20):
            ch.basic_publish(f"m{i}".encode(), routing_key="g1")
        for i in range(5):
            ch.basic_publish(f"n{i}".encode(), routing_key="g2")
        await asyncio.sleep(0.1)
        assert (broker.queue_depth, broker.queue_unacked,
                broker.queue_consumers) == _walk(broker)

        # unacked consumer takes deliveries without settling
        await ch.basic_qos(prefetch_count=8)
        got = asyncio.Event()
        tags = []

        def on_msg(msg):
            tags.append(msg.delivery_tag)
            if len(tags) >= 8:
                got.set()

        await ch.basic_consume("g1", on_msg, consumer_tag="t1")
        await asyncio.wait_for(got.wait(), 5)
        await asyncio.sleep(0.05)
        assert broker.queue_unacked == 8
        assert (broker.queue_depth, broker.queue_unacked,
                broker.queue_consumers) == _walk(broker)

        # ack half, requeue the rest via recover
        for tag in tags[:4]:
            ch.basic_ack(tag)
        await asyncio.sleep(0.05)
        await ch.basic_cancel("t1")
        await ch.basic_recover(requeue=True)
        await asyncio.sleep(0.1)
        assert (broker.queue_depth, broker.queue_unacked,
                broker.queue_consumers) == _walk(broker)

        # purge one queue, delete the other
        await ch.queue_purge("g1")
        await ch.queue_delete("g2")
        await asyncio.sleep(0.05)
        assert (broker.queue_depth, broker.queue_unacked,
                broker.queue_consumers) == _walk(broker)
        await c.close()
        # connection teardown releases everything: gauges return to zero
        await asyncio.sleep(0.1)
        assert (broker.queue_depth, broker.queue_unacked,
                broker.queue_consumers) == _walk(broker)
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# service sampling + payloads
# ---------------------------------------------------------------------------


async def test_service_samples_and_serves_payload():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    try:
        broker = server.broker
        svc = TelemetryService(broker, interval_s=1.0, ring_ticks=16)
        broker.telemetry = svc
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("ts_q")
        svc.sample_tick(1.0)  # baseline before the burst
        for i in range(10):
            ch.basic_publish(b"x", routing_key="ts_q")
        await asyncio.sleep(0.1)
        svc.sample_tick(1.0)

        payload = svc.local_payload(window=8)
        entry = next(q for q in payload["queues"] if q["name"] == "ts_q")
        fields = payload["fields"]["queue"]
        latest = dict(zip(fields, entry["series"][-1]))
        assert latest["depth"] == 10.0
        assert latest["publish_rate"] == 10.0  # 10 msgs over dt=1 s
        assert payload["queues"] and payload["connections"]
        assert payload["health"]["ready"] is True
        # entity count reflects both AMQP queues and the ring stats
        assert payload["stats"]["queues"]["entities"] >= 1

        # gauges merge into the broker metrics snapshot
        snap = broker.metrics_snapshot()
        assert snap["telemetry_queue_entities"] >= 1
        assert snap["telemetry_ticks"] == 2

        # top-K features: busiest queue's (depth, publish_rate) first,
        # zero-padded to 2k
        feats = svc.topk_features(3)
        assert feats.shape == (6,)
        assert feats[0] == 10.0 and feats[1] == 10.0

        # retired connection slots recycle on the next tick
        await c.close()
        await asyncio.sleep(0.05)
        svc.sample_tick(1.0)
        assert len(svc.conns) == 0
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# admin routes: conventions, 404s, readiness 503, opaque 500
# ---------------------------------------------------------------------------


@pytest.fixture
async def telemetry_stack():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    server.broker.telemetry = TelemetryService(
        server.broker, interval_s=1.0, ring_ticks=16)
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    yield server, admin
    await admin.stop()
    await server.stop()


async def test_admin_telemetry_get_and_405(telemetry_stack):
    server, admin = telemetry_stack
    server.broker.telemetry.sample_tick(1.0)
    for path in ("/admin/timeseries", "/admin/health",
                 "/admin/health/live", "/admin/alerts"):
        status, _ = await http_req(admin.bound_port, path)
        assert status == 200, path
        status, body = await http_req(admin.bound_port, path, "POST")
        assert status == 405 and body == {"error": "use GET"}, path

    status, body = await http_req(admin.bound_port, "/admin/timeseries")
    node = server.broker.trace_node
    assert node in body["nodes"]
    assert body["nodes"][node]["fields"]["queue"] == list(QUEUE_FIELDS)
    assert "top_queues" in body

    status, body = await http_req(admin.bound_port, "/admin/alerts")
    assert [r["name"] for r in body["rules"]] == [
        "backlog-growth", "consumer-stall", "replication-lag", "loop-lag",
        "memory-pressure", "control-prearm-stuck", "drain-stuck"]
    assert body["firing"] == []


async def test_admin_timeseries_drilldown_and_404(telemetry_stack):
    server, admin = telemetry_stack
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    ch = await c.channel()
    await ch.queue_declare("drill_q")
    server.broker.telemetry.sample_tick(1.0)

    status, body = await http_req(
        admin.bound_port, "/admin/timeseries/queue/%2F/drill_q")
    assert status == 200
    assert body["vhost"] == "/" and body["name"] == "drill_q"
    assert len(body["series"]) == 1

    status, body = await http_req(
        admin.bound_port, "/admin/timeseries/queue/%2F/no_such_q")
    assert status == 404 and "no telemetry" in body["error"]

    conn_id = next(iter(server.broker.connections)).id
    status, body = await http_req(
        admin.bound_port, f"/admin/timeseries/connection/{conn_id}")
    assert status == 200 and body["id"] == conn_id

    status, body = await http_req(
        admin.bound_port, "/admin/timeseries/connection/999999")
    assert status == 404

    status, body = await http_req(
        admin.bound_port, "/admin/timeseries/connection/notanint")
    assert status == 400

    status, body = await http_req(
        admin.bound_port, "/admin/timeseries?window=banana")
    assert status == 400
    await c.close()


async def test_health_flips_503_on_drain(telemetry_stack):
    server, admin = telemetry_stack
    server.broker.telemetry.sample_tick(1.0)
    status, body = await http_req(admin.bound_port, "/admin/health")
    assert status == 200 and body["ready"] is True

    server.broker.draining = True
    status, body = await http_req(admin.bound_port, "/admin/health")
    assert status == 503 and body["ready"] is False
    assert any("draining" in r for r in body["reasons"])
    assert body["live"] is True  # still alive, just not accepting work
    # liveness endpoint is unaffected by the drain
    status, body = await http_req(admin.bound_port, "/admin/health/live")
    assert status == 200 and body["live"] is True


async def test_admin_telemetry_disabled_409():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        for path in ("/admin/timeseries", "/admin/alerts"):
            status, body = await http_req(admin.bound_port, path)
            assert status == 409 and "telemetry disabled" in body["error"]
        # health still answers without telemetry (drain check only)
        status, body = await http_req(admin.bound_port, "/admin/health")
        assert status == 200 and body["ready"] is True
    finally:
        await admin.stop()
        await server.stop()


async def test_admin_internal_errors_are_opaque(telemetry_stack):
    server, admin = telemetry_stack

    def boom():
        raise RuntimeError("secret /etc/path leaked")

    server.broker.metrics_snapshot = boom
    status, body = await http_req(admin.bound_port, "/admin/metrics")
    assert status == 500
    assert body == {"error": "internal error"}  # no str(exc) leak


# ---------------------------------------------------------------------------
# cluster aggregation: the whole-cluster view from either node
# ---------------------------------------------------------------------------


async def test_cluster_timeseries_served_from_either_node():
    from chanamq_tpu.cluster.node import ClusterNode

    async def start_node(seeds):
        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                           store=MemoryStore())
        await srv.start()
        cl = ClusterNode(srv.broker, "127.0.0.1", 0, seeds,
                         heartbeat_interval_s=0.2, failure_timeout_s=2.0)
        await cl.start()
        srv.broker.telemetry = TelemetryService(
            srv.broker, interval_s=1.0, ring_ticks=16)
        adm = AdminServer(srv.broker, port=0)
        await adm.start()
        return srv, cl, adm

    a = b = None
    try:
        a = await start_node([])
        b = await start_node([a[1].name])
        for _ in range(100):
            if all(len(n[1].membership.alive_members()) == 2 for n in (a, b)):
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("membership did not converge")

        # a queue owned by A, declared and published via A
        qname = next(f"agg{i}" for i in range(200)
                     if a[1].queue_owner("/", f"agg{i}") == a[1].name)
        c = await AMQPClient.connect("127.0.0.1", a[0].bound_port)
        ch = await c.channel()
        await ch.queue_declare(qname)
        for _ in range(6):
            ch.basic_publish(b"x", routing_key=qname)
        await asyncio.sleep(0.1)
        for node in (a, b):
            node[0].broker.telemetry.sample_tick(1.0)

        # B serves the cluster view including A's queue series
        status, body = await http_req(b[2].bound_port, "/admin/timeseries")
        assert status == 200
        assert set(body["nodes"]) == {a[1].name, b[1].name}
        a_queues = {q["name"] for q in body["nodes"][a[1].name]["queues"]}
        assert qname in a_queues
        # and the merged top-K sees it as the busiest queue cluster-wide
        assert any(r["name"] == qname and r["node"] == a[1].name
                   for r in body["top_queues"])

        # per-entity drilldown from B finds the series on A
        status, body = await http_req(
            b[2].bound_port, f"/admin/timeseries/queue/%2F/{qname}")
        assert status == 200 and body["node"] == a[1].name
        assert len(body["series"]) >= 1

        # cluster-scope health from B reports both nodes ready
        status, body = await http_req(
            b[2].bound_port, "/admin/health?scope=cluster")
        assert status == 200
        assert set(body["cluster"]) == {a[1].name, b[1].name}
        assert all(h["ready"] for h in body["cluster"].values())

        # cluster-scope alerts include both nodes
        status, body = await http_req(b[2].bound_port, "/admin/alerts")
        assert status == 200
        assert set(body["cluster"]) == {a[1].name, b[1].name}
        await c.close()
    finally:
        for node in (b, a):
            if node is None:
                continue
            await node[2].stop()
            await node[1].stop()
            await node[0].stop()
