"""Follower-side replication: apply shipped event batches to a warm copy.

A ReplicaCopy mirrors one queue's durable state — the ready-row list, the
unack map, the watermark, and the queue meta — both in memory (for instant
promotion election and materialization) and in the local store under the
replica namespace (so a follower restart doesn't silently forget copies it
acked; see store.api.replica_vhost).

Message blobs are shared with the node's regular store rows by id. The
applier refcounts each blob (one ref per ready row + one per unack entry
naming it) and only deletes a blob at refcount zero if the applier itself
inserted it (`_owned_blobs`): in shared-store deployments the owner's own
blob row is already present and must never be collected from under it.

Gap handling: the owner keeps no shipped-event history, so a follower that
receives a batch whose base is beyond applied+1 buffers it and resyncs
wholesale from the owner's store. All replica store ops are upsert/delete
style, so events at or below the resync snapshot's seq re-apply
idempotently afterwards.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import TYPE_CHECKING

from ..store.api import StoredMessage, StoredQueue, replica_vhost

if TYPE_CHECKING:  # pragma: no cover
    from .log import ReplicationManager

log = logging.getLogger("chanamq.replicate")

_FETCH_CHUNK = 128  # blob ids per repl.fetch round-trip


class ReplicaCopy:
    """One queue's passive copy on a follower node."""

    __slots__ = ("vhost", "name", "owner", "applied_seq", "resyncing",
                 "buffered", "rows", "unacks", "wm", "ttl_ms", "arguments",
                 "meta_written", "peer_acks")

    def __init__(self, vhost: str, name: str, owner: str) -> None:
        self.vhost = vhost
        self.name = name
        self.owner = owner
        self.applied_seq = 0
        self.resyncing = False
        self.buffered: list[dict] = []      # batches parked during resync/gap
        # offset -> (msg_id, body_size, expire_at_ms): the ready rows
        self.rows: dict[int, tuple[int, int, object]] = {}
        # msg_id -> (offset, body_size, expire_at_ms): in-flight deliveries
        self.unacks: dict[int, tuple[int, int, object]] = {}
        self.wm = 0
        self.ttl_ms = None
        self.arguments: dict = {}
        self.meta_written = False
        self.peer_acks: dict[str, int] = {}  # owner's last shipped ack map


class ReplicaApplier:
    def __init__(self, manager: "ReplicationManager") -> None:
        self.manager = manager
        self.copies: dict[tuple[str, str], ReplicaCopy] = {}
        self._blob_refs: dict[int, int] = {}
        self._owned_blobs: set[int] = set()

    @property
    def _store(self):
        return self.manager.broker.store

    def _bg(self, aw) -> None:
        self.manager.broker.store_bg(aw)

    # ------------------------------------------------------------------
    # RPC entry point
    # ------------------------------------------------------------------

    async def h_probe(self, payload: dict) -> dict:
        """Sync probe for the graceful-handoff gate: report how far this
        node's copy of a queue has applied (−1: no copy for that owner)."""
        copy = self.copies.get((str(payload["vhost"]),
                                str(payload["queue"])))
        if copy is None or copy.owner != str(payload.get("owner") or ""):
            return {"applied": -1}
        return {"applied": copy.applied_seq, "resyncing": copy.resyncing}

    async def h_retire(self, payload: dict) -> dict:
        """The owner dropped this node from a queue's follower set (ring
        reshuffle on join/leave): discard the copy. It would never see
        another ship, so keeping it is not redundancy — it is a stale
        ack map waiting to split a future failover election."""
        key = (str(payload["vhost"]), str(payload["queue"]))
        copy = self.copies.get(key)
        if copy is None or copy.owner != str(payload.get("owner") or ""):
            return {"retired": False}
        self._discard(copy)
        return {"retired": True}

    async def h_append(self, payload: dict) -> dict:
        vhost = str(payload["vhost"])
        name = str(payload["queue"])
        owner = str(payload["owner"])
        key = (vhost, name)
        epoch = int(payload.get("epoch") or 0)
        node = self.manager.node
        known = node.queue_epoch(vhost, name)
        if epoch and known > epoch:
            # fenced: the shipper lost holdership (drain/handoff bumped the
            # epoch) but doesn't know yet — a partitioned ex-owner must not
            # graft its stale history onto the copy of the queue's new life
            node.broker.metrics.lifecycle_stale_epoch_refused += 1
            log.warning("%s: refused stale-epoch ship of %s/%s from %s "
                        "(epoch %d < %d)", node.name, vhost, name, owner,
                        epoch, known)
            return {"applied": 0, "refused": True}
        copy = self.copies.get(key)
        if copy is not None and copy.owner != owner:
            # the queue moved (promotion elsewhere, or a delete+redeclare
            # landing on a new owner): the old copy's history is dead
            self._discard(copy)
            copy = None
        if copy is None:
            copy = ReplicaCopy(vhost, name, owner)
            self.copies[key] = copy
        copy.peer_acks = dict(payload.get("acks") or {})
        if copy.resyncing:
            copy.buffered.append(payload)
            return {"applied": copy.applied_seq}
        base = int(payload["base"])
        if base > copy.applied_seq + 1:
            copy.buffered.append(payload)
            self._start_resync(copy)
            return {"applied": copy.applied_seq}
        await self._apply_events(copy, payload["events"])
        return {"applied": copy.applied_seq}

    # ------------------------------------------------------------------
    # event application
    # ------------------------------------------------------------------

    async def _apply_events(self, copy: ReplicaCopy, events: list) -> None:
        key = (copy.vhost, copy.name)
        for event in events:
            if self.copies.get(key) is not copy:
                return  # a delete event discarded the copy mid-batch
            seq = int(event["s"])
            if seq <= copy.applied_seq:
                continue  # idempotent replay past a resync snapshot
            ok = await self._apply(copy, str(event["op"]), event)
            if ok is False:
                self._start_resync(copy)
                return
            copy.applied_seq = seq
            self.manager.metrics.repl_events_applied += 1

    async def _apply(self, copy: ReplicaCopy, op: str, ev: dict):
        rv = replica_vhost(copy.vhost)
        store = self._store
        if op == "enqueue":
            if ev.get("body") is None:
                # a fanout sibling passivated the shared body before we got
                # the event: the blob lives only in the owner's store now
                return False
            self._write_meta_if_new(copy)
            mid = int(ev["m"])
            await self._ensure_blob(
                mid, ev.get("props"), ev["body"], str(ev.get("ex") or ""),
                str(ev.get("rk") or ""), ev.get("ttl"))
            off = int(ev["o"])
            copy.rows[off] = (mid, int(ev["z"]), ev.get("e"))
            self._ref(mid)
            self._bg(store.insert_queue_msg(
                rv, copy.name, off, mid, int(ev["z"]), ev.get("e")))
        elif op == "row_add":
            # requeue re-insert: the blob is already resident (its unack
            # entry holds a ref; the owner ships row_add before unack_del)
            mid = int(ev["m"])
            if mid not in self._blob_refs:
                return False
            self._write_meta_if_new(copy)
            off = int(ev["o"])
            copy.rows[off] = (mid, int(ev["z"]), ev.get("e"))
            self._ref(mid)
            self._bg(store.insert_queue_msg(
                rv, copy.name, off, mid, int(ev["z"]), ev.get("e")))
        elif op == "unacks":
            self._write_meta_if_new(copy)
            batch = []
            for mid, off, z, e in ev.get("rows") or []:
                mid = int(mid)
                if mid not in self._blob_refs:
                    return False  # delivery of a row we never saw
                copy.unacks[mid] = (int(off), int(z), e)
                self._ref(mid)
                batch.append((mid, int(off), int(z), e))
            if batch:
                self._bg(store.insert_queue_unacks(rv, copy.name, batch))
        elif op == "unack_del":
            ids = [int(i) for i in ev.get("ids") or []]
            dropped = [i for i in ids if copy.unacks.pop(i, None) is not None]
            if dropped:
                self._bg(store.delete_queue_unacks(rv, copy.name, dropped))
                for mid in dropped:
                    self._unref(mid)
        elif op == "row_del":
            offs = [int(o) for o in ev.get("offs") or []]
            gone = [copy.rows.pop(o) for o in offs if o in copy.rows]
            if gone:
                self._bg(store.delete_queue_msgs_offsets(rv, copy.name, offs))
                for mid, _z, _e in gone:
                    self._unref(mid)
        elif op == "watermark":
            # moves both ways: dispatch advances it, a requeue rewinds it
            # (store semantics make rewind a pure meta update — the delete
            # of rows <= wm just covers fewer rows)
            wm = int(ev["wm"])
            if wm > copy.wm:
                stale = [o for o in copy.rows if o <= wm]
                for off in stale:
                    mid, _z, _e = copy.rows.pop(off)
                    self._unref(mid)
            copy.wm = wm
            self._write_meta_if_new(copy)
            self._bg(store.update_queue_last_consumed(rv, copy.name, wm))
        elif op == "purge":
            for mid, _z, _e in copy.rows.values():
                self._unref(mid)
            copy.rows.clear()
            self._bg(store.purge_queue_msgs(rv, copy.name))
        elif op == "meta":
            copy.ttl_ms = ev.get("ttl")
            try:
                copy.arguments = json.loads(ev.get("args") or "{}")
            except ValueError:
                copy.arguments = {}
            if int(ev.get("backlog") or 0) > 0 and not copy.rows \
                    and not copy.unacks:
                # the queue predates this log binding (or predates us as a
                # follower): the event stream alone can't rebuild it
                return False
            if int(ev.get("wm") or 0) > copy.wm:
                copy.wm = int(ev["wm"])
            self._write_meta(copy)
        elif op == "delete":
            self._discard(copy)
        else:
            log.warning("unknown replication op %r for %s/%s",
                        op, copy.vhost, copy.name)
        return True

    # ------------------------------------------------------------------
    # blob refcounting
    # ------------------------------------------------------------------

    async def _ensure_blob(self, mid, props, body, exchange, routing_key,
                           ttl_ms) -> None:
        if mid in self._blob_refs:
            return
        existing = await self._store.select_message_metas([mid])
        if mid in existing:
            # shared-store deployment: the owner's row is already visible
            # here — reference it, never own (and never delete) it
            self._blob_refs.setdefault(mid, 0)
            return
        self._bg(self._store.insert_message(StoredMessage(
            id=mid, properties_raw=props or b"", body=body,
            exchange=exchange, routing_key=routing_key,
            refer_count=1, ttl_ms=ttl_ms)))
        self._owned_blobs.add(mid)
        self._blob_refs.setdefault(mid, 0)

    def _ref(self, mid: int) -> None:
        self._blob_refs[mid] = self._blob_refs.get(mid, 0) + 1

    def _unref(self, mid: int) -> None:
        n = self._blob_refs.get(mid, 0) - 1
        if n > 0:
            self._blob_refs[mid] = n
            return
        self._blob_refs.pop(mid, None)
        if mid in self._owned_blobs:
            self._owned_blobs.discard(mid)
            self._bg(self._store.delete_message(mid))

    def _release_blob(self, mid: int) -> None:
        """Drop tracking without deleting: promotion moved the blob's
        ownership to the live queue."""
        self._blob_refs.pop(mid, None)
        self._owned_blobs.discard(mid)

    # ------------------------------------------------------------------
    # replica-namespace meta
    # ------------------------------------------------------------------

    def _write_meta_if_new(self, copy: ReplicaCopy) -> None:
        if not copy.meta_written:
            self._write_meta(copy)

    def _write_meta(self, copy: ReplicaCopy) -> None:
        # MemoryStore row writes silently no-op without a meta row, so this
        # must land (same FIFO) before the first row write
        self._bg(self._store.insert_queue_meta(StoredQueue(
            vhost=replica_vhost(copy.vhost), name=copy.name, durable=True,
            ttl_ms=copy.ttl_ms, last_consumed=copy.wm,
            arguments=dict(copy.arguments))))
        copy.meta_written = True

    # ------------------------------------------------------------------
    # teardown / promotion handoff
    # ------------------------------------------------------------------

    def _discard(self, copy: ReplicaCopy) -> None:
        """Queue deleted (or copy superseded): unreference everything,
        collecting owned blobs, and drop the replica-namespace rows."""
        for mid, _z, _e in copy.rows.values():
            self._unref(mid)
        for mid in copy.unacks:
            self._unref(mid)
        copy.rows.clear()
        copy.unacks.clear()
        copy.buffered.clear()
        self._bg(self._store.delete_queue(replica_vhost(copy.vhost),
                                          copy.name))
        self.copies.pop((copy.vhost, copy.name), None)

    def release_copy(self, key: tuple[str, str]) -> None:
        """Promotion handoff: stop tracking the copy WITHOUT deleting its
        blobs — they now back the live queue's rows."""
        copy = self.copies.pop(key, None)
        if copy is None:
            return
        for mid, _z, _e in copy.rows.values():
            self._release_blob(mid)
        for mid in copy.unacks:
            self._release_blob(mid)
        self._bg(self._store.delete_queue(replica_vhost(copy.vhost),
                                          copy.name))

    # ------------------------------------------------------------------
    # resync
    # ------------------------------------------------------------------

    def _start_resync(self, copy: ReplicaCopy) -> None:
        if copy.resyncing:
            return
        copy.resyncing = True
        asyncio.get_event_loop().create_task(self._resync(copy))

    async def _resync(self, copy: ReplicaCopy) -> None:
        from ..cluster.rpc import RpcError, RpcTimeout

        key = (copy.vhost, copy.name)
        mgr = self.manager
        self.manager.metrics.repl_resyncs += 1
        try:
            client = mgr.client_for(copy.owner)
            snap = await client.call(
                "repl.resync", {"vhost": copy.vhost, "queue": copy.name},
                timeout_s=max(5.0, mgr.ack_timeout_s))
            rows = [tuple(r) for r in snap.get("rows") or []]
            while snap.get("more"):
                after = rows[-1][0] if rows else 0
                snap_more = await client.call(
                    "repl.rows",
                    {"vhost": copy.vhost, "queue": copy.name, "after": after},
                    timeout_s=max(5.0, mgr.ack_timeout_s))
                page = [tuple(r) for r in snap_more.get("rows") or []]
                if not page:
                    break
                rows.extend(page)
                snap["more"] = snap_more.get("more")
            unacks = {int(m): (int(o), int(z), e)
                      for m, o, z, e in snap.get("unacks") or []}
            need = {int(r[1]) for r in rows} | set(unacks)
            missing = sorted(
                mid for mid in need if mid not in self._blob_refs)
            if missing:
                local = await self._store.select_message_metas(missing)
                missing = [m for m in missing if m not in local]
                for m in need:
                    if m in local:
                        self._blob_refs.setdefault(m, 0)  # shared store
            for i in range(0, len(missing), _FETCH_CHUNK):
                chunk = missing[i:i + _FETCH_CHUNK]
                got = await client.call(
                    "repl.fetch", {"ids": chunk},
                    timeout_s=max(5.0, mgr.ack_timeout_s))
                for mid, props, body, ex, rk, ttl in got.get("msgs") or []:
                    mid = int(mid)
                    self._bg(self._store.insert_message(StoredMessage(
                        id=mid, properties_raw=props or b"", body=body or b"",
                        exchange=str(ex or ""), routing_key=str(rk or ""),
                        refer_count=1, ttl_ms=ttl)))
                    self._owned_blobs.add(mid)
                    self._blob_refs.setdefault(mid, 0)
            if self.copies.get(key) is not copy:
                return  # deleted while we were syncing
            # install: swap the old state's refs for the snapshot's
            for mid, _z, _e in copy.rows.values():
                self._unref(mid)
            for mid in copy.unacks:
                self._unref(mid)
            copy.rows = {int(o): (int(m), int(z), e) for o, m, z, e in rows}
            copy.unacks = unacks
            copy.wm = int(snap.get("wm") or 0)
            copy.ttl_ms = snap.get("ttl")
            try:
                copy.arguments = json.loads(snap.get("args") or "{}")
            except ValueError:
                copy.arguments = {}
            for mid, _z, _e in copy.rows.values():
                self._ref(mid)
            for mid in copy.unacks:
                self._ref(mid)
            copy.applied_seq = int(snap.get("seq") or 0)
            copy.meta_written = False
            self._write_meta(copy)
            rv = replica_vhost(copy.vhost)
            self._bg(self._store.replace_queue_msgs(
                rv, copy.name,
                [(o, m, z, e) for o, (m, z, e) in sorted(copy.rows.items())]))
            self._bg(self._store.replace_queue_unacks(
                rv, copy.name,
                [(m, o, z, e) for m, (o, z, e) in copy.unacks.items()]))
            log.info("resynced replica %s/%s from %s at seq %d "
                     "(%d rows, %d unacks)", copy.vhost, copy.name,
                     copy.owner, copy.applied_seq, len(copy.rows),
                     len(copy.unacks))
        except (RpcError, RpcTimeout, OSError) as exc:
            # drop the parked batches: replaying them against stale state
            # would immediately re-trigger resync in a tight loop; the next
            # live batch gap-detects and retries instead
            copy.buffered.clear()
            log.warning("resync of %s/%s from %s failed: %r",
                        copy.vhost, copy.name, copy.owner, exc)
        except Exception:
            copy.buffered.clear()
            log.exception("resync of %s/%s from %s failed",
                          copy.vhost, copy.name, copy.owner)
        finally:
            copy.resyncing = False
            buffered, copy.buffered = copy.buffered, []
            gapped = False
            for payload in sorted(buffered,
                                  key=lambda p: int(p.get("base") or 0)):
                if self.copies.get(key) is not copy:
                    break
                base = int(payload.get("base") or 0)
                if base > copy.applied_seq + 1:
                    gapped = True
                    copy.buffered.append(payload)
                    continue
                await self._apply_events(copy, payload.get("events") or [])
            if gapped and self.copies.get(key) is copy \
                    and not copy.resyncing:
                self._start_resync(copy)
