"""ctypes bindings for the native hot paths (native/chanamq_native.cpp).

Load order: (1) the library pip built at install time
(chanamq_tpu/_chanamq_native*.so, see setup.py), (2) a repo checkout's
native/libchanamq_native.so, compiled on first use when a C++ toolchain is
present. Falls back silently (callers keep the pure-Python implementations)
when no library can be found or built, or CHANAMQ_NATIVE=0.

Exposes:
  NativeFrameParser   — drop-in for amqp.frame.FrameParser; batches also
                        carry fused-publish triple marks (chana_scan_publish)
  NativeTopicMatcher  — drop-in for broker.matchers.TopicMatcher
  NativeEgressEncoder — batch basic.deliver encode into pooled native
                        buffers (chana_encode_deliveries + chana_pool_*)
"""

from __future__ import annotations

import ctypes
import glob
import logging
import os
import struct
import subprocess
import time
from typing import Iterator, Optional

from . import profile
from .amqp.constants import ErrorCode
from .amqp.frame import Frame, FrameError
from .broker.matchers import Matcher

log = logging.getLogger("chanamq.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libchanamq_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
# the loaded library carries the fused-pipeline entry points (scan_publish /
# encode_deliveries / pool). False for a stale pip-built lib predating them:
# frame scan + trie still run native, the pipeline extras fall back.
_has_pipeline = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "chanamq_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception as exc:
        log.info("native build unavailable: %r", exc)
        return False


def _find_lib() -> Optional[str]:
    src = os.path.join(_NATIVE_DIR, "chanamq_native.cpp")
    # (1) library built by pip at install time, sitting inside the package —
    # unless a repo checkout's source is newer (editable-install dev loop:
    # a stale pip build must not shadow edited native code)
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    installed = sorted(glob.glob(os.path.join(pkg_dir, "_chanamq_native*.so")))
    if installed and not (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(installed[0])):
        return installed[0]
    # (2) repo checkout: make-on-demand in native/
    needs_build = not os.path.exists(_LIB_PATH) or (
        os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH))
    if needs_build and not _build():
        return None
    return _LIB_PATH


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on demand. None when unavailable."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None
    _load_attempted = True
    if os.environ.get("CHANAMQ_NATIVE", "1") in ("0", "false", "no"):
        return None
    lib_path = _find_lib()
    if lib_path is None:
        return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError as exc:
        log.info("native lib load failed: %r", exc)
        return None
    lib.chana_scan_frames.restype = ctypes.c_int
    lib.chana_scan_frames.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.chana_trie_new.restype = ctypes.c_void_p
    lib.chana_trie_free.argtypes = [ctypes.c_void_p]
    lib.chana_trie_bind.restype = ctypes.c_int
    lib.chana_trie_bind.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
    lib.chana_trie_unbind.restype = ctypes.c_int
    lib.chana_trie_unbind.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
    lib.chana_trie_route.restype = ctypes.c_int
    lib.chana_trie_route.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.chana_trie_size.restype = ctypes.c_int
    lib.chana_trie_size.argtypes = [ctypes.c_void_p]
    global _has_pipeline
    try:
        _setup_pipeline_signatures(lib)
        _has_pipeline = True
    except AttributeError:
        log.info("native lib predates the fused pipeline entry points; "
                 "scan/trie stay native, encode/pool fall back")
    _lib = lib
    log.info("native hot paths loaded from %s", lib_path)
    return _lib


def _setup_pipeline_signatures(lib: ctypes.CDLL) -> None:
    lib.chana_scan_publish.restype = ctypes.c_int
    lib.chana_scan_publish.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.chana_encode_deliveries.restype = ctypes.c_int64
    lib.chana_encode_deliveries.argtypes = [
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.chana_encode_deliveries_packed.restype = ctypes.c_int64
    lib.chana_encode_deliveries_packed.argtypes = [
        ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.chana_pool_new.restype = ctypes.c_void_p
    lib.chana_pool_new.argtypes = [ctypes.c_int64, ctypes.c_int32]
    lib.chana_pool_destroy.argtypes = [ctypes.c_void_p]
    lib.chana_pool_acquire.restype = ctypes.c_int32
    lib.chana_pool_acquire.argtypes = [ctypes.c_void_p]
    lib.chana_pool_release.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.chana_pool_buf.restype = ctypes.c_void_p
    lib.chana_pool_buf.argtypes = [ctypes.c_void_p, ctypes.c_int32]


def available() -> bool:
    return load() is not None


def pipeline_available() -> bool:
    """True when the loaded library has the fused-pipeline entry points."""
    return load() is not None and _has_pipeline


_MAX_FRAMES_PER_SCAN = 4096


class NativeFrameParser:
    """Drop-in FrameParser backed by the C scanner: one native call per read
    chunk instead of a Python loop per frame."""

    __slots__ = ("frame_max", "_buf", "_dead", "_lib", "_scan_publish",
                 "_types", "_channels", "_offsets", "_lengths",
                 "_pub_mark", "_body_off", "_body_len",
                 "_consumed", "_error")

    def __init__(self, frame_max: int = 0) -> None:
        self.frame_max = frame_max
        self._buf = bytearray()
        self._dead = False
        self._lib = load()
        assert self._lib is not None, "native library unavailable"
        self._scan_publish = _has_pipeline
        self._types = (ctypes.c_int32 * _MAX_FRAMES_PER_SCAN)()
        self._channels = (ctypes.c_int32 * _MAX_FRAMES_PER_SCAN)()
        self._offsets = (ctypes.c_int64 * _MAX_FRAMES_PER_SCAN)()
        self._lengths = (ctypes.c_int64 * _MAX_FRAMES_PER_SCAN)()
        # fused-publish triple marks (chana_scan_publish); stay all-zero —
        # "no fusable publish" — when the lib predates the pipeline
        self._pub_mark = (ctypes.c_int32 * _MAX_FRAMES_PER_SCAN)()
        self._body_off = (ctypes.c_int64 * _MAX_FRAMES_PER_SCAN)()
        self._body_len = (ctypes.c_int64 * _MAX_FRAMES_PER_SCAN)()
        self._consumed = ctypes.c_int64()
        self._error = ctypes.c_int32()

    def scan_batches(self, data: bytes) -> Iterator[tuple | FrameError]:
        """Scan a read chunk into frame-index batches WITHOUT creating Frame
        objects: yields ``(raw, n, types, channels, offsets, lengths,
        pub_mark, body_off, body_len)`` tuples (the arrays are reused
        between yields — consume a batch fully before advancing), then a
        FrameError if the stream is corrupt. pub_mark[i] > 0 marks a frame
        that starts a complete Basic.Publish triple the native scanner
        already validated (2 = empty body, 3 = single body frame at
        body_off/body_len). The connection hot loop walks the arrays
        directly; feed() adapts them to Frame objects for everything
        else."""
        if self._dead:
            return
        # One buffer->bytes conversion per call (NOT per scan pass — a
        # per-pass copy would be O(n^2) when a backlog accumulates); the
        # rare >_MAX_FRAMES_PER_SCAN continuation slices off the consumed
        # prefix, amortized O(1) per byte.
        if self._buf:
            self._buf += data
            raw = bytes(self._buf)
            self._buf = bytearray()
        else:
            raw = bytes(data)
        while True:
            # batch-granular cost ledger: one stamp pair per scan pass (up
            # to _MAX_FRAMES_PER_SCAN frames), accumulated inside the lazy
            # generator so the native call itself is what gets timed
            prof = profile.ACTIVE
            t_prof = time.perf_counter_ns() if prof is not None else 0
            if self._scan_publish:
                n = self._lib.chana_scan_publish(
                    raw, len(raw), self.frame_max,
                    self._types, self._channels, self._offsets,
                    self._lengths, self._pub_mark, self._body_off,
                    self._body_len,
                    _MAX_FRAMES_PER_SCAN, ctypes.byref(self._consumed),
                    ctypes.byref(self._error))
            else:
                n = self._lib.chana_scan_frames(
                    raw, len(raw), self.frame_max,
                    self._types, self._channels, self._offsets,
                    self._lengths,
                    _MAX_FRAMES_PER_SCAN, ctypes.byref(self._consumed),
                    ctypes.byref(self._error))
            if prof is not None and n:
                prof.stage_ns[profile.INGRESS_PARSE] += (
                    time.perf_counter_ns() - t_prof)
                prof.stage_calls[profile.INGRESS_PARSE] += n
            if n:
                yield (raw, n, self._types, self._channels,
                       self._offsets, self._lengths,
                       self._pub_mark, self._body_off, self._body_len)
            consumed = self._consumed.value
            error = self._error.value
            if error:
                self._dead = True
                if error == 1:
                    yield FrameError(ErrorCode.FRAME_ERROR,
                                     "unknown frame type")
                elif error == 2:
                    yield FrameError(
                        ErrorCode.FRAME_ERROR,
                        f"frame exceeds negotiated frame-max {self.frame_max}")
                else:
                    yield FrameError(ErrorCode.FRAME_ERROR,
                                     "missing frame-end octet")
                return
            if n < _MAX_FRAMES_PER_SCAN:
                if consumed < len(raw):
                    self._buf = bytearray(raw[consumed:])
                return
            raw = raw[consumed:]

    def feed(self, data: bytes) -> Iterator[Frame | FrameError]:
        for batch in self.scan_batches(data):
            if isinstance(batch, FrameError):
                yield batch
                return
            raw, n, types, channels, offsets, lengths = batch[:6]
            for i in range(n):
                off = offsets[i]
                yield Frame(types[i], channels[i], raw[off:off + lengths[i]])


class NativeTopicMatcher(Matcher):
    """Drop-in TopicMatcher routing through the C++ trie. The (pattern,
    queue) registry stays Python-side for bindings()/recovery; the trie is
    the routing fast path."""

    def __init__(self) -> None:
        lib = load()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.chana_trie_new())
        self._queue_ids: dict[str, int] = {}
        self._queue_names: dict[int, str] = {}
        self._next_id = 1
        self._patterns: dict[tuple[str, str], int] = {}
        self.binding_table = self._patterns
        # per-queue key index: queue -> its bound patterns, so unbind_queue
        # (mass teardown, 10k-tenant churn) walks its OWN bindings instead
        # of scanning every (key, queue) pair in the exchange
        self._queue_keys: dict[str, set[str]] = {}
        self._out = (ctypes.c_int32 * 4096)()

    def __del__(self) -> None:  # pragma: no cover
        try:
            if self._handle:
                self._lib.chana_trie_free(self._handle)
        except Exception:
            pass

    def _queue_id(self, queue: str) -> int:
        qid = self._queue_ids.get(queue)
        if qid is None:
            qid = self._next_id
            self._next_id += 1
            self._queue_ids[queue] = qid
            self._queue_names[qid] = queue
        return qid

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if (key, queue) in self._patterns:
            return False
        self._patterns[(key, queue)] = 1
        self._queue_keys.setdefault(queue, set()).add(key)
        self._lib.chana_trie_bind(
            self._handle, key.encode(), self._queue_id(queue))
        return True

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if self._patterns.pop((key, queue), None) is None:
            return False
        keys = self._queue_keys.get(queue)
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._queue_keys[queue]
        self._lib.chana_trie_unbind(
            self._handle, key.encode(), self._queue_id(queue))
        return True

    def unbind_queue(self, queue: str) -> int:
        # O(own bindings): pop the queue's key set up front (unbind's
        # discard then runs against the popped set, a safe no-op miss)
        keys = self._queue_keys.pop(queue, None)
        if not keys:
            return 0
        for key in keys:
            self.unbind(key, queue)
        return len(keys)

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        kb = key.encode()
        n = self._lib.chana_trie_route(self._handle, kb, self._out, len(self._out))
        while n > len(self._out):
            # returned count is the TOTAL match count: grow and re-route
            # instead of silently truncating at the buffer size
            self._out = (ctypes.c_int32 * max(n, len(self._out) * 2))()
            n = self._lib.chana_trie_route(
                self._handle, kb, self._out, len(self._out))
        return {self._queue_names[self._out[i]] for i in range(n)}

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        return [(k, q, None) for (k, q) in sorted(self._patterns)]

    def is_empty(self) -> bool:
        return not self._patterns


# per-record meta header of the packed encode blob; must mirror the layout
# chana_encode_deliveries_packed reads — canonical definition lives next to
# the pure-Python renderer in amqp.frame (imported late: this module loads
# before the package's broker imports settle)
from .amqp.frame import ENC_META as _ENC_META  # noqa: E402


class NativeEgressEncoder:
    """Batch basic.deliver encode into a native buffer pool.

    One ``chana_encode_deliveries`` call renders a whole dispatch pass's
    deliveries (method + content-header + split body frames, byte-identical
    to ServerChannel._render_deliver) into one contiguous buffer drawn from
    a reusable native arena — steady-state delivery allocates zero Python
    bytes per message. Buffers are handed to the connection writer as
    memoryview slices and returned to the pool once the kernel write
    completes (slot -1 = pool exhausted or batch oversized: the encode
    landed in a fresh bytearray instead, nothing to release).

    Single event-loop-thread use only (like everything else on the broker
    data plane): acquire/encode happen in dispatch, release in the writer
    task, both on the loop thread.
    """

    def __init__(self, pool_buffers: int = 16,
                 pool_buffer_bytes: int = 256 * 1024) -> None:
        lib = load()
        assert lib is not None and _has_pipeline, "native pipeline unavailable"
        self._lib = lib
        self.pool_buffers = pool_buffers
        self.buf_bytes = pool_buffer_bytes
        self._pool = ctypes.c_void_p(
            lib.chana_pool_new(pool_buffer_bytes, pool_buffers))
        # each arena slot wrapped ONCE as a writable view; encode() hands
        # out zero-copy slices of these
        self._views: list[memoryview] = []
        self._ptrs: list = []
        for slot in range(pool_buffers):
            ptr = lib.chana_pool_buf(self._pool, slot)
            arr = (ctypes.c_ubyte * pool_buffer_bytes).from_address(ptr)
            self._views.append(memoryview(arr))
            self._ptrs.append(ctypes.cast(
                ctypes.c_void_p(ptr), ctypes.POINTER(ctypes.c_uint8)))

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        try:
            self._views.clear()
            if self._pool:
                self._lib.chana_pool_destroy(self._pool)
                self._pool = ctypes.c_void_p()
        except Exception:
            pass

    def encode_packed(self, parts: list, n: int, frame_max: int,
                      nbytes: int):
        """Encode a pre-packed parts list (the connection's egress buffer:
        ``meta, prefix, exrk, header, body`` per record, meta from
        ``_ENC_META``) into one wire buffer of exactly ``nbytes``. One
        b"".join and one lib call per batch — the per-record marshalling
        happened incrementally at egress_deliver time. Returns the same
        ``(buffer, slot)`` / None contract as encode()."""
        blob = b"".join(parts)
        slot = -1
        if nbytes <= self.buf_bytes:
            slot = self._lib.chana_pool_acquire(self._pool)
        if slot >= 0:
            view = self._views[slot]
            out = self._ptrs[slot]
            written = self._lib.chana_encode_deliveries_packed(
                n, blob, len(blob), frame_max, out, self.buf_bytes)
            if written != nbytes:
                self._lib.chana_pool_release(self._pool, slot)
                return None
            return view[:nbytes], slot
        heap = bytearray(nbytes)
        out = (ctypes.c_uint8 * nbytes).from_buffer(heap)
        written = self._lib.chana_encode_deliveries_packed(
            n, blob, len(blob), frame_max, out, nbytes)
        del out  # drop the exported buffer so the bytearray is usable
        if written != nbytes:
            return None
        return heap, -1

    def encode(self, records: list, frame_max: int, nbytes: int):
        """Encode ``(channel_id, prefix, tag, redelivered, exrk, header,
        body)`` records into one wire buffer of exactly ``nbytes`` (the
        caller pre-computed the wire size). Returns ``(buffer, slot)`` —
        a pooled memoryview slice (release(slot) after the kernel write)
        or a fresh bytearray with slot -1 — or None if the native encode
        disagreed with the expected size (caller falls back to Python
        rendering; defensive, never expected)."""
        # one packed meta+payload blob per batch: a single c_char_p
        # conversion at the call boundary (per-element c_char_p stores
        # cost more than the whole Python fallback encode)
        pack = _ENC_META.pack
        parts = []
        for cid, prefix, tag, red, exrk, header, body in records:
            parts += (
                pack(cid, tag, 1 if red else 0, len(prefix), len(exrk),
                     len(header), len(body)),
                prefix, exrk, header, body)  # join takes memoryviews too
        return self.encode_packed(parts, len(records), frame_max, nbytes)

    def release(self, slot: int) -> None:
        self._lib.chana_pool_release(self._pool, slot)


_EGRESS_ENCODER: Optional[NativeEgressEncoder] = None


def egress_encoder(pool_buffers: int = 16,
                   pool_buffer_kb: int = 256) -> Optional[NativeEgressEncoder]:
    """Process-wide encoder + pool singleton (brokers share one loop thread
    per process; the first caller's sizing wins and later callers reuse the
    arena instead of re-allocating it per Broker). None when the native
    pipeline is unavailable or CHANAMQ_NATIVE_EGRESS=0."""
    global _EGRESS_ENCODER
    if not pipeline_available():
        return None
    if os.environ.get("CHANAMQ_NATIVE_EGRESS", "1") in ("0", "false", "no"):
        return None
    if _EGRESS_ENCODER is None:
        _EGRESS_ENCODER = NativeEgressEncoder(
            pool_buffers, pool_buffer_kb * 1024)
    return _EGRESS_ENCODER
