"""Regression tests for broker defects found in review."""

import asyncio

import pytest

from chanamq_tpu.amqp import methods as am
from chanamq_tpu.amqp.frame import Frame
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError

pytestmark = pytest.mark.asyncio


@pytest.fixture
async def server():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


async def test_delete_queue_with_autodelete_exchange_does_not_crash(client):
    """Auto-delete exchange whose last binding dies with the queue: the
    queue delete must complete and the exchange must auto-delete."""
    ch = await client.channel()
    await ch.exchange_declare("auto_ex", "direct", auto_delete=True)
    await ch.queue_declare("only_q")
    await ch.queue_bind("only_q", "auto_ex", "k")
    count = await ch.queue_delete("only_q")  # used to RuntimeError server-side
    assert count == 0
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.exchange_declare("auto_ex", "direct", passive=True)
    assert exc_info.value.reply_code == 404


async def test_client_heartbeat_zero_not_timed_out():
    """A client negotiating heartbeat=0 must not be disconnected while idle,
    even when the server has a (tiny) configured heartbeat."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=1)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port, heartbeat=0)
        # client explicitly asked for heartbeat=0 in tune-ok
        assert c.heartbeat_s == 0
        await asyncio.sleep(2.5)  # > 2x server heartbeat interval
        ch = await c.channel()  # connection must still be alive
        ok = await ch.queue_declare("still_alive")
        assert ok.queue == "still_alive"
        await c.close()
    finally:
        await srv.stop()


async def test_pipelined_commands_after_soft_error_are_discarded(client):
    """Commands already pipelined on a channel that just got a soft
    Channel.Close must be discarded, not escalate to a connection error."""
    ch = await client.channel()
    # two commands in one write: first triggers 404, second is pipelined junk
    client._send_method(ch.id, am.Basic.Get(queue="missing_q"))
    client._send_method(ch.id, am.Queue.Declare(queue="pipelined_q"))
    await asyncio.sleep(0.2)
    assert ch.closed
    assert ch.close_reason.reply_code == 404
    # the connection survived; a fresh channel works
    ch2 = await client.channel()
    ok = await ch2.queue_declare("post_error_q")
    assert ok.queue == "post_error_q"


async def test_client_channel_ids_are_reused(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    try:
        c.channel_max = 8  # tiny budget: without reuse this exhausts fast
        for _ in range(50):
            ch = await c.channel()
            await ch.close()
        assert c._next_channel <= 3
    finally:
        await c.close()


async def test_async_fixture_with_request_param(request):
    """conftest shim must pass `request` through to async fixtures/tests."""
    assert request.node.name == "test_async_fixture_with_request_param"


async def test_confirms_flushed_before_pipelined_channel_close(client):
    """Publishes pipelined immediately ahead of Channel.Close in one TCP
    batch must still be confirmed before the close-ok (review regression:
    deferred coalesced confirms were dropped on close)."""
    ch = await client.channel()
    await ch.confirm_select()
    await ch.queue_declare("pc_q")
    # one write burst: 10 publishes + channel.close, no drain between
    for _ in range(10):
        ch.basic_publish(b"m", routing_key="pc_q")
    close_fut = asyncio.get_event_loop().create_task(ch.close())
    await asyncio.wait_for(close_fut, 5)
    # every publish was confirmed before the channel went away
    assert not ch.unconfirmed


async def test_wait_unconfirmed_wakes_on_close(server):
    """wait_unconfirmed_below must raise promptly when the channel dies,
    not sleep out its timeout."""
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    ch.basic_publish(b"m", exchange="missing_ex", routing_key="x")  # 404 soft error
    t0 = asyncio.get_event_loop().time()
    with pytest.raises((ChannelClosedError, asyncio.TimeoutError)):
        await ch.wait_unconfirmed_below(1, timeout=10)
    assert asyncio.get_event_loop().time() - t0 < 5  # woke early, not at timeout


async def test_nack_multiple_unknown_tag_is_channel_error(client):
    """ADVICE r3: an unknown nonzero tag with multiple=true that resolves no
    deliveries must raise PRECONDITION_FAILED like the single-tag path
    (RabbitMQ errors on unknown nonzero tags regardless of multiple)."""
    ch = await client.channel()
    await ch.queue_declare("nack_q")
    # no deliveries ever issued on this channel: tag 5 is above the range
    client._send_method(ch.id, am.Basic.Nack(
        delivery_tag=5, multiple=True, requeue=True))
    await asyncio.sleep(0.2)
    assert ch.closed
    assert ch.close_reason.reply_code == 406


async def test_ack_multiple_settled_range_is_noop(client):
    """A multiple ack whose covered tags are already settled is a legal
    no-op (tag within the issued range) — only above-range tags error."""
    ch = await client.channel()
    await ch.queue_declare("ack_q")
    ch.basic_publish(b"m1", routing_key="ack_q")
    m = None
    for _ in range(50):
        m = await ch.basic_get("ack_q")
        if m is not None:
            break
        await asyncio.sleep(0.02)
    assert m is not None
    ch.basic_ack(m.delivery_tag)
    # re-ack the same (settled) tag with multiple=true: inside issued range
    client._send_method(ch.id, am.Basic.Ack(
        delivery_tag=m.delivery_tag, multiple=True))
    await asyncio.sleep(0.2)
    assert not ch.closed
    # but an above-range multiple ack errors
    client._send_method(ch.id, am.Basic.Ack(delivery_tag=99, multiple=True))
    await asyncio.sleep(0.2)
    assert ch.closed
    assert ch.close_reason.reply_code == 406


async def test_reject_unknown_tag_is_channel_error(client):
    """Basic.Reject with an unknown tag follows the same RabbitMQ contract
    as Ack/Nack: PRECONDITION_FAILED, not a silent no-op."""
    ch = await client.channel()
    await ch.queue_declare("rej_q")
    client._send_method(ch.id, am.Basic.Reject(delivery_tag=3, requeue=True))
    await asyncio.sleep(0.2)
    assert ch.closed
    assert ch.close_reason.reply_code == 406


async def test_tiny_reads_force_fused_fallback(monkeypatch):
    """Every frame spanning multiple reads must route through the
    assembler fallback of the fused scan loop (connection._consume_scan):
    with 13-byte reads no publish triple is ever contained in one batch,
    and with varied body sizes (0, small, > frame-max) the stateful
    content machine sees every shape. Order and content must survive."""
    from chanamq_tpu.broker.connection import AMQPConnection

    orig = AMQPConnection._read_chunk

    async def tiny_read(self):
        data = await self.reader.read(13)
        if not data:
            return await orig(self)  # raise ConnectionClosed the same way
        self._last_recv = asyncio.get_event_loop().time()
        return data

    monkeypatch.setattr(AMQPConnection, "_read_chunk", tiny_read)
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("tiny_q")
    bodies = [b"", b"x", b"hello world", bytes(range(256)) * 600,  # >128KB
              b"tail-%d" % 7]
    for body in bodies:
        ch.basic_publish(body, routing_key="tiny_q")
    await ch.wait_unconfirmed_below(1, timeout=30)
    got, done = [], asyncio.get_event_loop().create_future()

    def cb(m):
        got.append(m.body)
        ch.basic_ack(m.delivery_tag)
        if len(got) >= len(bodies) and not done.done():
            done.set_result(None)

    await ch.basic_consume("tiny_q", cb)
    await asyncio.wait_for(done, 30)
    assert got == bodies
    await c.close()
    await srv.stop()


async def test_interleaved_channel_content_frames(client):
    """Content frames of two channels interleaved on one connection (legal
    per AMQP §4.2.6 — interleaving is only forbidden WITHIN a channel):
    the fused scan loop must fall back to the per-channel assembler and
    deliver both messages intact."""
    ch1 = await client.channel()
    ch2 = await client.channel()
    await ch1.queue_declare("il_q")
    from chanamq_tpu.amqp.command import AMQCommand

    f1 = AMQCommand(
        ch1.id, am.Basic.Publish(exchange="", routing_key="il_q"),
        body=b"from-ch1").render_frames(client.frame_max)
    f2 = AMQCommand(
        ch2.id, am.Basic.Publish(exchange="", routing_key="il_q"),
        body=b"from-ch2").render_frames(client.frame_max)
    # interleave: m1 m2 h1 h2 b1 b2 — one write so one scan batch sees all
    wire = b"".join(f.to_bytes() for f in
                    (f1[0], f2[0], f1[1], f2[1], f1[2], f2[2]))
    client._write(wire)
    got = []
    for _ in range(100):
        m = await ch1.basic_get("il_q", no_ack=True)
        if m is not None:
            got.append(m.body)
        if len(got) >= 2:
            break
        await asyncio.sleep(0.02)
    assert sorted(got) == [b"from-ch1", b"from-ch2"]


async def test_tiny_negotiated_frame_max_round_trip():
    """frame_max=4096 (near the spec minimum): every large body splits
    into dozens of frames in both directions; reassembly must be exact
    for varied sizes including one spanning ~25 frames."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       frame_max=4096)
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    assert c.frame_max == 4096
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("frag_q")
    bodies = [bytes([i % 256]) * (4000 + i * 997) for i in range(12)]
    bodies.append(bytes(range(256)) * 400)  # 102400 bytes
    got, done = [], asyncio.get_event_loop().create_future()

    def cb(m):
        got.append(m.body)
        ch.basic_ack(m.delivery_tag)
        if len(got) >= len(bodies) and not done.done():
            done.set_result(None)

    await ch.basic_consume("frag_q", cb)
    for body in bodies:
        ch.basic_publish(body, routing_key="frag_q")
    await ch.wait_unconfirmed_below(1)
    await asyncio.wait_for(done, 30)
    assert got == bodies
    await c.close()
    await srv.stop()


async def test_channel_max_enforced():
    """Opening more channels than the negotiated channel-max is refused
    with a connection error; existing channels keep working."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       channel_max=4)
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    chans = [await c.channel() for _ in range(4)]
    with pytest.raises(Exception):
        await c.channel()
    await chans[0].queue_declare("cm_q")
    chans[0].basic_publish(b"ok", routing_key="cm_q")
    m = await chans[0].basic_get("cm_q", no_ack=True)
    assert m is not None and m.body == b"ok"
    await c.close()
    await srv.stop()


async def test_oversized_declared_body_rejected():
    """A content header declaring a body beyond chana.mq.message.max-size
    must close the connection with FRAME_ERROR instead of buffering toward
    it — body chunks accumulate in the assembler BEFORE the memory
    backpressure gauge can see them, so the cap is the only bound
    (reference: FrameParser's message size limit, FrameParser.scala:67-158)."""
    import struct

    def raw_frame(t, ch, payload):
        return struct.pack(">BHI", t, ch, len(payload)) + payload + b"\xce"

    def raw_method(ch, cid, mid, args):
        return raw_frame(1, ch, struct.pack(">HH", cid, mid) + args)

    def sstr(s):
        b = s.encode()
        return bytes([len(b)]) + b

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       max_message_size=1024 * 1024)
    await srv.start()
    r, w = await asyncio.open_connection("127.0.0.1", srv.bound_port)
    w.write(b"AMQP\x00\x00\x09\x01")
    await r.read(4096)
    w.write(raw_method(0, 10, 11, struct.pack(">I", 0) + sstr("PLAIN")
                       + struct.pack(">I", 12) + b"\x00guest\x00guest"
                       + sstr("en_US")))
    await r.read(4096)
    w.write(raw_method(0, 10, 31, struct.pack(">HIH", 100, 131072, 0)))
    w.write(raw_method(0, 10, 40, sstr("/") + sstr("") + b"\x00"))
    await r.read(4096)
    w.write(raw_method(1, 20, 10, sstr("")))
    await r.read(4096)
    w.write(raw_method(1, 50, 10, struct.pack(">H", 0) + sstr("capq")
                       + b"\x00" + struct.pack(">I", 0)))
    await r.read(4096)
    # declare a body one byte over the 1 MiB cap
    w.write(raw_method(1, 60, 40, struct.pack(">H", 0) + sstr("")
                       + sstr("capq") + b"\x00")
            + raw_frame(2, 1, struct.pack(">HHQH", 60, 0,
                                          1024 * 1024 + 1, 0)))
    data = await asyncio.wait_for(r.read(4096), 5)
    assert data[7:11] == struct.pack(">HH", 10, 50)  # connection.close
    assert struct.unpack(">H", data[11:13])[0] == 501  # FRAME_ERROR
    w.close()

    # a body under the cap (over frame_max) is untouched
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("okq")
    ch.basic_publish(bytes(400_000), routing_key="okq")
    m = await ch.basic_get("okq", no_ack=True)
    assert m is not None and len(m.body) == 400_000
    await c.close()
    await srv.stop()


async def test_protocol_state_violations_rejected():
    """Out-of-order protocol moves get the spec's connection errors:
    publish before Connection.Open (503), content on an unopened channel
    (504), content frames on channel 0 (505), unknown class (503) — and
    the broker survives all of them."""
    import struct

    def raw_frame(t, ch, payload):
        return struct.pack(">BHI", t, ch, len(payload)) + payload + b"\xce"

    def raw_method(ch, cid, mid, args):
        return raw_frame(1, ch, struct.pack(">HH", cid, mid) + args)

    def sstr(s):
        b = s.encode()
        return bytes([len(b)]) + b

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    port = srv.bound_port

    async def fresh(do_open=True, open_channel=False):
        r, w = await asyncio.open_connection("127.0.0.1", port)
        w.write(b"AMQP\x00\x00\x09\x01")
        await r.read(4096)
        w.write(raw_method(0, 10, 11, struct.pack(">I", 0) + sstr("PLAIN")
                           + struct.pack(">I", 12) + b"\x00guest\x00guest"
                           + sstr("en_US")))
        await r.read(4096)
        w.write(raw_method(0, 10, 31, struct.pack(">HIH", 100, 131072, 0)))
        if do_open:
            w.write(raw_method(0, 10, 40, sstr("/") + sstr("") + b"\x00"))
            await r.read(4096)
        if open_channel:
            w.write(raw_method(1, 20, 10, sstr("")))
            await r.read(4096)
        return r, w

    async def expect_conn_close(r, code):
        data = await asyncio.wait_for(r.read(4096), 5)
        assert data[7:11] == struct.pack(">HH", 10, 50), data[:16].hex()
        assert struct.unpack(">H", data[11:13])[0] == code

    publish = (raw_method(1, 60, 40, struct.pack(">H", 0) + sstr("")
                          + sstr("x") + b"\x00")
               + raw_frame(2, 1, struct.pack(">HHQH", 60, 0, 1, 0))
               + raw_frame(3, 1, b"z"))

    r, w = await fresh(do_open=False)
    w.write(publish)
    await expect_conn_close(r, 503)  # command-invalid before open
    w.close()

    r, w = await fresh()
    w.write(publish)                 # channel 1 never opened
    await expect_conn_close(r, 504)
    w.close()

    r, w = await fresh()
    w.write(raw_frame(2, 0, struct.pack(">HHQH", 60, 0, 1, 0)))
    await expect_conn_close(r, 505)  # content on channel 0
    w.close()

    r, w = await fresh(open_channel=True)
    w.write(raw_method(1, 99, 10, b""))
    await expect_conn_close(r, 503)  # unknown class
    w.close()

    # broker healthy after every violation
    c = await AMQPClient.connect("127.0.0.1", port)
    ch = await c.channel()
    await ch.queue_declare("ps_q")
    ch.basic_publish(b"ok", routing_key="ps_q")
    assert (await ch.basic_get("ps_q", no_ack=True)).body == b"ok"
    await c.close()
    await srv.stop()


async def test_route_cache_invalidates_on_topology_churn(client):
    """The publish route cache must never serve a stale route: rebinding,
    unbinding, queue deletion and redeclaration mid-flow all take effect on
    the very next publish (topology epoch bump)."""
    ch = await client.channel()
    await ch.exchange_declare("rc_ex", "direct")
    await ch.queue_declare("rc_q1")
    await ch.queue_declare("rc_q2")
    await ch.queue_bind("rc_q1", "rc_ex", "k")

    async def get(q):
        for _ in range(50):
            msg = await ch.basic_get(q, no_ack=True)
            if msg is not None:
                return msg
            await asyncio.sleep(0.01)
        return None

    # warm the cache, then churn
    for _ in range(3):
        ch.basic_publish(b"warm", exchange="rc_ex", routing_key="k")
    await ch.queue_unbind("rc_q1", "rc_ex", "k")
    await ch.queue_bind("rc_q2", "rc_ex", "k")
    ch.basic_publish(b"moved", exchange="rc_ex", routing_key="k")
    assert (await get("rc_q2")).body == b"moved"
    await asyncio.sleep(0.05)
    # q1 got only the warmup messages, not the post-churn one
    bodies = []
    while True:
        m = await ch.basic_get("rc_q1", no_ack=True)
        if m is None:
            break
        bodies.append(m.body)
    assert bodies == [b"warm"] * 3

    # queue deletion invalidates a cached resolved-queue reference
    ch.basic_publish(b"pre-delete", exchange="rc_ex", routing_key="k")
    assert (await get("rc_q2")).body == b"pre-delete"
    await ch.queue_delete("rc_q2")
    ch.basic_publish(b"into-void", exchange="rc_ex", routing_key="k")
    await ch.queue_declare("rc_q2")
    await ch.queue_bind("rc_q2", "rc_ex", "k")
    ch.basic_publish(b"reborn", exchange="rc_ex", routing_key="k")
    assert (await get("rc_q2")).body == b"reborn"

    # default-exchange routes churn with queue lifecycle too
    await ch.queue_declare("rc_dq")
    ch.basic_publish(b"d1", routing_key="rc_dq")
    assert (await get("rc_dq")).body == b"d1"
    await ch.queue_delete("rc_dq")
    await ch.queue_declare("rc_dq")
    ch.basic_publish(b"d2", routing_key="rc_dq")
    assert (await get("rc_dq")).body == b"d2"


async def test_live_server_method_fuzz_stays_healthy():
    """Hostile-input hardening at the METHOD layer (the parser/assembler
    fuzz covers the frame layer): a seeded stream of random method frames —
    real class/method ids with garbage args, unknown ids, wrong-state
    methods, random channels — must only ever produce clean protocol
    closes, never a broker crash; after every hostile connection a fresh
    well-behaved client still gets full service."""
    import random
    import struct

    def raw_frame(t, ch, payload):
        return struct.pack(">BHI", t, ch, len(payload)) + payload + b"\xce"

    def raw_method(ch, cid, mid, args):
        return raw_frame(1, ch, struct.pack(">HH", cid, mid) + args)

    rng = random.Random(0xC0FFEE)
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    port = srv.bound_port

    real_ids = [(10, 10), (10, 40), (20, 10), (20, 20), (40, 10), (40, 30),
                (50, 10), (50, 20), (60, 40), (60, 80), (60, 70), (85, 10),
                (90, 10), (90, 20), (90, 30), (8, 8), (99, 1), (60, 999)]

    async def hostile_session() -> None:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            writer.write(b"AMQP\x00\x00\x09\x01")
            # read Connection.Start, then skip the proper handshake for most
            # sessions: hostile frames straight into every protocol state
            await asyncio.wait_for(reader.readexactly(7), 5)
            if rng.random() < 0.5:
                # complete a minimal handshake half the time so the fuzz
                # also reaches the post-open dispatch states
                hdr = await asyncio.wait_for(reader.read(65536), 1)
                writer.write(raw_method(0, 10, 11,
                    b"\x00\x00\x00\x00" + b"\x05PLAIN"
                    + struct.pack(">I", 4) + b"\x00u\x00p" + b"\x05en_US"))
                writer.write(raw_method(0, 10, 31,
                    struct.pack(">HIH", 0, 131072, 0)))
                writer.write(raw_method(0, 10, 40, b"\x01/\x00\x00"))
                writer.write(raw_method(1, 20, 10, b"\x00"))
                await asyncio.sleep(0.05)
            for _ in range(30):
                cls, mid = rng.choice(real_ids)
                args = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(0, 40)))
                channel = rng.choice([0, 1, 2, 7])
                ftype = rng.choice([1, 1, 1, 2, 3])
                if ftype == 1:
                    writer.write(raw_method(channel, cls, mid, args))
                else:
                    writer.write(raw_frame(ftype, channel, args))
                if rng.random() < 0.3:
                    await asyncio.sleep(0)
            await writer.drain()
            # server may close on us at any point; drain whatever comes
            try:
                await asyncio.wait_for(reader.read(262144), 0.5)
            except asyncio.TimeoutError:
                pass
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    try:
        for round_no in range(12):
            await hostile_session()
            # the broker shrugs it off: full service for a clean client
            c = await AMQPClient.connect("127.0.0.1", port)
            ch = await c.channel()
            await ch.queue_declare("fuzz_ok")
            ch.basic_publish(b"alive-%d" % round_no, routing_key="fuzz_ok")
            got = None
            for _ in range(50):
                got = await ch.basic_get("fuzz_ok", no_ack=True)
                if got is not None:
                    break
                await asyncio.sleep(0.02)
            assert got is not None and got.body == b"alive-%d" % round_no
            await c.close()
    finally:
        await srv.stop()
