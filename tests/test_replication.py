"""Queue replication (chanamq_tpu/replicate/): owner-side log sequencing
and batch framing, follower-side gap-triggered resync, and the end-to-end
failover contract — with chana.mq.replicate.factor=2 + sync=true on
PRIVATE per-node stores (nothing shared), killing the owner mid
publish/consume loses no confirmed persistent message, the surviving
replica promotes, and the consumer resumes."""

import asyncio
import json

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster.hashring import HashRing
from chanamq_tpu.cluster.node import ClusterNode
from chanamq_tpu.replicate import QueueRepLog, ReplicationManager
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.api import replica_vhost
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


# ---------------------------------------------------------------------------
# fakes for unit-level tests (no sockets: the manager/applier only see
# duck-typed node/membership/client objects)
# ---------------------------------------------------------------------------


class FakeBroker:
    def __init__(self):
        self.store = MemoryStore()
        self.metrics = Metrics()
        self.vhosts = {}

    def store_bg(self, aw):
        pass  # MemoryStore writes apply at call time; the handle is inert


class FakeRpc:
    def __init__(self):
        self.handlers = {}

    def register(self, method, handler):
        self.handlers[method] = handler


class FakeMembership:
    def __init__(self, alive):
        self.alive = set(alive)
        self.clients = {}

    def is_alive(self, name):
        return name in self.alive

    def alive_members(self):
        return sorted(self.alive)

    def client(self, name):
        return self.clients[name]


class FakeClient:
    """Records repl.* calls; replies are canned per method."""

    def __init__(self):
        self.calls = []
        self.replies = {}

    async def call(self, method, payload, timeout_s=None):
        self.calls.append((method, payload))
        reply = self.replies.get(method)
        if callable(reply):
            return reply(payload)
        if reply is None:
            raise AssertionError(f"unexpected rpc {method}")
        return reply


class FakeNode:
    def __init__(self, name="n1", alive=("n1", "n2")):
        self.name = name
        self.broker = FakeBroker()
        self.rpc = FakeRpc()
        self.ring = HashRing(list(alive), 8)
        self.membership = FakeMembership(alive)
        self.epochs = {}

    def queue_epoch(self, vhost, name):
        return self.epochs.get((vhost, name), 0)


def make_manager(**kw):
    node = FakeNode()
    kw.setdefault("factor", 2)
    manager = ReplicationManager(node, **kw)
    return node, manager


# ---------------------------------------------------------------------------
# unit: log sequencing
# ---------------------------------------------------------------------------


async def test_log_sequencing_and_lag():
    node, manager = make_manager()
    log = QueueRepLog("/", "q", manager)
    log.followers["n2"] = 0
    node.membership.clients["n2"] = client = FakeClient()
    client.replies["repl.append"] = lambda p: {
        "applied": p["events"][-1]["s"]}
    for i in range(5):
        log.append("watermark", {"wm": i})
    # sequences are assigned monotonically from 1 in append order
    assert log.seq == 5
    for _ in range(100):
        if not log.pending and (log._ship_task is None or log._ship_task.done()):
            break
        await asyncio.sleep(0.01)
    seqs = [e["s"] for _m, p in client.calls for e in p["events"]]
    assert seqs == [1, 2, 3, 4, 5]
    assert log.followers["n2"] == 5
    assert log.live_ack_floor() == 5 and log.lag() == 0
    # a dead follower stops counting against the floor
    log.followers["n2"] = 2
    assert log.lag() == 3
    node.membership.alive.discard("n2")
    assert log.lag() == 0


# ---------------------------------------------------------------------------
# unit: batch framing
# ---------------------------------------------------------------------------


async def test_batch_framing_respects_batch_max():
    node, manager = make_manager(batch_max=4)
    log = QueueRepLog("/", "q", manager)
    log.followers["n2"] = 0
    node.membership.clients["n2"] = client = FakeClient()
    client.replies["repl.append"] = lambda p: {
        "applied": p["events"][-1]["s"]}
    # append everything before the ship task gets a tick: one burst
    for i in range(10):
        log.append("watermark", {"wm": i})
    for _ in range(100):
        if log.followers["n2"] == 10:
            break
        await asyncio.sleep(0.01)
    batches = [p for m, p in client.calls if m == "repl.append"]
    assert [len(p["events"]) for p in batches] == [4, 4, 2]
    # frames are contiguous: each base is the previous batch's end + 1
    assert [p["base"] for p in batches] == [1, 5, 9]
    for p in batches:
        assert p["owner"] == "n1" and p["vhost"] == "/" and p["queue"] == "q"
        assert [e["s"] for e in p["events"]] == list(
            range(p["base"], p["base"] + len(p["events"])))
    assert node.broker.metrics.repl_batches_shipped == 3
    assert node.broker.metrics.repl_events_shipped == 10


# ---------------------------------------------------------------------------
# unit: gap triggers resync from the owner's store
# ---------------------------------------------------------------------------


async def test_gap_triggers_resync():
    node, manager = make_manager()
    applier = manager.applier
    owner_client = FakeClient()
    node.membership.clients["owner"] = owner_client
    node.membership.alive.add("owner")

    # in-sequence batch applies cleanly
    reply = await applier.h_append({
        "vhost": "/", "queue": "q", "owner": "owner", "base": 1,
        "events": [
            {"s": 1, "op": "enqueue", "o": 1, "m": 11, "z": 3, "e": None,
             "body": b"abc", "props": b"", "ex": "", "rk": "", "ttl": None},
        ],
        "acks": {},
    })
    assert reply == {"applied": 1}
    copy = applier.copies[("/", "q")]
    assert copy.rows == {1: (11, 3, None)}

    # the owner's store snapshot the gapped follower will pull
    # snapshot covers everything through seq 5 (the store reflects all the
    # events this follower missed; the owner reports its current head)
    owner_client.replies["repl.resync"] = {
        "seq": 5, "durable": True, "ttl": None, "args": "{}", "wm": 1,
        "rows": [[2, 22, 3, None], [3, 33, 3, None]], "more": False,
        "unacks": [[11, 1, 3, None]],
    }
    owner_client.replies["repl.fetch"] = lambda p: {
        "msgs": [[mid, b"", b"blob", "", "", None] for mid in p["ids"]]}

    # gapped batch (base 6 > applied 1 + 1): buffered, resync kicks off
    reply = await applier.h_append({
        "vhost": "/", "queue": "q", "owner": "owner", "base": 6,
        "events": [{"s": 6, "op": "watermark", "wm": 2}],
        "acks": {},
    })
    assert reply == {"applied": 1}
    for _ in range(200):
        if not copy.resyncing and copy.applied_seq >= 6:
            break
        await asyncio.sleep(0.01)
    # snapshot installed at seq 5, then the buffered batch replayed on top
    assert copy.applied_seq == 6
    assert copy.unacks == {11: (1, 3, None)}
    assert copy.wm == 2
    assert copy.rows == {3: (33, 3, None)}  # row 2 consumed by wm=2
    assert node.broker.metrics.repl_resyncs == 1
    assert any(m == "repl.resync" for m, _ in owner_client.calls)
    # the replica namespace holds the warm copy in the local store
    sq = await node.broker.store.select_queue(replica_vhost("/"), "q")
    assert sq is not None and sq.last_consumed == 2
    # replica namespaces stay invisible to recovery
    assert await node.broker.store.all_queues() == []


async def test_owner_change_discards_stale_copy():
    node, manager = make_manager()
    applier = manager.applier
    await applier.h_append({
        "vhost": "/", "queue": "q", "owner": "a", "base": 1,
        "events": [
            {"s": 1, "op": "enqueue", "o": 1, "m": 5, "z": 1, "e": None,
             "body": b"x", "props": b"", "ex": "", "rk": "", "ttl": None}],
        "acks": {},
    })
    assert applier.copies[("/", "q")].owner == "a"
    # a batch from a different owner supersedes the old copy wholesale
    await applier.h_append({
        "vhost": "/", "queue": "q", "owner": "b", "base": 1,
        "events": [{"s": 1, "op": "meta", "durable": True, "ttl": None,
                    "args": "{}", "wm": 0, "backlog": 0}],
        "acks": {},
    })
    copy = applier.copies[("/", "q")]
    assert copy.owner == "b" and copy.rows == {} and copy.applied_seq == 1


# ---------------------------------------------------------------------------
# end-to-end: failover promotion with zero confirmed-message loss
# ---------------------------------------------------------------------------


class Node:
    def __init__(self, server, cluster):
        self.server = server
        self.cluster = cluster

    @property
    def port(self):
        return self.server.bound_port

    @property
    def name(self):
        return self.cluster.name

    async def stop(self):
        await self.cluster.stop()
        await self.server.stop()


async def start_node(seeds):
    """One in-process node with a PRIVATE MemoryStore: surviving the
    owner's death then proves replication, not shared-store recovery."""
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                          store=MemoryStore())
    await server.start()
    cluster = ClusterNode(server.broker, "127.0.0.1", 0, seeds,
                          heartbeat_interval_s=0.1, failure_timeout_s=0.8,
                          replicate_factor=2, replicate_sync=True,
                          replicate_ack_timeout_ms=2000)
    await cluster.start()
    return Node(server, cluster)


async def admin_get(broker, path):
    admin = AdminServer(broker, port=0)
    await admin.start()
    try:
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", admin.bound_port)
        writer.write(f"GET {path} HTTP/1.1\r\n\r\n".encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), 5)
        writer.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        assert b"200 OK" in head.splitlines()[0]
        return json.loads(body)
    finally:
        await admin.stop()


async def test_failover_promotion_zero_confirmed_loss():
    total = 30
    first = await start_node([])
    second = await start_node([first.name])
    nodes = [first, second]
    for _ in range(100):
        if all(len(n.cluster.membership.alive_members()) == 2 for n in nodes):
            break
        await asyncio.sleep(0.05)
    try:
        owner_name = first.cluster.queue_owner("/", "ha_q")
        owner = next(n for n in nodes if n.name == owner_name)
        survivor = next(n for n in nodes if n.name != owner_name)

        # client rides the SURVIVOR so it outlives the owner
        client = await AMQPClient.connect("127.0.0.1", survivor.port)
        ch = await client.channel()
        await ch.confirm_select()
        await ch.queue_declare("ha_q", durable=True)

        got = {}
        done = asyncio.get_event_loop().create_future()

        def on_msg(msg):
            got[bytes(msg.body)] = None
            ch.basic_ack(msg.delivery_tag)
            if len(got) == total and not done.done():
                done.set_result(None)

        await ch.basic_consume("ha_q", on_msg)

        # publish the first half and require every confirm before the kill:
        # with sync=true a released confirm means the replica acked
        for i in range(total // 2):
            ch.basic_publish(b"m%02d" % i, routing_key="ha_q",
                             properties=PERSISTENT)
        await ch.wait_unconfirmed_below(1, timeout=30)

        # the survivor's warm copy is visible through /admin/replication
        status = await admin_get(survivor.server.broker, "/admin/replication")
        entry = status["queues"]["//ha_q"]
        if entry.get("role") == "follower":
            assert entry["applied_seq"] > 0
        owner_status = await admin_get(
            owner.server.broker, "/admin/replication")
        owner_entry = owner_status["queues"]["//ha_q"]
        assert owner_entry["role"] == "owner"
        assert survivor.name in owner_entry["followers"]
        assert "lag" in owner_entry

        # kill the owner mid-consume (deliveries are in flight, some unacked)
        await owner.stop()

        # wait for failure detection + promotion on the survivor (a publish
        # into the not-yet-detected window would tear the connection down on
        # the escalated remote-push failure, as the confirm contract demands)
        for _ in range(200):
            if (owner.name not in survivor.cluster.membership.alive_members()
                    and survivor.server.broker.metrics.repl_promotions == 1
                    and "ha_q" in survivor.server.broker.vhosts["/"].queues):
                break
            await asyncio.sleep(0.05)
        assert survivor.server.broker.metrics.repl_promotions == 1

        # publish the second half through the survivor, now the owner
        for i in range(total // 2, total):
            ch.basic_publish(b"m%02d" % i, routing_key="ha_q",
                             properties=PERSISTENT)
        await asyncio.wait_for(done, 30)
        # zero loss: every confirmed persistent message was delivered
        assert sorted(got) == [b"m%02d" % i for i in range(total)]
        await ch.wait_unconfirmed_below(1, timeout=30)

        assert survivor.server.broker.metrics.repl_promotions == 1
        status = await admin_get(survivor.server.broker, "/admin/replication")
        assert status["queues"]["//ha_q"]["role"] == "owner"
        # drained queue: nothing outstanding on the promoted copy
        await asyncio.sleep(0.3)
        queue = survivor.server.broker.vhosts["/"].queues["ha_q"]
        assert len(queue.messages) == 0 and len(queue.outstanding) == 0
        await client.close()
    finally:
        for node in nodes:
            try:
                await node.stop()
            except Exception:
                pass
