#!/usr/bin/env python3
"""Manual smoke publisher — the rebuild's analogue of the reference's
SimplePublisher (chana-mq-test .../SimplePublisher.scala:24-61): declare a
durable direct exchange and a durable queue with x-message-ttl=60000, bind,
and publish five messages across three property shapes (persistent,
persistent+expiration, bare).

Usage: python examples/simple_publisher.py [host] [port]
(start a broker first: chanamq-server --port 5672, or
 python -m chanamq_tpu.broker.server --port 5672)
"""

import asyncio
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.client import AMQPClient

EXCHANGE = "test_exchange"
QUEUE = "test_queue"
ROUTING_KEY = "quote"


async def main() -> None:
    host = sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1"
    port = int(sys.argv[2]) if len(sys.argv) > 2 else 5672
    conn = await AMQPClient.connect(host, port)
    ch = await conn.channel()
    await ch.confirm_select()

    await ch.exchange_declare(EXCHANGE, "direct", durable=True)
    ok = await ch.queue_declare(
        QUEUE, durable=True, arguments={"x-message-ttl": 60000})
    print(f"declare queue: {ok.queue}")
    await ch.queue_bind(QUEUE, EXCHANGE, ROUTING_KEY)

    props_persistent = BasicProperties(delivery_mode=2)
    props_expiring = BasicProperties(delivery_mode=2, expiration="100000")
    shapes = [props_persistent, props_expiring, None, None, None]
    for i, props in enumerate(shapes):
        ch.basic_publish(b"Hello, world%d" % i, exchange=EXCHANGE,
                         routing_key=ROUTING_KEY, properties=props)
        print("published")
    await ch.wait_unconfirmed_below(1)
    print("confirmed; closing ...")
    await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
