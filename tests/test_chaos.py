"""Fault-injection subsystem tests: deterministic plans, seam injection,
the /admin/chaos surface, reconnect backoff (jitter + admin state), the
mid-batch confirm-chain abort and promotion-during-ship regressions, and
the full seeded 3-node chaos soak."""

import asyncio
import json

import pytest

from chanamq_tpu import chaos
from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.chaos import ChaosStore, FaultPlan, FaultRule, _LazyRuntime
from chanamq_tpu.chaos.soak import run_soak
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster.node import ClusterNode
from chanamq_tpu.cluster.rpc import ReconnectBackoff, RpcClient, RpcError
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# FaultPlan determinism + trigger semantics
# ---------------------------------------------------------------------------

def _prob_plan(seed):
    return FaultPlan(seed, [
        FaultRule(name="maybe", kind="latency", sites=["x.*"],
                  probability=0.4, delay_ms=1),
    ])


def test_same_seed_same_decision_sequence():
    p1, p2 = _prob_plan(99), _prob_plan(99)
    seq1 = [p1.decide("x.op") is not None for _ in range(200)]
    seq2 = [p2.decide("x.op") is not None for _ in range(200)]
    assert seq1 == seq2
    assert any(seq1) and not all(seq1)  # probability actually gated draws
    assert p1.fingerprint() == p2.fingerprint()


def test_different_seed_different_schedule():
    seq1 = [_prob_plan(1).decide("x.op") is not None for _ in range(200)]
    p2 = _prob_plan(2)
    seq2 = [p2.decide("x.op") is not None for _ in range(200)]
    assert seq1 != seq2
    assert _prob_plan(1).fingerprint() != p2.fingerprint()


def test_fingerprint_ignores_endpoint_bindings():
    """Ephemeral host:port targets must not break same-seed reproduction."""
    def plan(port):
        return FaultPlan(5, [FaultRule(
            name="part", kind="partition", sites=["data.send"],
            nodes=[f"127.0.0.1:{port}"])])
    assert plan(1111).fingerprint() == plan(2222).fingerprint()


def test_count_window_and_site_triggers():
    plan = FaultPlan(0, [
        FaultRule(name="once", kind="error", sites=["a"], count=1),
        FaultRule(name="windowed", kind="drop", sites=["b"],
                  after=2, until=4),
    ])
    # count: fires exactly once despite always-eligible probability
    fires = [plan.decide("a") is not None for _ in range(5)]
    assert fires == [True, False, False, False, False]
    # window [after, until): armed only for matching invocations 3..4
    fires = [plan.decide("b") is not None for _ in range(6)]
    assert fires == [False, False, True, True, False, False]
    # site mismatch never counts an invocation
    assert plan.decide("c") is None
    counters = plan.counters()
    assert counters["once"] == {"kind": "error", "invocations": 5, "fires": 1}
    assert counters["windowed"]["fires"] == 2


def test_peer_glob_and_partition_ctx():
    plan = FaultPlan(0, [
        FaultRule(name="peered", kind="error", sites=["s"], peer="10.0.*"),
        FaultRule(name="part", kind="partition", sites=["s"],
                  nodes=["1.2.3.4:9"]),
    ])
    assert plan.decide("s", peer="10.0.0.5") is not None  # peered matches
    assert plan.decide("s", peer="1.2.3.4:9") is not None  # partition node
    assert plan.decide("s", peer="192.168.0.1") is None


def test_plan_round_trips_through_json():
    plan = FaultPlan(3, [FaultRule(name="r", kind="disconnect",
                                   sites=["rpc.*"], probability=0.5,
                                   count=2, after=1, delay_ms=7)])
    clone = FaultPlan.from_dict(json.loads(json.dumps(plan.to_dict())))
    assert clone.fingerprint() == plan.fingerprint()
    with pytest.raises(ValueError):
        FaultRule(name="bad", kind="nope")
    with pytest.raises(ValueError):
        FaultPlan(0, [FaultRule(name="dup", kind="drop"),
                      FaultRule(name="dup", kind="drop")])


# ---------------------------------------------------------------------------
# Runtime hook + metrics + store seam
# ---------------------------------------------------------------------------

async def test_install_clear_and_metrics_accounting():
    assert chaos.ACTIVE is None
    metrics = Metrics()
    runtime = chaos.install(FaultPlan(0, [
        FaultRule(name="err", kind="error", sites=["s"], count=2),
        FaultRule(name="lat", kind="latency", sites=["t"], count=1),
    ]), metrics=metrics)
    assert chaos.ACTIVE is runtime
    with pytest.raises(OSError):
        await runtime.fire("s")
    await runtime.fire("t")  # latency: slept (0ms) in place, no raise
    assert metrics.chaos_fires == 2
    assert metrics.chaos_errors == 1 and metrics.chaos_latency == 1
    status = runtime.status()
    assert status["total_fires"] == 2
    assert [e["rule"] for e in status["fire_log_tail"]] == ["err", "lat"]
    chaos.clear()
    assert chaos.ACTIVE is None


async def test_chaos_store_injects_and_passes_through():
    inner = MemoryStore()
    await inner.open()
    store = ChaosStore(inner, _LazyRuntime())
    # no plan installed: pure delegation
    await store.insert_vhost("v1")
    assert ("v1", True) in await store.all_vhosts()
    chaos.install(FaultPlan(0, [
        FaultRule(name="read-err", kind="error", sites=["store.read"],
                  count=1),
        FaultRule(name="write-drop", kind="drop", sites=["store.write"],
                  count=1),
    ]))
    with pytest.raises(OSError):
        await store.all_vhosts()
    await store.insert_vhost("v2")  # dropped: silently did nothing
    assert ("v2", True) not in await store.all_vhosts()
    await store.insert_vhost("v3")  # drop count exhausted: lands
    assert ("v3", True) in await store.all_vhosts()
    chaos.clear()
    await store.flush()  # flush barrier delegates cleanly with chaos off
    await inner.close()


# ---------------------------------------------------------------------------
# Satellite: ReconnectBackoff decorrelated jitter
# ---------------------------------------------------------------------------

async def test_backoff_jitter_envelope():
    backoff = ReconnectBackoff(base_s=0.1, max_s=5.0)
    prev = backoff.base_s
    for n in range(1, 12):
        backoff.failed()
        delay = backoff._delay_s
        assert backoff.base_s <= delay <= min(5.0, prev * 3) + 1e-9
        assert backoff.failures == n
        prev = max(delay, backoff.base_s)
    with pytest.raises(RpcError):
        backoff.check()
    backoff.succeeded()
    # dial success clears only the suppression window; the delay and
    # failure count survive until enough clean calls round-trip
    backoff.check()  # no longer suppressed...
    assert backoff.failures == 11  # ...but history is not forgiven yet
    for _ in range(backoff.clean_reset_calls):
        backoff.note_clean()
    assert backoff.state() == {"delay_s": 0.0, "consecutive_failures": 0}


async def test_backoff_flapping_peer_keeps_delay():
    """A peer that accepts the dial then drops every call must not get its
    backoff zeroed by the dial alone — that was a tight reconnect loop."""
    backoff = ReconnectBackoff(base_s=0.1, max_s=5.0, clean_reset_calls=4)
    for _ in range(5):
        backoff.failed()      # dial refused a few times
    for _ in range(3):
        backoff.succeeded()   # dial lands...
        backoff.note_clean()  # ...one call round-trips...
        backoff.failed()      # ...then the peer drops the connection
    # the jittered delay may wander, but it is never zeroed mid-flap and
    # the failure streak keeps compounding across the fake recoveries
    assert backoff._delay_s >= backoff.base_s
    assert backoff.failures == 8
    # sustained health: a full run of clean calls resets to base
    backoff.succeeded()
    for _ in range(4):
        backoff.note_clean()
    assert backoff.state() == {"delay_s": 0.0, "consecutive_failures": 0}
    # and a healthy-from-birth backoff never counts clean calls
    fresh = ReconnectBackoff(clean_reset_calls=2)
    for _ in range(10):
        fresh.note_clean()
    assert fresh._clean_calls == 0


async def test_backoff_jitter_spreads_clients():
    """The point of decorrelation: two clients failing in lockstep must not
    share a delay sequence (with the unseeded module RNG)."""
    seqs = []
    for _ in range(2):
        backoff = ReconnectBackoff(base_s=0.05, max_s=60.0)
        for _ in range(8):
            backoff.failed()
        seqs.append(backoff._delay_s)
    # 8 compounding uniform draws: collision is ~impossible
    assert seqs[0] != seqs[1]


async def test_backoff_deterministic_when_chaos_seeded():
    def run():
        chaos.install(FaultPlan(77, [
            FaultRule(name="idle", kind="latency", sites=["nowhere"])]))
        backoff = ReconnectBackoff(base_s=0.1, max_s=5.0)
        seq = []
        for _ in range(6):
            backoff.failed()
            seq.append(backoff._delay_s)
        chaos.clear()
        return seq
    assert run() == run()


# ---------------------------------------------------------------------------
# Satellite: backoff state in /admin/cluster; /admin/chaos endpoints
# ---------------------------------------------------------------------------

async def _admin_request(port, method, path, body=b""):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
        f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    return head.split(b"\r\n")[0].decode(), json.loads(payload)


async def _start_pair(**kwargs):
    async def one(seeds):
        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                           store=MemoryStore())
        await srv.start()
        cl = ClusterNode(srv.broker, "127.0.0.1", 0, seeds,
                         heartbeat_interval_s=0.1, failure_timeout_s=0.8,
                         **kwargs)
        await cl.start()
        return srv, cl

    a_srv, a_cl = await one([])
    b_srv, b_cl = await one([a_cl.name])
    for _ in range(100):
        if (len(a_cl.membership.alive_members()) == 2
                and len(b_cl.membership.alive_members()) == 2):
            break
        await asyncio.sleep(0.05)
    else:
        raise RuntimeError("membership did not converge")
    return a_srv, a_cl, b_srv, b_cl


async def _stop_all(*parts):
    for part in parts:
        if part is not None:
            try:
                await part.stop()
            except Exception:
                pass


async def test_admin_cluster_reports_backoff_state(tmp_path):
    a_srv, a_cl, b_srv, b_cl = await _start_pair()
    admin = AdminServer(b_srv.broker, port=0)
    await admin.start()
    conn = None
    try:
        qn = next(f"aq{i}" for i in range(200)
                  if a_cl.queue_owner("/", f"aq{i}") == a_cl.name)
        conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn, durable=True)
        await ch.basic_publish_confirmed(b"x", routing_key=qn, timeout=10)

        status, payload = await _admin_request(
            admin.bound_port, "GET", "/admin/cluster")
        assert status.startswith("HTTP/1.1 200")
        inter = payload["interconnect"]
        # data plane: every stream reports its backoff posture
        assert inter["peers"], "remote publish should have opened a plane"
        for stats in inter["peers"].values():
            for st in stats["backoff"]:
                assert set(st) == {"delay_s", "consecutive_failures",
                                   "last_error"}
        # control plane: gossip clients report theirs too
        assert inter["control"]
        for st in inter["control"].values():
            assert st["consecutive_failures"] == 0
    finally:
        if conn is not None:
            await conn.close()
        await admin.stop()
        await _stop_all(b_cl, b_srv, a_cl, a_srv)


async def test_rpc_client_records_last_error():
    # a port with nothing listening: dial fails, state must say so
    probe = await asyncio.start_server(lambda r, w: None, "127.0.0.1", 0)
    port = probe.sockets[0].getsockname()[1]
    probe.close()
    await probe.wait_closed()
    client = RpcClient("127.0.0.1", port, connect_timeout_s=0.5)
    with pytest.raises((RpcError, OSError)):
        await client.call("ping", {}, timeout_s=1)
    state = client.backoff_state()
    assert state["consecutive_failures"] >= 1
    assert state["delay_s"] > 0
    assert state["last_error"]
    await client.close()


async def test_admin_chaos_endpoints():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=MemoryStore())
    await srv.start()
    admin = AdminServer(srv.broker, port=0)
    await admin.start()
    try:
        # not chaos-capable: install refused
        body = json.dumps({"seed": 11, "rules": [
            {"name": "lat", "kind": "latency", "sites": ["s"],
             "delay_ms": 1}]}).encode()
        status, payload = await _admin_request(
            admin.bound_port, "POST", "/admin/chaos/install", body)
        assert status.startswith("HTTP/1.1 409")
        assert "chaos disabled" in payload["error"]

        srv.broker.chaos_enabled = True
        status, payload = await _admin_request(
            admin.bound_port, "POST", "/admin/chaos/install", body)
        assert status.startswith("HTTP/1.1 200")
        assert payload["seed"] == 11 and payload["rules"] == ["lat"]
        fingerprint = payload["fingerprint"]

        await chaos.ACTIVE.fire("s")
        status, payload = await _admin_request(
            admin.bound_port, "GET", "/admin/chaos")
        assert payload["enabled"] and payload["installed"]
        assert payload["fingerprint"] == fingerprint
        assert payload["rules"]["lat"]["fires"] == 1
        assert payload["total_fires"] == 1

        # chaos_* land in the Prometheus scrape as counters
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", admin.bound_port)
        writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        scrape = (await reader.read()).decode()
        writer.close()
        assert "# TYPE chanamq_chaos_fires counter" in scrape
        assert "chanamq_chaos_fires 1" in scrape

        status, payload = await _admin_request(
            admin.bound_port, "POST", "/admin/chaos/clear")
        assert payload == {"ok": True, "total_fires": 1}
        assert chaos.ACTIVE is None
        status, payload = await _admin_request(
            admin.bound_port, "GET", "/admin/chaos")
        assert payload == {"enabled": True, "installed": False}

        # wrong verb on a known chaos path: 405, not 404
        status, payload = await _admin_request(
            admin.bound_port, "GET", "/admin/chaos/clear")
        assert status.startswith("HTTP/1.1 405")
    finally:
        await admin.stop()
        await srv.stop()


# ---------------------------------------------------------------------------
# Regression: mid-batch transport failure under the pipelined confirm chain
# ---------------------------------------------------------------------------

async def test_midbatch_send_failure_aborts_confirm_chain():
    """A transport fault in the middle of a pipelined push_many burst must
    abort the ordered confirm chain: the client sees a prefix of confirms
    then a dead connection — never a confirm for an unpushed message, and
    never a deadlocked confirm wait."""
    a_srv, a_cl, b_srv, b_cl = await _start_pair()
    conn = drain_conn = None
    try:
        qn = next(f"mq{i}" for i in range(200)
                  if a_cl.queue_owner("/", f"mq{i}") == a_cl.name)
        conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn, durable=True)
        for _ in range(100):
            if ("/", qn) in b_cl.queue_metas:
                break
            await asyncio.sleep(0.05)

        # first data.send passes, the second dies mid-pipeline
        chaos.install(FaultPlan(1, [FaultRule(
            name="mid", kind="error", sites=["data.send"],
            after=1, count=1)]))

        n = 400
        async def burst():
            for i in range(n):
                ch.basic_publish(f"b{i:05d}".encode(), routing_key=qn,
                                 properties=PERSISTENT)
                if i == n // 2:
                    # split the burst across flush windows so the fault
                    # lands between batches of one confirm chain
                    await asyncio.sleep(0.02)
            await ch.wait_unconfirmed_below(1, timeout=20)

        # no deadlock: the burst either confirms fully (fault hit a settle
        # frame instead) or fails fast with the aborted connection
        aborted = False
        try:
            await asyncio.wait_for(burst(), 30)
        except Exception:
            aborted = True
        confirmed = n - len(ch.unconfirmed)
        fired = chaos.ACTIVE.plan.counters()["mid"]["fires"]
        assert fired == 1, "fault rule must have fired mid-burst"
        assert aborted, "a mid-batch send failure must abort the connection"
        assert confirmed < n, "no false confirm for the failed batch"
        chaos.clear()

        # every confirm the client DID receive is a real stored message:
        # drain the queue and check prefix containment
        drain_conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        dch = await drain_conn.channel()
        got = set()
        done = asyncio.Event()

        def cb(msg):
            got.add(bytes(msg.body).decode())
            done.set()

        await dch.basic_consume(qn, cb, no_ack=True)
        while True:
            done.clear()
            try:
                await asyncio.wait_for(done.wait(), 1.0)
            except asyncio.TimeoutError:
                break
        expected_prefix = {f"b{i:05d}" for i in range(confirmed)}
        assert expected_prefix <= got
    finally:
        chaos.clear()
        for c in (conn, drain_conn):
            if c is not None:
                try:
                    await c.close()
                except Exception:
                    pass
        await _stop_all(b_cl, b_srv, a_cl, a_srv)


# ---------------------------------------------------------------------------
# Regression: promotion while the mutation-log ship is in flight
# ---------------------------------------------------------------------------

async def test_promotion_after_dropped_ship_batch_heals_via_resync():
    """Drop the owner's first ship batch mid-flight: the follower must
    gap-detect on the next batch and resync (trigger not lost), and after
    the owner dies the promoted replica must hold every confirmed message
    exactly once (no torn batch applied)."""
    a_srv, a_cl, b_srv, b_cl = await _start_pair(
        replicate_factor=2, replicate_sync=True,
        replicate_ack_timeout_ms=500)
    conn = None
    try:
        qn = next(f"pq{i}" for i in range(200)
                  if a_cl.queue_owner("/", f"pq{i}") == a_cl.name)
        chaos.install(FaultPlan(2, [FaultRule(
            name="drop-ship", kind="drop", sites=["repl.ship"], count=1)]))

        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn, durable=True)
        bodies = [f"r{i}".encode() for i in range(5)]
        for body in bodies:
            # first confirm rides the dropped batch: it gates on the sync
            # barrier's ack timeout, then proceeds (follower will resync)
            await ch.basic_publish_confirmed(
                body, routing_key=qn, properties=PERSISTENT, timeout=10)

        # follower heals: gap detected on the next batch -> wholesale resync
        owner_log = a_cl.replication._logs[("/", qn)]
        for _ in range(200):
            copies = b_cl.replication.applier.copies
            if copies and all(c.applied_seq >= owner_log.seq
                              for c in copies.values()):
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("follower never caught up after drop")
        assert b_srv.broker.metrics.repl_resyncs >= 1, \
            "gap-detect resync trigger was lost"
        chaos.clear()
        await conn.close()
        conn = None

        # owner dies abruptly; B must promote and serve the full set
        await _stop_all(a_cl, a_srv)
        for _ in range(100):
            if b_srv.broker.metrics.repl_promotions == 1:
                break
            await asyncio.sleep(0.05)
        assert b_srv.broker.metrics.repl_promotions == 1

        conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        ch = await conn.channel()
        got = []
        done = asyncio.Event()

        def cb(msg):
            got.append(bytes(msg.body).decode())
            if len(got) >= len(bodies):
                done.set()

        await ch.basic_consume(qn, cb, no_ack=True)
        await asyncio.wait_for(done.wait(), 10)
        await asyncio.sleep(0.3)  # a torn apply would surface extras here
        assert sorted(got) == sorted(b.decode() for b in bodies)
    finally:
        chaos.clear()
        if conn is not None:
            try:
                await conn.close()
            except Exception:
                pass
        await _stop_all(b_cl, b_srv, a_cl, a_srv)


# ---------------------------------------------------------------------------
# The seeded soak: every invariant under partition + crash + slow store
# ---------------------------------------------------------------------------

async def test_seeded_soak_holds_all_invariants():
    report = await asyncio.wait_for(
        run_soak(42, messages=80, stream_records=30), timeout=120)
    assert report["violations"] == []
    assert report["crashed"] is True
    assert report["promotions"] == 1
    assert report["confirmed"] == 80
    assert report["delivered_unique"] == 80
    assert report["post_settle_duplicates"] == 0
    assert report["stream"]["contiguous"] is True
    # health gate: all three nodes reported ready before load was offered
    assert all(report["health_gate"].values())
    assert len(report["health_gate"]) == 3
    # the replica holder promotes; both survivors re-hash once each
    assert report["handoffs"] == 2
    # the scripted alert phase fired exactly the expected rules
    from chanamq_tpu.chaos.soak import EXPECTED_ALERT_RULES
    assert tuple(report["alerts"]["fired_rules"]) == EXPECTED_ALERT_RULES
    # reproducibility: the installed plan's schedule is seed-determined
    from chanamq_tpu.chaos.soak import default_plan
    assert (default_plan(42, "any:1", 80).fingerprint()
            == report["fingerprint"])
