"""End-to-end message tracing (chanamq_tpu/trace/): sampling determinism,
wire blob + trailer codec, cross-node stitching over the binary data plane
(memoryview bodies untouched), ring eviction, slow capture, chaos-fire
tagging, admin endpoint shapes, and the sampled-tracing overhead claim
(slow-marked)."""

import asyncio
import json
import time
from urllib.parse import quote

import pytest

from chanamq_tpu import chaos, trace
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.chaos.plan import FaultPlan, FaultRule
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.config import Config
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.trace import (
    CLUSTER_PUSH, DELIVER, ENQUEUE, INGRESS_PARSE, REMOTE_APPLY, ROUTE,
    SETTLE, STAGES, Trace, TraceRuntime, decode_trailer, encode_trailer,
)
from chanamq_tpu.utils.metrics import Metrics

from test_cluster_broker import start_cluster

pytestmark = pytest.mark.asyncio


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    trace.clear()
    chaos.clear()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


async def test_sampling_deterministic_per_seed():
    rt1 = TraceRuntime(sample_rate=0.3, seed=7)
    rt2 = TraceRuntime(sample_rate=0.3, seed=7)
    d1 = [rt1.begin_publish() is not None for _ in range(200)]
    d2 = [rt2.begin_publish() is not None for _ in range(200)]
    assert d1 == d2
    assert any(d1) and not all(d1)  # a 0.3 rate samples some, not all
    # a different seed draws a different subset
    rt3 = TraceRuntime(sample_rate=0.3, seed=8)
    assert [rt3.begin_publish() is not None for _ in range(200)] != d1


async def test_sampling_consumes_one_draw_regardless_of_rate():
    # same seed, different rates: after N publishes both RNGs must sit at
    # the same stream position, so rate changes never reshuffle later
    # sampling decisions of a seeded run
    rt_none = TraceRuntime(sample_rate=0.0, seed=7)
    rt_all = TraceRuntime(sample_rate=1.0, seed=7)
    for _ in range(200):
        assert rt_none.begin_publish() is None
        assert rt_all.begin_publish() is not None
    assert rt_none._rng.random() == rt_all._rng.random()


async def test_enable_from_config_inherits_chaos_seed(tmp_path):
    config = Config({"chana.mq.trace.enabled": True,
                     "chana.mq.chaos.seed": 123})
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    try:
        rt = trace.enable_from_config(config, server.broker)
        assert rt is trace.ACTIVE and rt.seed == 123
        assert server.broker.trace_enabled is True
        trace.clear()
        # an installed chaos plan's seed wins over the config default
        chaos.install(FaultPlan(seed=77, rules=[
            FaultRule(name="r", kind="latency", sites=["none"],
                      probability=0.0)]))
        rt = trace.enable_from_config(config, server.broker)
        assert rt.seed == 77
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


async def test_blob_roundtrip_and_trailer():
    tr = Trace("nodeA:1#42", "nodeA:1")
    tr.span(INGRESS_PARSE, 100, 250, "nodeA:1")
    tr.span(ROUTE, 250, 300, "nodeA:1")
    tr.tag_chaos("slow-store")
    back = Trace.from_blob(tr.to_blob())
    assert back.trace_id == tr.trace_id and back.origin == tr.origin
    assert back.slots[INGRESS_PARSE] == (100, 250, "nodeA:1")
    assert back.slots[ROUTE] == (250, 300, "nodeA:1")
    assert back.chaos_rules == ["slow-store"]

    tr2 = Trace("nodeA:1#43", "nodeA:1")
    tr2.span(ENQUEUE, 7, 9, "nodeB:1")
    payload = b"\x00recordbytes" + encode_trailer([(0, tr), (3, tr2)])
    got = decode_trailer(payload)
    assert sorted(got) == [0, 3]
    assert got[0].trace_id == "nodeA:1#42"
    assert got[3].slots[ENQUEUE] == (7, 9, "nodeB:1")
    # payloads without a trailer (or too short) decode to None, even when
    # the tail happens to contain arbitrary bytes
    assert decode_trailer(b"\x00recordbytes") is None
    assert decode_trailer(b"") is None


# ---------------------------------------------------------------------------
# cross-node stitching over the data plane
# ---------------------------------------------------------------------------


async def test_cross_node_trace_stitching(tmp_path):
    """Publish via the NON-owner with sample-rate 1.0: the trace must ride
    the push trailer to the owner, come back on the deliver trailer, and
    finish as ONE stitched trace spanning both nodes — with the message
    body delivered byte-identical (the trailer never perturbs the
    zero-copy record decode)."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        qn = next(f"tq{i}" for i in range(200)
                  if nodes[0].cluster.queue_owner("/", f"tq{i}")
                  != nodes[0].name)
        other = nodes[0]  # non-owner of qn by construction
        rt = trace.install(TraceRuntime(
            sample_rate=1.0, metrics=other.server.broker.metrics,
            node=other.name))

        body = b"\xde\xad" + bytes(range(256))
        client = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await client.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn)
        for _ in range(100):  # owner's meta broadcast is fire-and-forget
            if ("/", qn) in other.cluster.queue_metas:
                break
            await asyncio.sleep(0.05)
        got = asyncio.get_event_loop().create_future()
        await ch.basic_consume(qn, lambda m: got.done()
                               or got.set_result(bytes(m.body)),
                               no_ack=True)
        ch.basic_publish(body, routing_key=qn)
        await ch.wait_unconfirmed_below(1, timeout=10)
        assert await asyncio.wait_for(got, 10) == body
        await client.close()

        for _ in range(100):  # settle lands via the async deliver path
            if rt.ring:
                break
            await asyncio.sleep(0.05)
        tr = rt.ring[-1]
        stitched = rt.find(tr.trace_id)
        d = stitched.to_dict()
        assert len(d["nodes"]) == 2, d
        for stage in (INGRESS_PARSE, ROUTE, CLUSTER_PUSH, REMOTE_APPLY,
                      DELIVER, SETTLE):
            assert stitched.slots[stage] is not None, (STAGES[stage], d)
        # monotone: every span sits inside the trace bounds
        lo, hi = stitched.bounds_ns()
        assert all(lo <= s[0] <= s[1] <= hi
                   for s in stitched.slots if s is not None)
        # the owner-side stages carry the owner's node tag
        owner_name = nodes[0].cluster.queue_owner("/", qn)
        assert stitched.slots[REMOTE_APPLY][2] == owner_name
        assert stitched.slots[INGRESS_PARSE][2] == other.name
        assert other.server.broker.metrics.trace_ctx_sent > 0
        assert other.server.broker.metrics.trace_ctx_recv > 0
    finally:
        trace.clear()
        for node in nodes:
            await node.stop()


# ---------------------------------------------------------------------------
# rings: eviction + slow capture + chaos tagging
# ---------------------------------------------------------------------------


async def test_ring_eviction_keeps_newest():
    rt = TraceRuntime(sample_rate=1.0, ring_size=4, metrics=Metrics())
    ids = []
    for _ in range(10):
        tr = rt.begin_publish()
        ids.append(tr.trace_id)
        rt.finish(tr)
    assert len(rt.ring) == 4
    assert [t.trace_id for t in rt.ring] == ids[-4:]
    assert rt.metrics.trace_completed == 10
    # parked traces that never finish are capped too (lost flushes must
    # not leak memory); the cap overflow is accounted
    for i in range(rt._inflight_cap + 5):
        rt.park(Trace(f"lost#{i}", "n"))
    assert len(rt._inflight) == rt._inflight_cap
    assert rt.metrics.trace_evicted == 5


async def test_slow_capture_threshold():
    m = Metrics()
    rt = TraceRuntime(sample_rate=1.0, slow_ms=1.0, metrics=m)
    fast = rt.begin_publish()
    rt.finish(fast)  # ingress span only: far under 1 ms
    slow = rt.begin_publish()
    t0 = time.perf_counter_ns()
    slow.span(DELIVER, t0, t0 + 5_000_000, "n")  # 5 ms
    rt.finish(slow)
    assert [t.trace_id for t in rt.slow] == [slow.trace_id]
    assert m.trace_slow == 1 and m.trace_completed == 2
    # per-stage histogram observed the deliver duration (~5000 us)
    h = m.trace_stage_us["trace_deliver_us"]
    assert h.count == 1 and 4_000 <= h.total_us <= 6_000


async def test_chaos_fire_tags_trace():
    m = Metrics()
    rt = TraceRuntime(sample_rate=1.0, metrics=m)
    trace.install(rt)
    chaos.install(FaultPlan(seed=1, rules=[
        FaultRule(name="always-lag", kind="latency", sites=["store.*"],
                  probability=1.0, delay_ms=0)]), metrics=m)
    try:
        tr = rt.begin_publish()
        await chaos.ACTIVE.fire("store.enqueue")  # tags via current
        rt.current = None
        rt.finish(tr)
        assert tr.chaos_rules == ["always-lag"]
        assert list(rt.slow) == [tr]  # chaos-touched => always captured
        assert m.trace_chaos_tagged == 1

        # a fire OFF the publish path still tags traces whose time window
        # covers it (fault -> latency causality)
        tr2 = rt.begin_publish()
        rt.current = None
        await chaos.ACTIVE.fire("store.flush")
        tr2.span(SETTLE, tr2.slots[INGRESS_PARSE][0],
                 time.perf_counter_ns(), "n")
        rt.finish(tr2)
        assert "always-lag" in tr2.chaos_rules
    finally:
        chaos.clear()
        trace.clear()


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------


async def _http(port, method, path, body=b""):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    head = (f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode()
    writer.write(head + body)
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(-1), 10)
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    return status, json.loads(payload) if payload else None


async def test_admin_trace_endpoints():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        # not installed: the listing endpoint still answers
        status, body = await _http(admin.bound_port, "GET", "/admin/traces")
        assert status == 200
        assert body == {"enabled": False, "installed": False}

        rt = trace.install(TraceRuntime(
            sample_rate=1.0, metrics=server.broker.metrics, node="n1"))
        tr = rt.begin_publish()
        rt.finish(tr)
        status, body = await _http(admin.bound_port, "GET", "/admin/traces")
        assert status == 200 and body["installed"] is True
        assert body["node"] == "n1" and body["sample_rate"] == 1.0
        assert body["completed_in_ring"] == 1
        assert body["recent"][0]["id"] == tr.trace_id
        assert "trace_ingress_parse_us" in body["stage_latency_us"]

        # detail: the id contains '#', so it rides urlencoded
        status, body = await _http(
            admin.bound_port, "GET",
            f"/admin/traces/{quote(tr.trace_id, safe='')}")
        assert status == 200
        assert body["id"] == tr.trace_id and body["finished"] is True
        assert "ingress-parse" in body["stages"]

        status, body = await _http(
            admin.bound_port, "GET", "/admin/traces/nope%23404")
        assert status == 404
        assert "no trace" in body["error"]

        status, body = await _http(
            admin.bound_port, "POST", "/admin/traces", b"{}")
        assert status == 405 and body == {"error": "use GET"}

        # /admin/metrics carries the trace counters + stage percentiles
        status, body = await _http(admin.bound_port, "GET", "/admin/metrics")
        assert status == 200 and body["trace_sampled"] == 1
        assert "trace_ingress_parse_p99_us" in body
        assert body["connections_open"] == (
            body["connections_opened"] - body["connections_closed"])
    finally:
        trace.clear()
        await admin.stop()
        await server.stop()


async def test_prometheus_cumulative_histograms():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        h = server.broker.metrics.publish_to_deliver_us
        for us in (3, 15, 15, 40_000_000):  # last one overflows all bounds
            h.observe_us(us)
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", admin.bound_port)
        writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), 10)
        writer.close()
        text = raw.partition(b"\r\n\r\n")[2].decode()
        lines = text.splitlines()
        assert ("# TYPE chanamq_publish_to_deliver_us histogram") in lines
        bucket = {}
        for line in lines:
            if line.startswith("chanamq_publish_to_deliver_us_bucket"):
                le = line.split('le="')[1].split('"')[0]
                bucket[le] = int(line.rsplit(" ", 1)[1])
        # cumulative: counts only grow along the bounds, +Inf == count
        assert bucket["5"] == 1 and bucket["20"] == 3
        assert bucket["10000000"] == 3 and bucket["+Inf"] == 4
        assert "chanamq_publish_to_deliver_us_count 4" in lines
        assert f"chanamq_publish_to_deliver_us_sum {h.total_us}" in lines
        # counters got their proper TYPE line
        assert "# TYPE chanamq_trace_sampled counter" in lines
    finally:
        await admin.stop()
        await server.stop()


# ---------------------------------------------------------------------------
# overhead claim (slow: two 5 s bench runs)
# ---------------------------------------------------------------------------


@pytest.mark.slow
async def test_trace_overhead_under_two_percent():
    """ISSUE 5's headline claim: the 1% default sample rate costs <=2%
    throughput on the saturated transient/autoAck spec."""
    import bench

    # run_spec drives its load generator with asyncio.run, which cannot
    # nest inside this (asyncio-marked) test's running loop — hop each
    # run onto a worker thread so it gets a loop of its own
    base = await asyncio.to_thread(bench.run_spec, "transient_autoack_3p3c")
    traced = await asyncio.to_thread(
        bench.run_spec, "transient_autoack_3p3c", extra_env={
            "CHANAMQ_TRACE_ENABLED": "true",
            "CHANAMQ_TRACE_SAMPLE_RATE": "0.01"})
    assert "error" not in base, base
    assert "error" not in traced, traced
    assert traced["delivered_per_s"] >= base["delivered_per_s"] * 0.98, (
        base, traced)
