"""Chaos runtime: the live object behind the module-level hook.

The broker's I/O seams gate every injection on ``chaos.ACTIVE is not None``
— one module-attribute load and an identity check when chaos is disabled,
so the production hot path stays branch-predictable and allocation-free.
When a plan is installed, ``ACTIVE`` points at a ``ChaosRuntime`` which
owns the plan, bumps ``chaos_*`` metrics per fired kind, and dispatches
``crash`` faults to harness-registered handlers.

``fire(site, ...)`` is the convenience most seams use: it consults the
plan, applies ``latency`` in place (asyncio sleep), raises for ``error``
and ``partition`` via the caller's exception factory, and hands every
other kind back so the seam can do the transport-specific thing (drop a
frame, close a writer, desync a stream).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Callable, Optional

from .. import events, trace
from .plan import Fault, FaultPlan

log = logging.getLogger("chanamq.chaos")

# metrics counter per fault kind (all registered in utils/metrics.py)
_KIND_COUNTERS = {
    "latency": "chaos_latency",
    "error": "chaos_errors",
    "drop": "chaos_drops",
    "disconnect": "chaos_disconnects",
    "corrupt": "chaos_corrupt_frames",
    "crash": "chaos_crashes",
    "partition": "chaos_partition_drops",
    "pressure": "chaos_pressure",
}


class ChaosRuntime:
    """One installed plan plus the machinery around it."""

    def __init__(self, plan: FaultPlan, metrics=None) -> None:
        self.plan = plan
        self.metrics = metrics
        # dedicated stream for consumers that want seeded-deterministic
        # randomness while chaos is active (e.g. ReconnectBackoff jitter)
        self._aux_rng = random.Random(plan.seed ^ 0x5EED_CA05)
        self._crash_handlers: dict[str, Callable[[], None]] = {}

    # -- seam API ----------------------------------------------------------

    def decide(self, site: str, peer: str = "") -> Optional[Fault]:
        """Consult the plan; account for the fault but leave acting on it
        to the caller. Crash faults are dispatched here (the handler is a
        harness callback, not a transport behavior) and swallowed."""
        fault = self.plan.decide(site, peer)
        if fault is None:
            return None
        self._account(fault, site)
        if fault.kind == "crash":
            self._dispatch_crash(fault)
            return None
        return fault

    async def fire(self, site: str, peer: str = "",
                   on_error: Optional[Callable[[Fault], BaseException]] = None,
                   ) -> Optional[Fault]:
        """decide() plus the kind-independent behaviors: sleep latency,
        raise error/partition. Returns the fault for kinds the seam must
        handle itself (drop / disconnect / corrupt), else None."""
        fault = self.decide(site, peer)
        if fault is None:
            return None
        if fault.kind == "latency":
            if fault.delay_s > 0:
                await asyncio.sleep(fault.delay_s)
            return None
        if fault.kind in ("error", "partition"):
            if on_error is not None:
                raise on_error(fault)
            raise OSError(f"chaos[{fault.rule}]: {fault.message}")
        return fault

    def aux_rng(self) -> random.Random:
        return self._aux_rng

    # -- crash dispatch ----------------------------------------------------

    def on_crash(self, node: str, handler: Callable[[], None]) -> None:
        """Register the harness callback that 'crashes' ``node`` when a
        crash rule naming it fires."""
        self._crash_handlers[node] = handler

    def _dispatch_crash(self, fault: Fault) -> None:
        rule = next(r for r in self.plan.rules if r.name == fault.rule)
        targets = rule.nodes or list(self._crash_handlers)
        for node in targets:
            handler = self._crash_handlers.pop(node, None)
            if handler is None:
                log.warning("chaos crash rule %r: no handler for node %r",
                            fault.rule, node)
                continue
            log.info("chaos: crashing node %r (rule %r)", node, fault.rule)
            try:
                handler()
            except Exception:
                log.exception("chaos crash handler for %r failed", node)

    # -- accounting --------------------------------------------------------

    def _account(self, fault: Fault, site: str) -> None:
        m = self.metrics
        if m is not None:
            m.chaos_fires += 1
            counter = _KIND_COUNTERS.get(fault.kind)
            if counter is not None:
                setattr(m, counter, getattr(m, counter) + 1)
        if trace.ACTIVE is not None:
            # fault -> latency causality: tag the in-flight trace (if any)
            # and remember the fire so traces whose window covers it get
            # tagged at finish (chanamq_tpu/trace/)
            trace.ACTIVE.note_chaos_fire(fault.rule)
        bus = events.ACTIVE
        if bus is not None:
            bus.emit(f"chaos.fired.{fault.rule}", {
                "rule": fault.rule, "kind": fault.kind, "site": site,
            })
        log.debug("chaos fire: rule=%s kind=%s site=%s",
                  fault.rule, fault.kind, site)

    # -- introspection (the /admin/chaos body) -----------------------------

    def status(self) -> dict:
        return {
            "seed": self.plan.seed,
            "fingerprint": self.plan.fingerprint(),
            "total_fires": self.plan.total_fires,
            "rules": self.plan.counters(),
            "fire_log_tail": [
                {"n": n, "rule": rule, "site": site}
                for n, rule, site in self.plan.fire_log[-50:]
            ],
        }
