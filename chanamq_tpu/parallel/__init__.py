"""Device-mesh sharding for the auxiliary analytics models.

The scaling-book recipe: pick a mesh, annotate shardings on params and data,
jit, and let GSPMD insert the collectives. Axes: "dp" (data parallel over the
batch) x "tp" (tensor parallel over attention heads / FFN columns).
"""

from .mesh import (
    make_mesh,
    param_shardings,
    batch_sharding,
    make_sharded_train_step,
)

__all__ = [
    "make_mesh",
    "param_shardings",
    "batch_sharding",
    "make_sharded_train_step",
]
