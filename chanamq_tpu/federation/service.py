"""FederationService: the per-node federation endpoint.

One service per broker plays both roles: the *receiving* side registers
the ``fed.*`` handlers on its own :class:`RpcServer` (a dedicated
listener — federation method ids share nothing with the intra-cluster
data plane), and the *shipping* side runs one :class:`FederationLink`
per configured remote. The service also owns the hook surface the rest
of the broker calls into (`on_seal`, `on_cursor_commit`,
`on_dead_letter`, `stage_tx_batch`) — each is a cheap dict/match walk,
and none exist at all when ``broker.federation is None``.
"""

from __future__ import annotations

import asyncio
import logging
import time
import zlib
from collections import deque
from typing import TYPE_CHECKING, Optional

from .. import events, trace
from ..amqp.properties import BasicProperties
from ..otel.context import extract as w3c_extract
from ..broker.broker import BrokerError
from ..cluster.dataplane import _Cursor
from ..cluster.rpc import RpcError, RpcServer
from ..streams.segment import (
    Segment, unpack_records, unpack_records_indexed)
from .link import FED_PUBLISH, FED_SHIP, FED_TX, FederationLink

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker

log = logging.getLogger("chanamq.federation")

# bounded transition log: enough for a soak's full decision history
_EVENT_LOG_MAX = 512

# mirror-side {offset: Trace} contexts awaiting their first dispatch —
# bounded per queue so a mirror nobody consumes can't grow without limit
_FED_TRACE_CAP = 1024


class FederationService:
    """Federation endpoint + link manager for one broker."""

    def __init__(
        self, broker: "Broker", *, node_name: str = "",
        interface: str = "127.0.0.1", port: int = 0, window: int = 4,
        retry_s: float = 0.5, idle_s: float = 0.2,
        links: Optional[list[dict]] = None, auth_token: str = "",
    ) -> None:
        self.broker = broker
        self.metrics = broker.metrics
        self.node_name = node_name
        self.window = max(1, window)
        self.retry_s = retry_s
        self.idle_s = idle_s
        #: shared secret every inbound fed.* call must present when set.
        #: The federation listener sits outside the AMQP SASL/ACL path,
        #: so this token is its whole admission control — leave it empty
        #: only on a trusted network. Outbound links default to the same
        #: value (symmetric deployments configure one secret per pair).
        self.auth_token = auth_token
        self.server = RpcServer(interface, port)
        self.server.register("fed.hello", self._h_hello)
        self.server.register("fed.resume", self._h_resume)
        self.server.register("fed.cursor", self._h_cursor)
        self.server.register_binary(FED_SHIP, self._h_ship)
        self.server.register_binary(FED_TX, self._h_tx)
        self.server.register_binary(FED_PUBLISH, self._h_publish)
        self.links: list[FederationLink] = [
            FederationLink(self, spec) for spec in (links or [])]
        #: bounded transition log (link.up/down/resumed + cursor batches).
        #: The event bus is a process-global singleton, so a two-broker
        #: soak can't tell the clusters' emissions apart there — this log
        #: is per-service and is what the determinism gate compares.
        self.events: deque = deque(maxlen=_EVENT_LOG_MAX)
        #: last applied Tx-batch / forwarded-publish sequence per link,
        #: keyed by the shipper's per-boot epoch: a batch the link
        #: re-ships after a drop mid-reply applies once, while a
        #: restarted shipper (sequences reset to 0 under a fresh epoch)
        #: starts a new dedup scope instead of being swallowed by the
        #: previous incarnation's high-water mark. One entry per link —
        #: a new epoch replaces the old one, so the maps stay bounded.
        self._applied_tx: dict[str, tuple[str, int]] = {}
        self._applied_pub: dict[str, tuple[str, int]] = {}

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.server.start()
        self.broker.federation = self
        for link in self.links:
            link.start()

    async def stop(self) -> None:
        if self.broker.federation is self:
            self.broker.federation = None
        for link in self.links:
            await link.stop()
        await self.server.stop()

    @property
    def port(self) -> int:
        return self.server.bound_port

    def record(self, event: str, payload: dict) -> None:
        """Append to the service log and mirror onto the event bus."""
        self.events.append((event, payload))
        bus = events.ACTIVE
        if bus is not None:
            bus.emit("federation." + event, payload)

    def transition_log(self) -> list:
        """Link state transitions only (up/down/resumed): the
        wall-clock-independent slice the soak determinism gate compares —
        per-flush events like cursor batches depend on coalescing timing
        and are excluded by construction."""
        return [(ev, dict(payload)) for ev, payload in self.events
                if ev.startswith("link.")]

    # -- local-side hooks (no-ops unless a link matches) -------------------

    def on_seal(self, queue) -> None:
        """A local stream sealed a segment: wake every link mirroring it."""
        for link in self.links:
            if link.vhost == queue.vhost and queue.name in link.queues:
                link.wake()

    def on_cursor_commit(self, queue, name: str, offset: int) -> None:
        """A local cursor committed: stage the (coalesced) mirror write."""
        for link in self.links:
            if link.vhost == queue.vhost and queue.name in link.queues:
                link.note_cursor(queue.name, name, offset)

    def on_dead_letter(self, vhost: str, exchange: str, routing_key: str,
                       header_raw: bytes, body: bytes) -> None:
        """A local dead-letter publish targeted a federated exchange:
        forward a copy across every link federating it."""
        for link in self.links:
            if link.vhost == vhost and exchange in link.exchanges:
                link.queue_publish(exchange, routing_key, header_raw, body)
                self.metrics.federation_dlx_forwarded += 1

    def stage_tx_batch(self, vhost: str, ops: list) -> None:
        """A local Tx committed with publishes to federated exchanges:
        ship each link its slice as ONE batch (all-or-nothing far side).
        ``ops`` is [(exchange, routing_key, header_raw, body), ...]."""
        for link in self.links:
            if link.vhost != vhost:
                continue
            slice_ = [op for op in ops if op[0] in link.exchanges]
            if slice_:
                link.queue_tx(slice_)

    def link_lags(self) -> dict[str, int]:
        return {link.name: link.total_lag() for link in self.links}

    def stats(self) -> dict:
        return {
            "port": self.port,
            "node": self.node_name,
            "links": [link.info() for link in self.links],
            "events": [
                {"event": ev, **payload} for ev, payload in self.events],
        }

    # -- receiving side ----------------------------------------------------

    def _check_token(self, token) -> None:
        """Admission control for every inbound fed.* call (control and
        data plane): when the service has an ``auth_token``, a caller
        that doesn't present it is refused before any queue is declared
        or any byte is applied."""
        if self.auth_token and str(token or "") != self.auth_token:
            self.metrics.federation_auth_failures += 1
            raise RpcError("auth", "bad federation token")

    @staticmethod
    def _already_applied(table: dict, link: str, epoch: str,
                         seq: int) -> bool:
        entry = table.get(link)
        return (entry is not None and entry[0] == epoch
                and seq <= entry[1])

    async def _mirror_queue(self, vhost: str, name: str):
        """The mirror stream for an inbound ship/resume, declared on first
        contact. Mirrors are receive-only by convention: local publishes
        into one would collide with shipped offsets (documented in the
        README runbook), so the apply path seals any locally-appended
        records before splicing a shipped segment."""
        try:
            queue = self.broker.get_queue(vhost, name)
        except BrokerError:
            queue = await self.broker.declare_queue(
                vhost, name, durable=True,
                arguments={"x-queue-type": "stream"})
        if not getattr(queue, "is_stream", False):
            raise RpcError("bad-type", f"'{name}' is not a stream queue")
        return queue

    async def _h_hello(self, payload: dict) -> dict:
        self._check_token(payload.get("token"))
        link = str(payload.get("link", ""))
        node = str(payload.get("node", ""))
        log.info("federation hello from link=%s node=%s epoch=%s",
                 link, node, str(payload.get("epoch", "")))
        return {"node": self.node_name, "ok": True}

    async def _h_resume(self, payload: dict) -> dict:
        """Resume point for one mirrored queue: the mirror's next expected
        offset (ship from here) plus its committed-cursor map."""
        self._check_token(payload.get("token"))
        queue = await self._mirror_queue(
            str(payload.get("vhost", "/")), str(payload.get("queue", "")))
        return {
            "next": queue.next_offset,
            "committed": dict(queue.committed),
        }

    async def _h_cursor(self, payload: dict) -> dict:
        """Apply a batch of mirrored cursor commits, monotonically (the
        mirror may already be ahead from an earlier flush that raced the
        link drop — ``_commit`` keeps the max)."""
        self._check_token(payload.get("token"))
        vhost = str(payload.get("vhost", "/"))
        qname = str(payload.get("queue", ""))
        cursors = payload.get("cursors") or {}
        queue = await self._mirror_queue(vhost, qname)
        for name, offset in cursors.items():
            queue._commit(str(name), int(offset))
        self.metrics.federation_cursors_mirrored += len(cursors)
        self.record("cursor.mirrored", {
            "vhost": vhost, "queue": qname, "cursors": len(cursors),
            "link": str(payload.get("link", ""))})
        return {"applied": len(cursors)}

    async def _h_ship(self, payload: memoryview):
        """Apply one shipped sealed segment.

        Wire: ss token | ss vhost | ss queue | u64 base | u64 last |
        u64 first_ts | u64 last_ts | u32 crc32 | u32 blob-len | blob.
        Replies the mirror's next expected offset (u64) — also on an
        idempotent duplicate, so a shipper that lost our ack
        mid-link-drop fast-forwards instead of re-sending the whole
        window.

        The claimed range is validated against the decoded payload, not
        just the CRC (which only guards transport corruption): ``last``
        must cover ``base`` and every record offset must fall inside
        ``[base, last]`` in ascending order — otherwise a buggy or
        hostile shipper could splice a range the blob doesn't actually
        cover and permanently corrupt the mirror's offset space. Sparse
        blobs (key-compaction holes, including fully-compacted empties)
        remain legal: holes are allowed, out-of-range records are not."""
        cur = _Cursor(payload)
        self._check_token(cur.ss())
        vhost = cur.ss()
        qname = cur.ss()
        base = cur.u64()
        last = cur.u64()
        first_ts = cur.u64()
        last_ts = cur.u64()
        crc = cur.u32()
        blob = cur.blob()
        if last < base:
            self.metrics.federation_invalid_segments += 1
            raise RpcError("bad-range", f"last {last} < base {base}")
        queue = await self._mirror_queue(vhost, qname)
        if queue._active:
            # locally-appended records on a mirror (operator error): seal
            # them out of the way so the splice below stays contiguous
            queue._seal_active()
        if base < queue.next_offset:
            self.metrics.federation_duplicate_segments += 1
            return [_u64(queue.next_offset)]
        if base > queue.next_offset:
            # str(RpcError) is "code: message" and that string is what the
            # binary error reply carries — the shipper parses "gap: <next>"
            raise RpcError("gap", str(queue.next_offset))
        if zlib.crc32(blob) & 0xFFFFFFFF != crc:
            self.metrics.federation_crc_failures += 1
            raise RpcError("crc", "segment crc mismatch")
        data = bytes(blob)
        prev = base - 1
        rt = trace.ACTIVE
        fed_traces: "dict | None" = None
        t_apply = time.perf_counter_ns() if rt is not None else 0
        for rec in unpack_records(data):
            if rec.offset <= prev or rec.offset > last:
                self.metrics.federation_invalid_segments += 1
                raise RpcError(
                    "bad-range",
                    f"record offset {rec.offset} outside [{base}, {last}]")
            prev = rec.offset
            # cross-cluster parenting (ISSUE 20): the validation walk is
            # already touching every record, so a cheap substring probe
            # finds the ones whose origin stamped a W3C context into the
            # header; each mints a mirror-side forced trace parented (via
            # the header's traceparent = the origin broker's root span)
            # into the same trace id the producer started
            if rt is not None and b"traceparent" in rec.header_raw:
                tr = self._lift_record_context(rt, rec, vhost, qname)
                if tr is not None:
                    if fed_traces is None:
                        fed_traces = {}
                    fed_traces[rec.offset] = tr
        seg = Segment(base, last, first_ts, last_ts, len(data),
                      unpack_records_indexed(data, base, last))
        queue._segments.append(seg)
        queue._seg_bases.append(base)
        queue.ready_bytes += seg.size_bytes
        queue.next_offset = last + 1
        queue._active_base = queue.next_offset
        if queue.durable and not queue.deleted:
            self.broker.store_bg(self.broker.store.insert_stream_segment(
                vhost, qname, base, last, first_ts, last_ts,
                len(data), data))
        self.metrics.federation_segments_applied += 1
        if fed_traces:
            now = time.perf_counter_ns()
            node = self.node_name or rt.node
            for tr in fed_traces.values():
                tr.span(trace.REMOTE_APPLY, t_apply, now, node)
            existing = queue.fed_traces
            if existing is None:
                existing = queue.fed_traces = {}
            existing.update(fed_traces)
            while len(existing) > _FED_TRACE_CAP:
                existing.pop(next(iter(existing)))
            self.metrics.trace_ctx_recv += len(fed_traces)
        queue._enforce_retention()
        queue._evict_cache(keep=seg)
        queue.schedule_dispatch()
        return [_u64(queue.next_offset)]

    def _lift_record_context(self, rt, rec, vhost: str, qname: str):
        """Mint the mirror-side half of a propagated trace from a shipped
        record's stamped traceparent header. Never raises — a record with
        an undecodable header is simply applied untraced."""
        try:
            _, _, props = BasicProperties.decode_header(rec.header_raw)
        except Exception:
            return None
        ctx = w3c_extract(props.headers)
        if ctx is None:
            return None
        return rt.begin_remote(ctx, node=self.node_name or rt.node, attrs={
            "vhost": vhost, "queue": qname, "exchange": rec.exchange,
            "routing_key": rec.routing_key, "federated": "1"})

    async def _h_tx(self, payload: memoryview):
        """Apply one federated Tx batch all-or-nothing.

        Wire: ss token | ss link | ss epoch | u64 seq | ss vhost |
        u32 count | count * (ss exchange | ss rkey | u32 header-len |
        header | u32 body-len | body). On a WalStore the replay runs
        inside the same ``tx_begin``/``tx_seal`` scope a local Tx.Commit
        uses, so the whole batch lands as one ``tx_batch`` WAL record.
        Replies the applied sequence (u64); an already-applied sequence
        *from the same shipper epoch* acks without re-publishing
        (idempotent retry after a lost reply), while a fresh epoch —
        a restarted shipper whose sequences restart at 1 — opens a new
        dedup scope so its batches are never mistaken for replays of the
        previous incarnation's."""
        cur = _Cursor(payload)
        self._check_token(cur.ss())
        link = cur.ss()
        epoch = cur.ss()
        seq = cur.u64()
        vhost = cur.ss()
        count = cur.u32()
        if self._already_applied(self._applied_tx, link, epoch, seq):
            self.metrics.federation_duplicate_forwards += 1
            return [_u64(seq)]
        ops = []
        for _ in range(count):
            exchange = cur.ss()
            rkey = cur.ss()
            header = bytes(cur.blob())
            body = bytes(cur.blob())
            ops.append((exchange, rkey, header, body))
        store = self.broker.store
        scoped = (self.broker.cluster is None
                  and getattr(store, "tx_begin", None) is not None)
        if scoped:
            store.tx_begin()
        try:
            for exchange, rkey, header, body in ops:
                _, _, props = BasicProperties.decode_header(header)
                await self.broker.publish(
                    vhost, exchange, rkey, props, body, header_raw=header)
        except BaseException:
            if scoped:
                store.tx_abort()
            raise
        if scoped:
            store.tx_seal()
        self._applied_tx[link] = (epoch, seq)
        self.metrics.federation_tx_applied += 1
        return [_u64(seq)]

    async def _h_publish(self, payload: memoryview):
        """Apply one forwarded (DLX) publish.

        Wire: ss token | ss link | ss epoch | u64 seq | ss vhost |
        ss exchange | ss rkey | u32 header-len | header | u32 body-len |
        body. Forwards carry the same per-link (epoch, seq) identity as
        Tx batches, so a retry after a link drop mid-reply acks without
        publishing a duplicate DLX message. A missing exchange drops the
        message, matching local DLX semantics."""
        cur = _Cursor(payload)
        self._check_token(cur.ss())
        link = cur.ss()
        epoch = cur.ss()
        seq = cur.u64()
        vhost = cur.ss()
        exchange = cur.ss()
        rkey = cur.ss()
        header = bytes(cur.blob())
        body = bytes(cur.blob())
        if self._already_applied(self._applied_pub, link, epoch, seq):
            self.metrics.federation_duplicate_forwards += 1
            return None
        _, _, props = BasicProperties.decode_header(header)
        try:
            await self.broker.publish(
                vhost, exchange, rkey, props, body, header_raw=header)
        except BrokerError as exc:
            log.warning("federated publish to '%s' dropped: %s",
                        exchange, exc.text)
        self._applied_pub[link] = (epoch, seq)
        return None


def _u64(value: int) -> bytes:
    return value.to_bytes(8, "big")
