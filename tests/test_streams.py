"""Stream queue tests: segmented log, cursors, replay, retention.

Covers the x-queue-type=stream contract (streams/queue.py): non-destructive
cursor consumption through x-stream-offset attach specs, server-tracked
committed offsets (resume after reconnect AND after broker restart),
whole-segment retention, and the replica-namespace isolation of the admin
stream listing.
"""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.amqp.value_codec import Timestamp
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.api import StoredQueue, replica_vhost
from chanamq_tpu.store.sqlite import SqliteStore
from chanamq_tpu.streams import StreamQueue, parse_offset_spec

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)
STREAM = {"x-queue-type": "stream"}


@pytest.fixture
def db_path(tmp_path):
    return str(tmp_path / "broker.db")


async def start_server(db_path=None):
    srv = BrokerServer(
        host="127.0.0.1", port=0, heartbeat_s=0,
        store=SqliteStore(db_path) if db_path else None)
    await srv.start()
    return srv


async def collect(ch, queue, n, *, offset="first", tag="", timeout=5.0,
                  ack=True):
    """Consume `n` records from a stream cursor; returns the messages."""
    got: list = []
    done = asyncio.get_event_loop().create_future()

    def on_msg(msg):
        if len(got) >= n:
            return  # surplus in-flight delivery racing the cancel
        got.append(msg)
        if ack:
            ch.basic_ack(msg.delivery_tag)
        if len(got) >= n and not done.done():
            done.set_result(None)

    used_tag = await ch.basic_consume(
        queue, on_msg, consumer_tag=tag,
        arguments={"x-stream-offset": offset})
    await asyncio.wait_for(done, timeout)
    await ch.basic_cancel(used_tag)
    return got


# ---------------------------------------------------------------------------
# declare validation
# ---------------------------------------------------------------------------


async def test_offset_spec_parsing():
    assert parse_offset_spec(None) == ("next", None)
    assert parse_offset_spec("first") == ("first", None)
    assert parse_offset_spec("last") == ("last", None)
    assert parse_offset_spec(42) == ("offset", 42)
    assert parse_offset_spec(Timestamp(10)) == ("timestamp", 10_000)
    for bad in ("tail", -1, True, 1.5, b"first"):
        with pytest.raises(ValueError):
            parse_offset_spec(bad)


async def test_stream_declare_validation():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        cases = [
            # transient / exclusive / auto-delete stream declares, bad
            # queue type, stream-incompatible args, x-max-age off-stream
            dict(durable=False, arguments=STREAM),
            dict(durable=True, exclusive=True, arguments=STREAM),
            dict(durable=True, auto_delete=True, arguments=STREAM),
            dict(durable=True, arguments={"x-queue-type": "quorum"}),
            dict(durable=True, arguments={**STREAM, "x-max-age": "soon"}),
            dict(durable=True, arguments={
                **STREAM, "x-stream-max-segment-size-bytes": 0}),
            dict(durable=True, arguments={**STREAM, "x-max-priority": 5}),
            dict(durable=True, arguments={**STREAM, "x-message-ttl": 1000}),
            dict(durable=True, arguments={
                **STREAM, "x-queue-mode": "lazy"}),
            dict(durable=True, arguments={"x-max-age": "7d"}),  # classic
        ]
        for kwargs in cases:
            ch = await c.channel()
            with pytest.raises(ChannelClosedError) as exc_info:
                await ch.queue_declare("bad_stream", **kwargs)
            assert exc_info.value.reply_code == 406, kwargs
        # a valid declare still works afterwards
        ch = await c.channel()
        ok = await ch.queue_declare(
            "good_stream", durable=True,
            arguments={**STREAM, "x-max-age": "7d",
                       "x-stream-max-segment-size-bytes": 4096})
        assert ok.queue == "good_stream"
        await c.close()
    finally:
        await srv.stop()


async def test_bad_stream_offset_rejected_before_consume_ok():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("s_off", durable=True, arguments=STREAM)
        with pytest.raises(ChannelClosedError) as exc_info:
            await ch.basic_consume("s_off", lambda m: None,
                                   arguments={"x-stream-offset": "tail"})
        assert exc_info.value.reply_code == 406
        await c.close()
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# cursor semantics
# ---------------------------------------------------------------------------


async def test_cursors_are_non_destructive_and_independent():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("s1", durable=True, arguments=STREAM)
        for i in range(10):
            ch.basic_publish(b"m%d" % i, routing_key="s1",
                             properties=PERSISTENT)
        await asyncio.sleep(0.1)
        # two cursors each replay the full log from "first"
        got_a = await collect(ch, "s1", 10, tag="cur-a")
        got_b = await collect(ch, "s1", 10, tag="cur-b")
        for got in (got_a, got_b):
            assert [m.body for m in got] == [b"m%d" % i for i in range(10)]
        # reading deleted nothing
        queue = srv.broker.vhosts["/"].queues["s1"]
        assert queue.message_count == 10
        assert queue.first_offset == 1
        await c.close()
    finally:
        await srv.stop()


async def test_committed_cursor_resumes_on_reattach():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("s2", durable=True, arguments=STREAM)
        for i in range(6):
            ch.basic_publish(b"r%d" % i, routing_key="s2",
                             properties=PERSISTENT)
        await asyncio.sleep(0.1)
        # consume + ack the first 3 under a fixed tag, then detach
        got = await collect(ch, "s2", 3, tag="worker")
        assert [m.body for m in got] == [b"r0", b"r1", b"r2"]
        await asyncio.sleep(0.05)  # let the coalesced commit flush
        # reattach at "next" with the SAME tag: resumes at committed+1,
        # not at the log tail
        got = await collect(ch, "s2", 3, tag="worker", offset="next")
        assert [m.body for m in got] == [b"r3", b"r4", b"r5"]
        await c.close()
    finally:
        await srv.stop()


async def test_offset_and_timestamp_attach():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("s3", durable=True, arguments=STREAM)
        for i in range(4):
            ch.basic_publish(b"a%d" % i, routing_key="s3",
                             properties=PERSISTENT)
        await asyncio.sleep(1.1)  # timestamp resolution is one second
        cut = Timestamp(int(__import__("time").time()))
        for i in range(4, 8):
            ch.basic_publish(b"a%d" % i, routing_key="s3",
                             properties=PERSISTENT)
        await asyncio.sleep(0.1)
        got = await collect(ch, "s3", 3, offset=6, tag="abs")
        assert [m.body for m in got] == [b"a5", b"a6", b"a7"]
        got = await collect(ch, "s3", 4, offset=cut, tag="ts")
        assert [m.body for m in got] == [b"a4", b"a5", b"a6", b"a7"]
        await c.close()
    finally:
        await srv.stop()


async def test_nack_requeue_rewinds_cursor():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("s4", durable=True, arguments=STREAM)
        ch.basic_publish(b"one", routing_key="s4", properties=PERSISTENT)
        await asyncio.sleep(0.05)
        got: list = []
        redelivered = asyncio.get_event_loop().create_future()

        def on_msg(msg):
            got.append(msg)
            if len(got) == 1:
                ch.basic_nack(msg.delivery_tag, requeue=True)
            else:
                ch.basic_ack(msg.delivery_tag)
                if not redelivered.done():
                    redelivered.set_result(None)

        await ch.basic_consume("s4", on_msg,
                               arguments={"x-stream-offset": "first"})
        await asyncio.wait_for(redelivered, 5)
        assert [m.body for m in got] == [b"one", b"one"]
        assert got[1].redelivered or True  # same record, replayed
        await c.close()
    finally:
        await srv.stop()


async def test_basic_get_reads_shared_cursor():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("s5", durable=True, arguments=STREAM)
        for i in range(3):
            ch.basic_publish(b"g%d" % i, routing_key="s5",
                             properties=PERSISTENT)
        await asyncio.sleep(0.05)
        m1 = await ch.basic_get("s5")
        assert m1 is not None and m1.body == b"g0"
        ch.basic_ack(m1.delivery_tag)
        m2 = await ch.basic_get("s5")
        assert m2 is not None and m2.body == b"g1"
        ch.basic_ack(m2.delivery_tag)
        await asyncio.sleep(0.05)
        # gets consumed nothing: the log still holds every record
        assert srv.broker.vhosts["/"].queues["s5"].message_count == 3
        await c.close()
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# restart replay (acceptance) + retention
# ---------------------------------------------------------------------------


async def test_restart_replays_all_records_from_first(db_path):
    """Acceptance: after a broker restart, a cursor attached at `first`
    replays ALL retained records in order with their original offsets."""
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare(
        "replay", durable=True,
        arguments={**STREAM, "x-stream-max-segment-size-bytes": 256})
    for i in range(50):
        ch.basic_publish(b"rec-%02d" % i, routing_key="replay",
                         properties=PERSISTENT)
    await ch.queue_declare("replay", passive=True)  # publish barrier
    await c.close()
    await srv.stop()  # clean shutdown seals + spills the active segment

    srv = await start_server(db_path)
    try:
        queue = srv.broker.vhosts["/"].queues["replay"]
        assert isinstance(queue, StreamQueue)
        assert queue.message_count == 50
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        got = await collect(ch, "replay", 50, tag="replayer")
        assert [m.body for m in got] == [b"rec-%02d" % i for i in range(50)]
        # offsets survive the restart verbatim: monotonic from 1
        assert queue.first_offset == 1 and queue.next_offset == 51
        # records keep flowing after recovery too
        ch.basic_publish(b"rec-50", routing_key="replay",
                         properties=PERSISTENT)
        got = await collect(ch, "replay", 1, tag="replayer", offset="next")
        assert got[0].body == b"rec-50"
        await c.close()
    finally:
        await srv.stop()


async def test_committed_cursor_survives_restart(db_path):
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("resume", durable=True, arguments=STREAM)
    for i in range(6):
        ch.basic_publish(b"c%d" % i, routing_key="resume",
                         properties=PERSISTENT)
    await asyncio.sleep(0.1)
    got = await collect(ch, "resume", 4, tag="tailer")
    assert [m.body for m in got] == [b"c0", b"c1", b"c2", b"c3"]
    await asyncio.sleep(0.05)
    await c.close()
    await srv.stop()

    srv = await start_server(db_path)
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        # same tag, "next": the server-side committed offset drives resume
        got = await collect(ch, "resume", 2, tag="tailer", offset="next")
        assert [m.body for m in got] == [b"c4", b"c5"]
        await c.close()
    finally:
        await srv.stop()


async def test_size_retention_truncates_whole_segments_only():
    """Acceptance: x-max-length-bytes truncates the oldest SEALED segments
    whole — never partial segments, never the active one."""
    broker = Broker()
    await broker.store.open()
    await broker.create_vhost("/")
    queue = await broker.declare_queue(
        "/", "capped", durable=True,
        arguments={**STREAM, "x-max-length-bytes": 2000,
                   "x-stream-max-segment-size-bytes": 512})
    queue.cache_segments = 100  # keep all sealed records resident to inspect
    for i in range(100):
        broker.push_local([queue], PERSISTENT, b"x" * 50, "", "capped",
                          None, None)
    assert queue.first_offset > 1  # retention kicked in
    assert queue.retained_bytes <= 2000 + 512  # cap + at most one segment
    # every retained sealed segment is intact end to end
    for seg in queue._segments:
        assert seg.records is None or len(seg.records) == (
            seg.last_offset - seg.base_offset + 1)
    # the head is exactly a segment boundary — no partial truncation
    assert queue.first_offset == queue._segments[0].base_offset
    # truncated prefix is contiguous: offsets below first_offset are gone,
    # first_offset itself is readable
    assert queue._record_at(queue.first_offset - 1) is None
    rec = queue._record_at(queue.first_offset)
    assert rec is not None and rec.offset == queue.first_offset
    assert broker.metrics.stream_segments_truncated > 0


async def test_age_retention_and_age_seal():
    broker = Broker()
    await broker.store.open()
    await broker.create_vhost("/")
    queue = await broker.declare_queue(
        "/", "aged", durable=True,
        arguments={**STREAM, "x-max-age": "1s"})
    for i in range(5):
        broker.push_local([queue], PERSISTENT, b"old", "", "aged",
                          None, None)
    # age-seal the quiet active segment, then age out the sealed one
    queue.segment_age_ms = 1
    await asyncio.sleep(0.01)
    queue._expire_head()
    assert queue.segment_count == 1 and not queue._active
    queue.max_age_ms = 1
    await asyncio.sleep(0.01)
    queue._expire_head()
    assert queue.message_count == 0
    assert queue.first_offset == queue.next_offset == 6
    # offsets never rewind: the next record continues the sequence
    broker.push_local([queue], PERSISTENT, b"new", "", "aged", None, None)
    assert queue.next_offset == 7


async def test_stream_delete_clears_store(db_path):
    srv = await start_server(db_path)
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare(
            "doomed", durable=True,
            arguments={**STREAM, "x-stream-max-segment-size-bytes": 64})
        for i in range(10):
            ch.basic_publish(b"d%d" % i, routing_key="doomed",
                             properties=PERSISTENT)
        await ch.queue_declare("doomed", passive=True)
        await ch.queue_delete("doomed")
        store = srv.broker.store
        assert await store.stream_segment_metas("/", "doomed") == []
        assert await store.select_stream_cursors("/", "doomed") == {}
        await c.close()
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# replica-namespace isolation (regression)
# ---------------------------------------------------------------------------


async def test_replica_vhosts_never_leak(db_path):
    """REPLICA_NS-namespaced vhosts (follower copies of replicated queues)
    must not surface in all_queues() recovery, /admin queue listings, or
    the /admin/streams listing."""
    store = SqliteStore(db_path)
    await store.open()
    await store.insert_vhost("/", True)
    await store.insert_queue_meta(StoredQueue(
        vhost="/", name="real_q", durable=True, arguments={}))
    await store.insert_queue_meta(StoredQueue(
        vhost="/", name="real_stream", durable=True,
        arguments={"x-queue-type": "stream"}))
    # a follower's warm copy, exactly as replicate/applier.py writes it
    await store.insert_queue_meta(StoredQueue(
        vhost=replica_vhost("/"), name="real_q", durable=True,
        arguments={}))
    await store.insert_queue_meta(StoredQueue(
        vhost=replica_vhost("/"), name="real_stream", durable=True,
        arguments={"x-queue-type": "stream"}))
    names = {(q.vhost, q.name) for q in await store.all_queues()}
    assert names == {("/", "real_q"), ("/", "real_stream")}
    await store.close()

    srv = await start_server(db_path)
    try:
        broker = srv.broker
        assert set(broker.vhosts) == {"/"}
        assert set(broker.vhosts["/"].queues) == {"real_q", "real_stream"}
        admin = AdminServer(broker, port=0)
        queues = {q["name"] for q in admin._queues("/")}
        assert queues == {"real_q", "real_stream"}
        assert admin._queues(replica_vhost("/")) == []
        streams = admin._streams()
        assert [(s["vhost"], s["name"]) for s in streams] == [
            ("/", "real_stream")]
        # the prometheus render exposes no replica-namespaced labels
        assert "repl\\x00" not in admin._prometheus()
    finally:
        await srv.stop()
