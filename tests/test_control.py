"""Predictive control plane tests: engine determinism, hysteresis and
cooldown, the accountant stage floor, dry-run's no-mutation guarantee,
apply/relax round-trips, identity-pinned forecast slots, forecast
accuracy tracking, cluster queue handoff, and the /admin/control surface.
"""

import asyncio
import json

import numpy as np
import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.control import (
    ControlConfig, ControlEngine, ControlInputs, ControlService, QueueInput,
)
from chanamq_tpu.flow import (
    MemoryAccountant, STAGE_NORMAL, STAGE_THROTTLE,
)
from chanamq_tpu.models.telemetry import TopKSlots
from chanamq_tpu.store.memory import MemoryStore

pytestmark = pytest.mark.asyncio

PROPS = BasicProperties()


def canonical(decisions: list) -> bytes:
    return b"\n".join(
        json.dumps(d, sort_keys=True, separators=(",", ":")).encode()
        for d in decisions)


# ---------------------------------------------------------------------------
# pure engine
# ---------------------------------------------------------------------------


def ramp_inputs(tick: int, gate: int, net: float, *, floor: int = 0,
                stage: int = 0) -> ControlInputs:
    return ControlInputs(
        tick=tick, interval_s=1.0, stage=stage, floor=floor,
        gate_total=gate, enter_throttle=1000, exit_throttle=800,
        net_rate=net, publish_credit=16384)


async def test_engine_same_series_same_log():
    """The tentpole determinism contract: the engine is a pure function
    of the input series, so two engines fed the same snapshots emit
    byte-identical decision logs."""
    logs = []
    for _ in range(2):
        engine = ControlEngine(ControlConfig(
            horizon_ticks=5, arm_ticks=2, cooldown_ticks=3))
        out = []
        floor = 0   # mirrors the applier: prearm pins it, relax drops it
        gate = 0
        for t in range(1, 8):
            net = 120.0 if t > 1 else 0.0
            gate += int(net)
            decisions, _ = engine.evaluate(
                ramp_inputs(t, gate, net, floor=floor, stage=floor))
            out.extend(decisions)
            for d in decisions:
                floor = d["action"].get("floor", floor)
        for t in range(8, 14):  # drained: the relax side of the episode
            decisions, _ = engine.evaluate(
                ramp_inputs(t, 0, -700.0 if t == 8 else 0.0,
                            floor=floor, stage=floor))
            out.extend(decisions)
            for d in decisions:
                floor = d["action"].get("floor", floor)
        logs.append(canonical(out))
    assert logs[0] == logs[1]
    kinds = [json.loads(line)["kind"] for line in logs[0].split(b"\n")]
    assert kinds == ["admission.prearm", "admission.relax"]


async def test_engine_hysteresis_and_cooldown():
    engine = ControlEngine(ControlConfig(
        horizon_ticks=5, arm_ticks=2, cooldown_ticks=10))
    # one breaching tick is not enough (arm_ticks=2)
    decisions, suppressed = engine.evaluate(ramp_inputs(1, 900, 100.0))
    assert decisions == [] and suppressed == 0
    # second consecutive breach arms
    decisions, _ = engine.evaluate(ramp_inputs(2, 1000, 100.0))
    assert [d["kind"] for d in decisions] == ["admission.prearm"]
    assert decisions[0]["action"]["floor"] == STAGE_THROTTLE
    assert decisions[0]["action"]["publish_credit"] == 8192
    # a non-breaching tick resets the arm streak
    engine2 = ControlEngine(ControlConfig(horizon_ticks=5, arm_ticks=2))
    engine2.evaluate(ramp_inputs(1, 900, 100.0))
    engine2.evaluate(ramp_inputs(2, 100, 0.0))
    decisions, _ = engine2.evaluate(ramp_inputs(3, 900, 100.0))
    assert decisions == []
    # relax inside the cooldown window is suppressed, not emitted
    calm = ramp_inputs(3, 0, 0.0, floor=STAGE_THROTTLE,
                       stage=STAGE_THROTTLE)
    decisions, suppressed = engine.evaluate(calm)
    assert decisions == [] and suppressed == 0      # streak 1 of 2
    decisions, suppressed = engine.evaluate(
        ramp_inputs(4, 0, 0.0, floor=STAGE_THROTTLE, stage=STAGE_THROTTLE))
    assert decisions == [] and suppressed == 1      # armed but cooling down
    decisions, _ = engine.evaluate(
        ramp_inputs(12, 0, 0.0, floor=STAGE_THROTTLE, stage=STAGE_THROTTLE))
    assert [d["kind"] for d in decisions] == ["admission.relax"]
    assert decisions[0]["action"]["publish_credit"] == 16384


async def test_engine_forecast_source_preferred():
    engine = ControlEngine(ControlConfig(horizon_ticks=5, arm_ticks=1))
    inp = ramp_inputs(1, 100, 0.0)
    inp.forecast_net_rate = 500.0   # trend says flat, forecast says spike
    decisions, _ = engine.evaluate(inp)
    assert decisions and decisions[0]["inputs"]["source"] == "forecast"
    assert decisions[0]["inputs"]["net_rate"] == 500.0


async def test_engine_rebalance_and_prefetch():
    engine = ControlEngine(ControlConfig(
        arm_ticks=1, rebalance_ratio=1.5, rebalance_min_rate=10.0,
        prefetch_min=8, prefetch_max=64))
    queues = (
        QueueInput(vhost="/", name="busy", depth=50, publish_rate=900,
                   deliver_rate=100, ack_rate=10, ready_bytes=1e5,
                   consumers=1, movable=True),
        QueueInput(vhost="/", name="idle", depth=0, publish_rate=1,
                   deliver_rate=1, ack_rate=1, ready_bytes=0,
                   consumers=1, movable=True),
    )
    inp = ControlInputs(
        tick=1, interval_s=1.0, stage=0, floor=0, gate_total=0,
        enter_throttle=0, exit_throttle=0, net_rate=0.0, publish_credit=0,
        queues=queues, node="a", self_load=1000.0,
        peer_loads={"b": 10.0, "c": 30.0}, consume_credit=32)
    decisions, _ = engine.evaluate(inp)
    kinds = {d["kind"]: d for d in decisions}
    move = kinds["rebalance.move"]
    assert move["action"] == {"vhost": "/", "name": "busy", "target": "b"}
    assert move["inputs"]["loads"]["a"] == 1000.0
    # ack keeps pace with deliver on "idle" but "busy" lags badly ->
    # the lagging queue wins and the window shrinks
    tune = kinds["prefetch.tune"]
    assert tune["action"]["consume_credit"] == 16
    assert tune["inputs"]["reason"] == "ack-lag"


# ---------------------------------------------------------------------------
# accountant stage floor
# ---------------------------------------------------------------------------


async def test_accountant_floor_pins_and_releases():
    acc = MemoryAccountant(high_watermark=1000)
    stages = []
    acc.listeners.append(lambda old, new: stages.append((old, new)))
    acc.floor = STAGE_THROTTLE
    acc.reevaluate()
    assert acc.stage == STAGE_THROTTLE      # pinned with zero bytes
    assert stages == [(STAGE_NORMAL, STAGE_THROTTLE)]
    acc.add("bodies", 100)                  # stays at the floor
    assert acc.stage == STAGE_THROTTLE
    acc.floor = STAGE_NORMAL
    acc.reevaluate()
    assert acc.stage == STAGE_NORMAL        # cascades back down
    assert stages[-1] == (STAGE_THROTTLE, STAGE_NORMAL)
    assert acc.snapshot()["floor"] == STAGE_NORMAL


# ---------------------------------------------------------------------------
# service on a live broker
# ---------------------------------------------------------------------------


def spike_broker() -> Broker:
    return Broker(store=MemoryStore(), flow_high_watermark=1000,
                  flow_hard_limit=4000, flow_publish_credit=16384,
                  message_sweep_interval_s=3600.0)


def spike_control(broker: Broker, *, dry_run: bool) -> ControlService:
    return ControlService(
        broker, interval_s=1.0, dry_run=dry_run, admission=True,
        rebalance=False, prefetch=False, horizon_s=5.0, arm_ticks=2,
        cooldown_s=2.0, credit_factor=0.5, credit_min=4096)


async def drive_spike(broker: Broker, control: ControlService) -> None:
    """Deterministic episode: 5 growth ticks (+120 B/s), then a drain
    and 4 quiescent ticks — enough for prearm and relax to both fire."""
    for _ in range(5):
        broker.account_memory(120)
        await control.step(1.0)
    broker.account_memory(-600)
    for _ in range(4):
        await control.step(1.0)


async def test_service_applies_prearm_and_relax():
    broker = spike_broker()
    control = spike_control(broker, dry_run=False)
    try:
        for _ in range(4):
            broker.account_memory(120)
            await control.step(1.0)
        # tick 4: gate 480, net 120 -> projected 1080 crossed 1000 on
        # ticks 4+5; the pre-arm lands on the second breach
        broker.account_memory(120)
        await control.step(1.0)
        assert broker.flow.floor == STAGE_THROTTLE
        assert broker.flow.stage == STAGE_THROTTLE   # pinned early: gate 600
        assert broker.flow_publish_credit == 8192
        assert broker.metrics.control_applied == 1
        # drain, then quiesce: relax must restore both actuators
        broker.account_memory(-600)
        for _ in range(4):
            await control.step(1.0)
        assert broker.flow.floor == STAGE_NORMAL
        assert broker.flow.stage == STAGE_NORMAL
        assert broker.flow_publish_credit == 16384
        assert broker.metrics.control_applied == 2
        kinds = [e["kind"] for e in control.log]
        assert kinds == ["admission.prearm", "admission.relax"]
        assert all(e["applied"] for e in control.log)
    finally:
        await control.stop()


async def test_service_dry_run_mutates_nothing():
    broker = spike_broker()
    control = spike_control(broker, dry_run=True)
    try:
        floors = set()
        credits = set()
        for _ in range(5):
            broker.account_memory(120)
            await control.step(1.0)
            floors.add(broker.flow.floor)
            credits.add(broker.flow_publish_credit)
        broker.account_memory(-600)
        for _ in range(4):
            await control.step(1.0)
            floors.add(broker.flow.floor)
            credits.add(broker.flow_publish_credit)
        # decisions recorded and counted...
        kinds = [e["kind"] for e in control.log]
        assert kinds == ["admission.prearm", "admission.relax"]
        assert all(e["dry_run"] and not e["applied"] for e in control.log)
        assert broker.metrics.control_dry_run == 2
        assert broker.metrics.control_decisions == 2
        # ...but no actuator ever moved
        assert floors == {STAGE_NORMAL}
        assert credits == {16384}
        assert broker.metrics.control_applied == 0
    finally:
        await control.stop()


async def test_service_same_series_byte_identical_log():
    logs = []
    for _ in range(2):
        broker = spike_broker()
        control = spike_control(broker, dry_run=False)
        try:
            await drive_spike(broker, control)
            logs.append(control.decision_log_bytes())
        finally:
            await control.stop()
    assert logs[0] == logs[1]
    assert logs[0]  # non-trivial: prearm + relax present
    entries = [json.loads(line) for line in logs[0].split(b"\n")]
    assert [e["kind"] for e in entries] == \
        ["admission.prearm", "admission.relax"]
    # every entry carries its replayable input snapshot
    assert all("gate_total" in e["inputs"] and "projected" in e["inputs"]
               for e in entries)


async def test_service_gauges_and_snapshot():
    broker = spike_broker()
    control = spike_control(broker, dry_run=False)
    try:
        await drive_spike(broker, control)
        snap = control.snapshot(tail=8)
        assert snap["enabled"] and not snap["dry_run"]
        assert snap["counters"]["applied"] == 2
        assert snap["flow"] == {"stage": 0, "floor": 0}
        assert len(snap["log"]) == 2
        # the broker-wide metrics snapshot folds the control gauges in
        msnap = broker.metrics_snapshot()
        assert msnap["control_log_entries"] == 2
        assert msnap["control_floor"] == 0
        assert msnap["flow_stage_floor"] == 0
    finally:
        await control.stop()


# ---------------------------------------------------------------------------
# identity-pinned forecast slots (models/telemetry.py)
# ---------------------------------------------------------------------------


def matrix(rows: dict[tuple, list]) -> tuple[list, np.ndarray]:
    keys = list(rows)
    # QUEUE_FIELDS order: publish, deliver, ack, depth, unacked,
    # consumers, ready_bytes
    return keys, np.array(list(rows.values()), dtype=np.float64)


async def test_topk_slots_pin_evict_reset():
    slots = TopKSlots(2)
    a, b, c = ("/", "a"), ("/", "b"), ("/", "c")
    keys, latest = matrix({a: [10, 0, 0, 5, 0, 0, 0],
                           b: [5, 0, 0, 7, 0, 0, 0],
                           c: [1, 0, 0, 9, 0, 0, 0]})
    # fresh slots emit zeros for exactly one tick (the reset marker)
    out = slots.update(keys, latest)
    assert slots.slot_queues() == [a, b]
    assert out.tolist() == [0, 0, 0, 0]
    out = slots.update(keys, latest)
    assert out.tolist() == [5, 10, 7, 5]     # (depth, publish_rate) pairs
    # c overtakes b: b is evicted, c lands in the freed slot, and the
    # incumbent a KEEPS its slot even though c now outranks it
    keys, latest = matrix({a: [10, 0, 0, 5, 0, 0, 0],
                           b: [0, 0, 0, 7, 0, 0, 0],
                           c: [99, 0, 0, 9, 0, 0, 0]})
    out = slots.update(keys, latest)
    assert slots.slot_queues() == [a, c]
    assert out.tolist() == [5, 10, 0, 0]     # c's slot resets this tick
    out = slots.update(keys, latest)
    assert out.tolist() == [5, 10, 9, 99]
    # the binding (and therefore the feature layout) is deterministic
    twin = TopKSlots(2)
    keys0, latest0 = matrix({a: [10, 0, 0, 5, 0, 0, 0],
                             b: [5, 0, 0, 7, 0, 0, 0],
                             c: [1, 0, 0, 9, 0, 0, 0]})
    twin.update(keys0, latest0)
    twin.update(keys0, latest0)
    twin.update(keys, latest)
    assert twin.slot_queues() == slots.slot_queues()


async def test_topk_slots_vanished_queue_freed():
    slots = TopKSlots(2)
    a, b = ("/", "a"), ("/", "b")
    keys, latest = matrix({a: [10, 0, 0, 5, 0, 0, 0],
                           b: [5, 0, 0, 7, 0, 0, 0]})
    slots.update(keys, latest)
    keys, latest = matrix({b: [5, 0, 0, 7, 0, 0, 0]})  # a deleted
    slots.update(keys, latest)
    assert slots.slot_queues() == [None, b]
    assert slots.update(keys, latest).tolist() == [0, 0, 7, 5]


# ---------------------------------------------------------------------------
# forecast accuracy tracking (models/service.py)
# ---------------------------------------------------------------------------


async def test_forecast_accuracy_mae():
    from chanamq_tpu.models.service import ForecastService

    broker = Broker(store=MemoryStore(), message_sweep_interval_s=3600.0)
    svc = ForecastService(broker)
    assert svc.accuracy() is None            # nothing scored yet
    n = svc.n_features
    svc._pending_forecast = np.full(n, 10.0, dtype=np.float32)
    svc.score_tick(np.full(n, 13.0, dtype=np.float32))
    acc = svc.accuracy()
    assert acc["scored"] == 1
    name = svc.feature_names[0]
    assert acc["last_abs_error"][name] == pytest.approx(3.0)
    assert acc["mae"][name] == pytest.approx(3.0)
    # second sample: running MAE averages the two errors
    svc._pending_forecast = np.full(n, 10.0, dtype=np.float32)
    svc.score_tick(np.full(n, 9.0, dtype=np.float32))
    acc = svc.accuracy()
    assert acc["scored"] == 2
    assert acc["mae"][name] == pytest.approx(2.0)
    # a tick with no pending forecast scores nothing
    svc.score_tick(np.full(n, 100.0, dtype=np.float32))
    assert svc.accuracy()["scored"] == 2
    assert "accuracy" in svc.snapshot()


async def test_control_forecast_trust_gate():
    """An inaccurate or stale forecast must not steer admission."""
    broker = spike_broker()
    control = spike_control(broker, dry_run=True)
    try:
        class FakeForecaster:
            forecast = {"publish_bytes_rate": 5000.0,
                        "deliver_bytes_rate": 0.0}
            updated_at = None

            def accuracy(self):
                return self._acc

            def slot_queues(self):
                return []

        fake = FakeForecaster()
        broker.forecaster = fake
        import time as _time
        fake.updated_at = _time.time()
        fake._acc = {"scored": 5, "mae": {"publish_bytes_rate": 1e9}}
        assert control._forecast_net_rate() is None      # failed the gate
        fake._acc = {"scored": 5, "mae": {"publish_bytes_rate": 1.0}}
        assert control._forecast_net_rate() == pytest.approx(5000.0)
        fake.updated_at = _time.time() - 1e6             # stale
        assert control._forecast_net_rate() is None
    finally:
        broker.forecaster = None
        await control.stop()


# ---------------------------------------------------------------------------
# proactive rebalancing: cluster queue handoff
# ---------------------------------------------------------------------------


async def _start_cluster_pair(tmp_path):
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.cluster.node import ClusterNode
    from chanamq_tpu.store.sqlite import SqliteStore

    store = str(tmp_path / "shared.db")
    nodes = []
    seeds: list = []
    for _ in range(2):
        server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                              store=SqliteStore(store))
        await server.start()
        cluster = ClusterNode(server.broker, "127.0.0.1", 0, list(seeds),
                              heartbeat_interval_s=0.1,
                              failure_timeout_s=0.8)
        await cluster.start()
        nodes.append((server, cluster))
        seeds = [nodes[0][1].name]
    for _ in range(100):
        if all(len(c.membership.alive_members()) == 2 for _, c in nodes):
            break
        await asyncio.sleep(0.05)
    assert all(len(c.membership.alive_members()) == 2 for _, c in nodes)
    return nodes


async def _stop_cluster(nodes):
    for server, cluster in nodes:
        await cluster.stop()
        await server.stop()


async def test_handoff_moves_durable_backlog(tmp_path):
    from chanamq_tpu.client import AMQPClient

    nodes = await _start_cluster_pair(tmp_path)
    try:
        owner_name = nodes[0][1].queue_owner("/", "hq")
        owner = next(n for n in nodes if n[1].name == owner_name)
        other = next(n for n in nodes if n[1].name != owner_name)

        client = await AMQPClient.connect(
            "127.0.0.1", owner[0].bound_port)
        ch = await client.channel()
        await ch.confirm_select()
        await ch.queue_declare("hq", durable=True)
        for i in range(3):
            ch.basic_publish(b"h%d" % i, routing_key="hq",
                             properties=BasicProperties(delivery_mode=2))
        await ch.wait_unconfirmed_below(1, timeout=10)
        await asyncio.sleep(0.3)   # let the store writes settle

        resident_before = owner[0].broker.resident_bytes
        moved = await owner[1].handoff_queue("/", "hq", other[1].name)
        assert moved is True
        # holdership converges on every node
        for _ in range(100):
            if all(c.queue_owner("/", "hq") == other[1].name
                   for _, c in nodes):
                break
            await asyncio.sleep(0.05)
        assert all(c.queue_owner("/", "hq") == other[1].name
                   for _, c in nodes)
        # the origin dropped the queue and released its accounted bytes
        assert "hq" not in owner[0].broker.vhosts["/"].queues
        assert owner[0].broker.resident_bytes < resident_before
        # the target serves the full durable backlog (recovered from the
        # shared store), proxied transparently through the old owner
        ok = await ch.queue_declare("hq", passive=True)
        assert ok.message_count == 3
        msg = await ch.basic_get("hq")
        assert msg.body == b"h0"
        ch.basic_ack(msg.delivery_tag)
        await client.close()
    finally:
        await _stop_cluster(nodes)


async def test_handoff_refuses_unsafe_queues(tmp_path):
    from chanamq_tpu.client import AMQPClient

    nodes = await _start_cluster_pair(tmp_path)
    try:
        owner_name = nodes[0][1].queue_owner("/", "uq")
        owner = next(n for n in nodes if n[1].name == owner_name)
        other = next(n for n in nodes if n[1].name != owner_name)
        client = await AMQPClient.connect(
            "127.0.0.1", owner[0].bound_port)
        ch = await client.channel()
        await ch.queue_declare("uq")          # transient
        ch.basic_publish(b"t0", routing_key="uq")
        await asyncio.sleep(0.3)
        # a transient backlog is NOT recoverable by the target: refused
        assert not await owner[1].handoff_queue("/", "uq", other[1].name)
        assert all(c.queue_owner("/", "uq") == owner[1].name
                   for _, c in nodes)
        # unknown target: refused
        await ch.queue_purge("uq")
        await asyncio.sleep(0.2)
        assert not await owner[1].handoff_queue("/", "uq", "nope")
        await client.close()
    finally:
        await _stop_cluster(nodes)


async def test_handoff_rebinds_remote_consumer(tmp_path):
    from chanamq_tpu.client import AMQPClient

    nodes = await _start_cluster_pair(tmp_path)
    try:
        owner_name = nodes[0][1].queue_owner("/", "rq")
        owner = next(n for n in nodes if n[1].name == owner_name)
        other = next(n for n in nodes if n[1].name != owner_name)
        # consumer attaches through the NON-owner: the owner sees a
        # RemoteConsumer stub, the safe-to-move kind
        c_client = await AMQPClient.connect(
            "127.0.0.1", other[0].bound_port)
        cch = await c_client.channel()
        await cch.queue_declare("rq", durable=True)
        got = []

        def on_msg(msg):
            got.append(bytes(msg.body))
            cch.basic_ack(msg.delivery_tag)

        await cch.basic_consume("rq", on_msg)
        await asyncio.sleep(0.3)

        moved = await owner[1].handoff_queue("/", "rq", other[1].name)
        assert moved is True
        for _ in range(100):
            if all(c.queue_owner("/", "rq") == other[1].name
                   for _, c in nodes):
                break
            await asyncio.sleep(0.05)
        # after the move the consumer's node owns the queue; a publish
        # through the OLD owner must still reach the consumer
        p_client = await AMQPClient.connect(
            "127.0.0.1", owner[0].bound_port)
        pch = await p_client.channel()
        pch.basic_publish(b"after-move", routing_key="rq")
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.05)
        assert got == [b"after-move"]
        await p_client.close()
        await c_client.close()
    finally:
        await _stop_cluster(nodes)


async def test_control_load_rpc(tmp_path):
    nodes = await _start_cluster_pair(tmp_path)
    try:
        reply = await nodes[0][1]._call(
            nodes[1][1].name, "control.load", {}, timeout_s=2.0)
        assert reply["node"] == nodes[1][1].name
        assert reply["load"] == 0.0
        # with a control service attached the RPC reports its EWMA
        control = ControlService(nodes[1][0].broker, rebalance=False,
                                 prefetch=False)
        control.load_rate = 123.5
        try:
            reply = await nodes[0][1]._call(
                nodes[1][1].name, "control.load", {}, timeout_s=2.0)
            assert reply["load"] == 123.5
        finally:
            await control.stop()
    finally:
        await _stop_cluster(nodes)


# ---------------------------------------------------------------------------
# /admin/control surface
# ---------------------------------------------------------------------------


async def _admin_req(port: int, path: str, method: str = "GET",
                     body: bytes = b"") -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    head = (f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode()
    writer.write(head + body)
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(262144), 5)
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), (json.loads(payload) if payload else {})


async def test_admin_control_endpoints():
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.rest.admin import AdminServer

    server = BrokerServer(broker=spike_broker(), host="127.0.0.1",
                          port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    control = None
    try:
        # disabled: GET reports it, configure conflicts
        status, body = await _admin_req(admin.bound_port, "/admin/control")
        assert status == 200 and body == {"enabled": False}
        status, _ = await _admin_req(
            admin.bound_port, "/admin/control/configure", "POST", b"{}")
        assert status == 409

        control = ControlService(server.broker, dry_run=True,
                                 rebalance=False, prefetch=False)
        await control.step(1.0)
        status, body = await _admin_req(
            admin.bound_port, "/admin/control?log=4")
        assert status == 200
        assert body["enabled"] and body["dry_run"]
        assert body["tick"] == 1
        assert body["counters"]["ticks"] == 1
        # the rollout flip: dry-run off at runtime, no restart
        status, body = await _admin_req(
            admin.bound_port, "/admin/control/configure", "POST",
            json.dumps({"dry-run": False, "rebalance": True}).encode())
        assert status == 200
        assert body["ok"] and body["dry_run"] is False
        assert body["features"]["rebalance"] is True
        assert control.dry_run is False

        # control counters + floor gauge land on the Prometheus surface
        status, _ = await _admin_req(admin.bound_port, "/admin/control")
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", admin.bound_port)
        writer.write(b"GET /metrics HTTP/1.1\r\n"
                     b"Host: localhost\r\n\r\n")
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(262144), 5)
        writer.close()
        text = raw.decode(errors="replace")
        assert "chanamq_control_ticks" in text
        assert "# TYPE chanamq_control_decisions counter" in text
        assert "chanamq_control_floor" in text
    finally:
        if control is not None:
            await control.stop()
        await admin.stop()
        await server.stop()
