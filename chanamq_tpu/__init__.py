"""chanamq_tpu — a from-scratch AMQP 0-9-1 message broker framework.

A clean-room rebuild of the capability set of ChanaMQ (reference:
/root/reference, Scala/Akka): full AMQP 0-9-1 wire codec, broker semantics
(exchanges, queues, QoS, acks, confirms, TTL), pluggable persistence, and a
multi-host cluster layer — host-native by design (the reference has no tensor
compute path; see SURVEY.md §7.1), with compiled C++ hot paths for frame
parsing and topic routing, and an auxiliary JAX analytics subsystem that sits
off the message path.

Layer map (mirrors SURVEY.md §1):
  chanamq_tpu.amqp     — L0 wire codec + protocol model
  chanamq_tpu.broker   — L2 connection engine + L3 broker entities
  chanamq_tpu.store    — L5 persistence (memory / sqlite)
  chanamq_tpu.cluster  — L4 multi-host services (membership, ownership, RPC, ids)
  chanamq_tpu.rest     — L6 admin API
  chanamq_tpu.client   — conformance/bench client
  chanamq_tpu.models/ops/parallel — auxiliary JAX analytics (off the message path)
"""

__version__ = "0.4.0"
