"""Tensorized router tests: randomized parity fuzzing of the compiled
kernels against the Python matcher oracles (and the native C++ trie when
built), engine/invalidation behavior, and the end-to-end deferred publish
path through a real connection.

The parity gate of ISSUE 13: TopicMatcher, NativeTopicMatcher, and the
tensor router must return identical destination sets over thousands of
generated bind/unbind/route sequences, including ``#`` edge cases."""

import asyncio
import random

import pytest

from chanamq_tpu import native_ext
from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.matchers import (
    DirectMatcher, FanoutMatcher, HeadersMatcher, TopicMatcher,
)
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.router import compile as rcompile
from chanamq_tpu.router.compile import Uncompilable, compile_exchange, route_batch

WORDS = ["a", "b", "c", "dd", "e1", "", "orders", "x"]


def _rand_pattern(rng):
    return ".".join(
        rng.choice(WORDS + ["*", "#"]) for _ in range(rng.randint(1, 6)))


def _rand_key(rng):
    return ".".join(rng.choice(WORDS) for _ in range(rng.randint(0, 6)))


def _route_all_backends(compiled, items):
    """Route via numpy and jit; assert the two kernels agree, return one.

    The result memo is cleared between backends — topic results are
    memoized by bare routing key, so without the clear the second
    backend would serve every answer from the first backend's kernel."""
    py = route_batch(compiled, items, "python")
    compiled._route_memo.clear()
    jx = route_batch(compiled, items, "jax")
    assert [set(a) for a in py] == [set(b) for b in jx]
    return py


# ---------------------------------------------------------------------------
# parity fuzz: compiled kernels vs Python trie vs native trie
# ---------------------------------------------------------------------------


def test_topic_parity_fuzz():
    """Thousands of randomized bind/unbind/route sequences: the Python
    trie, the native trie (when built), and both tensor backends must be
    destination-set identical."""
    rng = random.Random(0xC0FFEE)
    native = native_ext.available()
    for trial in range(150):
        py = TopicMatcher()
        nat = native_ext.NativeTopicMatcher() if native else None
        bound = []
        for _ in range(rng.randint(1, 30)):
            pattern, queue = _rand_pattern(rng), f"q{rng.randint(0, 9)}"
            py.bind(pattern, queue)
            if nat is not None:
                nat.bind(pattern, queue)
            bound.append((pattern, queue))
        # interleave some unbinds so pruning paths run too
        for _ in range(rng.randint(0, len(bound) // 2)):
            pattern, queue = rng.choice(bound)
            py.unbind(pattern, queue)
            if nat is not None:
                nat.unbind(pattern, queue)
        try:
            compiled = compile_exchange("topic", py.bindings())
        except Uncompilable:
            # multi-# pattern: the tensor router would fall back to the
            # matcher; nothing to diff, but native must still agree
            if nat is not None:
                for _ in range(10):
                    key = _rand_key(rng)
                    assert nat.route(key) == py.route(key), key
            continue
        keys = [_rand_key(rng) for _ in range(rng.randint(1, 40))]
        got = _route_all_backends(compiled, [(k, None) for k in keys])
        for key, names in zip(keys, got):
            oracle = py.route(key)
            assert set(names) == oracle, (key, sorted(py._patterns))
            if nat is not None:
                assert nat.route(key) == oracle, key


def test_topic_hash_edge_cases():
    """The '#' grammar corners: zero-word match, leading/trailing/middle
    '#', '#' vs empty words, and the lone-'#' always-match fold."""
    cases = [
        (["#"], ["", "a", "a.b.c"]),
        (["a.#"], ["a", "a.b", "a.b.c", "b.a", ""]),
        (["#.a"], ["a", "b.a", "a.a.a", "a.b"]),
        (["a.#.b"], ["a.b", "a.x.b", "a.x.y.b", "a", "b"]),
        (["*.#"], ["", "a", "a.b", "a.b.c"]),
        (["#.*"], ["", "a", "a.b"]),
        (["..#"], ["", ".", "..", "..a", ".a."]),
        (["#.b.*"], ["b.a", "x.b.a", "b.b.b", "b"]),
        (["a.*.c", "a.#"], ["a.b.c", "a.c", "a.b.c.d"]),
    ]
    for patterns, keys in cases:
        py = TopicMatcher()
        for i, pattern in enumerate(patterns):
            py.bind(pattern, f"q{i}")
        compiled = compile_exchange("topic", py.bindings())
        got = _route_all_backends(compiled, [(k, None) for k in keys])
        for key, names in zip(keys, got):
            assert set(names) == py.route(key), (patterns, key)


def test_headers_parity_fuzz():
    rng = random.Random(0xBEEF)
    values = [1, "s", True, 2.5, "t", 0, False]
    for trial in range(150):
        m = HeadersMatcher()
        for _ in range(rng.randint(1, 15)):
            args = {f"h{rng.randint(0, 4)}": rng.choice(values)
                    for _ in range(rng.randint(0, 3))}
            if rng.random() < 0.8:
                args["x-match"] = rng.choice(["all", "any"])
            m.bind("", f"q{rng.randint(0, 6)}", args)
        compiled = compile_exchange("headers", m.bindings())
        msgs = []
        for _ in range(25):
            msgs.append({f"h{rng.randint(0, 5)}": rng.choice(values)
                         for _ in range(rng.randint(0, 4))})
        got = _route_all_backends(compiled, [("", h) for h in msgs])
        for headers, names in zip(msgs, got):
            assert set(names) == m.route("", headers), headers


def test_headers_unhashable_binding_uncompilable():
    m = HeadersMatcher()
    m.bind("", "q0", {"x-match": "all", "h": [1, 2]})
    with pytest.raises(Uncompilable):
        compile_exchange("headers", m.bindings())


def test_headers_unhashable_message_value_skipped():
    m = HeadersMatcher()
    m.bind("", "q0", {"x-match": "any", "h": 1, "g": 2})
    compiled = compile_exchange("headers", m.bindings())
    headers = {"h": [1, 2], "g": 2}
    got = _route_all_backends(compiled, [("", headers)])
    assert set(got[0]) == m.route("", headers) == {"q0"}


def test_direct_fanout_compile():
    d = DirectMatcher()
    d.bind("k1", "a")
    d.bind("k1", "b")
    d.bind("k2", "c")
    cd = compile_exchange("direct", d.bindings())
    got = route_batch(cd, [("k1", None), ("k2", None), ("zzz", None)])
    assert [set(g) for g in got] == [{"a", "b"}, {"c"}, set()]
    f = FanoutMatcher()
    f.bind("ignored", "a")
    f.bind("", "b")
    cf = compile_exchange("fanout", f.bindings())
    got = route_batch(cf, [("anything", None), ("", None)])
    assert [set(g) for g in got] == [{"a", "b"}, {"a", "b"}]


def test_multi_hash_uncompilable_and_caps():
    m = TopicMatcher()
    m.bind("a.#.b.#", "q0")
    with pytest.raises(Uncompilable):
        compile_exchange("topic", m.bindings())
    m2 = TopicMatcher()
    for i in range(5):
        m2.bind(f"w{i}.*", f"q{i}")
    with pytest.raises(Uncompilable):
        compile_exchange("topic", m2.bindings(), max_wildcards=3)
    with pytest.raises(Uncompilable):
        compile_exchange("topic", m2.bindings(), max_queues=2)
    # exact patterns never count against the wildcard cap
    m3 = TopicMatcher()
    for i in range(50):
        m3.bind(f"exact.{i}", f"q{i}")
    m3.bind("wild.*", "qw")
    compiled = compile_exchange("topic", m3.bindings(), max_wildcards=1)
    got = _route_all_backends(
        compiled, [("exact.7", None), ("wild.x", None), ("nope", None)])
    assert [set(g) for g in got] == [{"q7"}, {"qw"}, set()]


# ---------------------------------------------------------------------------
# engine: incremental recompile, generations, fallback, verify mode
# ---------------------------------------------------------------------------


def _mk_broker_with_topic(loop):
    broker = Broker()
    loop.run_until_complete(broker.create_vhost("/"))
    loop.run_until_complete(broker.declare_exchange("/", "ex", "topic"))
    loop.run_until_complete(broker.declare_queue("/", "q1"))
    loop.run_until_complete(broker.declare_queue("/", "q2"))
    loop.run_until_complete(broker.bind_queue("/", "q1", "ex", "a.*"))
    loop.run_until_complete(broker.bind_queue("/", "q2", "ex", "a.b"))
    return broker


def _entries(pairs):
    props = BasicProperties()
    return [(ex, rk, props, b"x", None, None, False) for ex, rk in pairs]


def test_engine_route_and_incremental_recompile(event_loop):
    broker = _mk_broker_with_topic(event_loop)
    router = broker.router
    router.min_batch = 1
    routes, _, _ = router.route_pending("/", _entries([("ex", "a.b")] * 4))
    assert sorted(q.name for q in routes[0]) == ["q1", "q2"]
    gen1 = router.generation
    assert broker.metrics.router_compiles == 1
    # routing again: same snapshot, no recompile
    router.route_pending("/", _entries([("ex", "a.c")]))
    assert router.generation == gen1
    # bind marks exactly this exchange dirty; next flush recompiles
    event_loop.run_until_complete(
        broker.bind_queue("/", "q2", "ex", "c.#"))
    routes, _, _ = router.route_pending("/", _entries([("ex", "c.x.y")]))
    assert [q.name for q in routes[0]] == ["q2"]
    assert router.generation == gen1 + 1
    assert broker.metrics.router_compiles == 2


def test_engine_python_backend_and_fallback(event_loop):
    broker = _mk_broker_with_topic(event_loop)
    router = broker.router
    router.min_batch = 1
    router.backend = "python"
    routes, _, _ = router.route_pending("/", _entries([("ex", "a.z")]))
    assert [q.name for q in routes[0]] == ["q1"]
    # an uncompilable table falls back to the matcher transparently
    event_loop.run_until_complete(
        broker.bind_queue("/", "q1", "ex", "#.mid.#"))
    before = broker.metrics.router_fallback_msgs
    routes, _, _ = router.route_pending("/", _entries([("ex", "x.mid.y")]))
    assert [q.name for q in routes[0]] == ["q1"]
    assert broker.metrics.router_fallback_msgs == before + 1


def test_engine_min_batch_falls_back(event_loop):
    broker = _mk_broker_with_topic(event_loop)
    router = broker.router
    router.min_batch = 8
    before = broker.metrics.router_fallback_msgs
    routes, _, _ = router.route_pending("/", _entries([("ex", "a.b")] * 3))
    assert broker.metrics.router_fallback_msgs == before + 3
    assert sorted(q.name for q in routes[0]) == ["q1", "q2"]
    assert broker.metrics.router_batches == 0


def test_engine_verify_mode_clean(event_loop):
    broker = _mk_broker_with_topic(event_loop)
    router = broker.router
    router.min_batch = 1
    router.verify = True
    router.route_pending(
        "/", _entries([("ex", k) for k in ("a.b", "a.x", "q", "", "a.b.c")]))
    assert broker.metrics.router_parity_mismatches == 0


def test_engine_defer_ok_gates(event_loop):
    broker = _mk_broker_with_topic(event_loop)
    router = broker.router
    assert router.defer_ok("/", "ex")
    assert not router.defer_ok("/", "")           # default exchange
    assert not router.defer_ok("/", "missing")    # no such exchange
    event_loop.run_until_complete(
        broker.declare_exchange("/", "alt-ex", "topic",
                                arguments={"alternate-exchange": "ex"}))
    assert not router.defer_ok("/", "alt-ex")     # alternate semantics
    event_loop.run_until_complete(broker.declare_exchange("/", "e2", "fanout"))
    assert router.defer_ok("/", "e2")
    event_loop.run_until_complete(
        broker.bind_exchange("/", "ex", "e2", "k"))
    assert router.defer_ok("/", "e2")             # e2e closure compiles
    # wildcard hop over a wildcard sub-closure cannot flatten: the walk stays
    event_loop.run_until_complete(broker.declare_exchange("/", "e3", "topic"))
    event_loop.run_until_complete(
        broker.bind_exchange("/", "ex", "e3", "x.*"))
    assert not router.defer_ok("/", "e3")         # uncompilable e2e graph


# ---------------------------------------------------------------------------
# end-to-end: deferred fused publishes through a live connection
# ---------------------------------------------------------------------------

pytest_plugins: list = []


@pytest.fixture
def server(event_loop):
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    event_loop.run_until_complete(srv.start())
    yield srv
    event_loop.run_until_complete(srv.stop())


def test_deferred_publish_end_to_end(event_loop, server):
    async def run():
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.exchange_declare("ex", "topic")
        await ch.queue_declare("q1")
        await ch.queue_bind("q1", "ex", "a.*.c")
        await ch.queue_bind("q1", "ex", "exact.key")
        await ch.confirm_select()
        for _ in range(100):
            ch.basic_publish(b"m", exchange="ex", routing_key="a.b.c")
        for _ in range(20):
            ch.basic_publish(b"m", exchange="ex", routing_key="miss")
        await ch.wait_unconfirmed_below(1)
        await c.close()

    event_loop.run_until_complete(run())
    metrics = server.broker.metrics
    assert metrics.router_batch_msgs >= 100
    assert metrics.router_batches >= 1
    assert metrics.router_parity_mismatches == 0
    q1 = server.broker.vhosts["/"].queues["q1"]
    assert q1.message_count == 100


def test_deferred_publish_fifo_with_nondeferrable(event_loop, server):
    """Deferred (topic) and non-deferrable (default-exchange) publishes on
    one channel must land in queue order — the flush-before-publish rule."""
    async def run():
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.exchange_declare("ex", "topic")
        await ch.queue_declare("q")
        await ch.queue_bind("q", "ex", "k.*")
        await ch.confirm_select()
        for i in range(30):
            if i % 3 == 2:
                # default exchange: never deferred
                ch.basic_publish(str(i).encode(), exchange="",
                                 routing_key="q")
            else:
                ch.basic_publish(str(i).encode(), exchange="ex",
                                 routing_key="k.x")
        await ch.wait_unconfirmed_below(1)
        got = []
        while True:
            msg = await ch.basic_get("q", no_ack=True)
            if msg is None:
                break
            got.append(int(msg.body))
        assert got == list(range(30))
        await c.close()

    event_loop.run_until_complete(run())


def test_router_disabled_still_routes(event_loop):
    async def run():
        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
        await srv.start()
        srv.broker.router = None  # runtime-off: inline publish_sync path
        try:
            c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            ch = await c.channel()
            await ch.exchange_declare("ex", "topic")
            await ch.queue_declare("q")
            await ch.queue_bind("q", "ex", "a.#")
            await ch.confirm_select()
            for _ in range(25):
                ch.basic_publish(b"m", exchange="ex", routing_key="a.b")
            await ch.wait_unconfirmed_below(1)
            assert srv.broker.vhosts["/"].queues["q"].message_count == 25
            assert srv.broker.metrics.router_batches == 0
            await c.close()
        finally:
            await srv.stop()

    event_loop.run_until_complete(run())
