"""Federation tests: segment shipping, cursor mirroring, DLX/Tx forwarding.

Covers the chanamq_tpu/federation/ contract: sealed segments ship to the
remote mirror CRC-checked and resume from the receiver's position (the
mirror is the source of truth — duplicates ack idempotently, gaps answer
with a resync hint), named-cursor commits mirror so a consumer group can
fail over, dead-letter publishes to federated exchanges forward a copy,
committed transactions arrive as one idempotent batch, and the whole
surface is observable (admin endpoint, Prometheus gauges, SLI samples).
"""

import asyncio
import json
import zlib

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster.dataplane import _put_ss
from chanamq_tpu.cluster.rpc import RpcError
from chanamq_tpu.federation import FederationService, links_from_json
from chanamq_tpu.federation.link import _parse_gap
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.streams.segment import StreamRecord, pack_records

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)
# small segments so a handful of publishes seals (and ships) several
STREAM_SMALL = {"x-queue-type": "stream",
                "x-stream-max-segment-size-bytes": 256}


async def eventually(predicate, timeout=10.0, what="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        assert asyncio.get_event_loop().time() < deadline, \
            f"timed out waiting for {what}"
        await asyncio.sleep(0.02)


async def start_pair(queues=("fq",), exchanges=()):
    """Two independent brokers joined by one A->B link ("to-b")."""
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="cluster-b", port=0)
    await fed_b.start()
    a_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await a_srv.start()
    fed_a = FederationService(
        a_srv.broker, node_name="cluster-a", port=0,
        retry_s=0.05, idle_s=0.02,
        links=[{"name": "to-b", "host": "127.0.0.1", "port": fed_b.port,
                "queues": list(queues), "exchanges": list(exchanges)}])
    await fed_a.start()
    return a_srv, fed_a, b_srv, fed_b


async def stop_pair(a_srv, fed_a, b_srv, fed_b):
    await fed_a.stop()
    await a_srv.stop()
    await fed_b.stop()
    await b_srv.stop()


async def collect(ch, queue, n, *, offset="first", tag="", ack=True,
                  timeout=10.0):
    got: list = []
    done = asyncio.get_event_loop().create_future()

    def on_msg(msg):
        if len(got) >= n:
            return
        got.append(msg)
        if ack:
            ch.basic_ack(msg.delivery_tag)
        if len(got) >= n and not done.done():
            done.set_result(None)

    used_tag = await ch.basic_consume(
        queue, on_msg, consumer_tag=tag,
        arguments={"x-stream-offset": offset})
    await asyncio.wait_for(done, timeout)
    await ch.basic_cancel(used_tag)
    return got


def _ship_payload(vhost, qname, base, last, blob, crc=None, token=""):
    head = bytearray()
    _put_ss(head, token)
    _put_ss(head, vhost)
    _put_ss(head, qname)
    head += base.to_bytes(8, "big")
    head += last.to_bytes(8, "big")
    head += (0).to_bytes(8, "big")   # first_ts_ms
    head += (0).to_bytes(8, "big")   # last_ts_ms
    crc = zlib.crc32(blob) & 0xFFFFFFFF if crc is None else crc
    head += crc.to_bytes(4, "big")
    head += len(blob).to_bytes(4, "big")
    return memoryview(bytes(head) + blob)


def _tx_payload(link, epoch, seq, publishes, token=""):
    """FED_TX wire: publishes is [(exchange, rkey, header, body), ...]."""
    buf = bytearray()
    _put_ss(buf, token)
    _put_ss(buf, link)
    _put_ss(buf, epoch)
    buf += seq.to_bytes(8, "big")
    _put_ss(buf, "/")
    buf += len(publishes).to_bytes(4, "big")
    for exchange, rkey, header, body in publishes:
        _put_ss(buf, exchange)
        _put_ss(buf, rkey)
        buf += len(header).to_bytes(4, "big")
        buf += header
        buf += len(body).to_bytes(4, "big")
        buf += body
    return memoryview(bytes(buf))


def _pub_payload(link, epoch, seq, exchange, rkey, header, body, token=""):
    buf = bytearray()
    _put_ss(buf, token)
    _put_ss(buf, link)
    _put_ss(buf, epoch)
    buf += seq.to_bytes(8, "big")
    _put_ss(buf, "/")
    _put_ss(buf, exchange)
    _put_ss(buf, rkey)
    buf += len(header).to_bytes(4, "big")
    buf += header
    buf += len(body).to_bytes(4, "big")
    buf += body
    return memoryview(bytes(buf))


def _records(base, last, prefix="r"):
    header = BasicProperties(delivery_mode=2).encode_header(8)
    return [StreamRecord(i, 1000 + i, "", "q", header,
                         f"{prefix}{i:06d}".encode())
            for i in range(base, last + 1)]


# ---------------------------------------------------------------------------
# config parsing
# ---------------------------------------------------------------------------


async def test_links_from_json_validation():
    assert links_from_json("") == []
    assert links_from_json("   ") == []
    specs = links_from_json(
        '[{"name": "west", "host": "h", "port": 1, "queues": ["q"]}]')
    assert specs[0]["name"] == "west" and specs[0]["queues"] == ["q"]
    with pytest.raises(ValueError):
        links_from_json('{"name": "not-a-list"}')
    with pytest.raises(ValueError):
        links_from_json('[{"name": "x", "host": "h"}]')  # missing port
    with pytest.raises(ValueError):
        links_from_json('["just-a-string"]')


# ---------------------------------------------------------------------------
# segment shipping + cursor mirroring
# ---------------------------------------------------------------------------


async def test_sealed_segments_ship_to_mirror():
    a_srv, fed_a, b_srv, fed_b = await start_pair()
    try:
        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare("fq", durable=True, arguments=STREAM_SMALL)
        for i in range(30):
            ch.basic_publish(f"f{i:06d}".encode(), routing_key="fq",
                             properties=PERSISTENT)
        await ch.wait_unconfirmed_below(1, timeout=15)
        a_queue = a_srv.broker.get_queue("/", "fq")
        sealed_tail = a_queue._active_base  # unsealed records don't ship
        assert sealed_tail > 1, "expected at least one sealed segment"
        await eventually(
            lambda: ("fq" in b_srv.broker.vhosts["/"].queues
                     and b_srv.broker.vhosts["/"].queues["fq"].next_offset
                     >= sealed_tail),
            what="mirror catch-up")
        # the mirror's content is byte-for-byte the shipped prefix
        b_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        b_ch = await b_conn.channel()
        await b_ch.basic_qos(prefetch_count=64)
        got = await collect(b_ch, "fq", sealed_tail - 1)
        assert [bytes(m.body).decode() for m in got] == \
            [f"f{i:06d}" for i in range(sealed_tail - 1)]
        metrics = a_srv.broker.metrics
        assert metrics.federation_segments_shipped >= 1
        assert metrics.federation_segment_bytes > 0
        assert b_srv.broker.metrics.federation_segments_applied >= 1
        assert any(ev == "link.up" for ev, _ in fed_a.events)
        await b_conn.close()
        await conn.close()
    finally:
        await stop_pair(a_srv, fed_a, b_srv, fed_b)


async def test_cursor_commits_mirror_to_remote():
    a_srv, fed_a, b_srv, fed_b = await start_pair()
    try:
        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare("fq", durable=True, arguments=STREAM_SMALL)
        for i in range(20):
            ch.basic_publish(f"f{i:06d}".encode(), routing_key="fq",
                             properties=PERSISTENT)
        await ch.wait_unconfirmed_below(1, timeout=15)
        ch2 = await conn.channel()
        await ch2.basic_qos(prefetch_count=64)
        await collect(ch2, "fq", 10, tag="group-1")
        # stream offsets are 1-based: the 10th record lives at offset 10,
        # and the coalesced mirror write carries the max committed offset
        await eventually(
            lambda: ("fq" in b_srv.broker.vhosts["/"].queues
                     and b_srv.broker.vhosts["/"].queues["fq"]
                     .committed.get("group-1") == 10),
            what="cursor mirror")
        assert b_srv.broker.metrics.federation_cursors_mirrored >= 1
        assert a_srv.broker.metrics.federation_cursors_shipped >= 1
        assert any(ev == "cursor.mirrored" for ev, _ in fed_b.events)
        await conn.close()
    finally:
        await stop_pair(a_srv, fed_a, b_srv, fed_b)


# ---------------------------------------------------------------------------
# receiver-side ship protocol: duplicate / gap / CRC
# ---------------------------------------------------------------------------


async def test_ship_duplicate_acks_idempotently_and_gap_resyncs():
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        blob = pack_records(_records(1, 3))
        reply = await fed_b._h_ship(_ship_payload("/", "mq", 1, 3, blob))
        assert int.from_bytes(reply[0], "big") == 4
        # duplicate: same segment again acks with the mirror's position
        # instead of failing, so a shipper that lost our ack fast-forwards
        reply = await fed_b._h_ship(_ship_payload("/", "mq", 1, 3, blob))
        assert int.from_bytes(reply[0], "big") == 4
        assert b_srv.broker.metrics.federation_duplicate_segments == 1
        assert b_srv.broker.vhosts["/"].queues["mq"].next_offset == 4
        # gap: a segment past the mirror's next offset answers the resync
        # hint (the shipper parses "gap: <next>" off the error reply)
        far = pack_records(_records(10, 12))
        with pytest.raises(RpcError) as exc:
            await fed_b._h_ship(_ship_payload("/", "mq", 10, 12, far))
        assert exc.value.code == "gap" and exc.value.message == "4"
        assert _parse_gap(RpcError("remote", "gap: 4")) == 4
        assert _parse_gap(RpcError("remote", "boom")) is None
    finally:
        await fed_b.stop()
        await b_srv.stop()


async def test_ship_crc_mismatch_rejected():
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        blob = pack_records(_records(1, 2))
        with pytest.raises(RpcError) as exc:
            await fed_b._h_ship(
                _ship_payload("/", "mq", 1, 2, blob, crc=0xDEADBEEF))
        assert exc.value.code == "crc"
        assert b_srv.broker.metrics.federation_crc_failures == 1
        # nothing applied: the mirror still expects offset 1
        assert b_srv.broker.vhosts["/"].queues["mq"].next_offset == 1
    finally:
        await fed_b.stop()
        await b_srv.stop()


async def test_ship_rejects_bad_range_claims():
    """CRC only guards transport corruption: a shipper claiming a range
    its blob doesn't cover must be refused before the splice, or the
    mirror's offset space corrupts permanently."""
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        # inverted range: last < base
        blob = pack_records(_records(1, 2))
        with pytest.raises(RpcError) as exc:
            await fed_b._h_ship(_ship_payload("/", "mq", 5, 1, blob))
        assert exc.value.code == "bad-range"
        # records outside the claimed range: blob holds offsets 1..5 but
        # the header claims only 1..2 (would advance next_offset past
        # records the mirror never stored)
        wide = pack_records(_records(1, 5))
        with pytest.raises(RpcError) as exc:
            await fed_b._h_ship(_ship_payload("/", "mq", 1, 2, wide))
        assert exc.value.code == "bad-range"
        assert b_srv.broker.metrics.federation_invalid_segments == 2
        # nothing spliced: the mirror still expects offset 1, and a
        # well-formed ship (sparse is fine — compaction holes are legal)
        # goes through afterwards
        sparse = pack_records([r for r in _records(1, 4) if r.offset != 2])
        reply = await fed_b._h_ship(_ship_payload("/", "mq", 1, 4, sparse))
        assert int.from_bytes(reply[0], "big") == 5
    finally:
        await fed_b.stop()
        await b_srv.stop()


async def test_auth_token_gates_every_handler():
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0,
                              auth_token="sesame")
    await fed_b.start()
    try:
        with pytest.raises(RpcError) as exc:
            await fed_b._h_hello({"link": "x", "node": "a"})
        assert exc.value.code == "auth"
        with pytest.raises(RpcError):
            await fed_b._h_resume({"vhost": "/", "queue": "mq",
                                   "token": "wrong"})
        blob = pack_records(_records(1, 2))
        with pytest.raises(RpcError) as exc:
            await fed_b._h_ship(_ship_payload("/", "mq", 1, 2, blob))
        assert exc.value.code == "auth"
        body = b"x"
        header = BasicProperties().encode_header(len(body))
        with pytest.raises(RpcError):
            await fed_b._h_tx(_tx_payload(
                "l", "e", 1, [("", "q", header, body)], token="wrong"))
        with pytest.raises(RpcError):
            await fed_b._h_publish(_pub_payload(
                "l", "e", 1, "", "q", header, body))
        assert b_srv.broker.metrics.federation_auth_failures == 5
        # nothing auto-declared on refused calls
        assert "mq" not in b_srv.broker.vhosts["/"].queues
        # the right token passes
        reply = await fed_b._h_ship(
            _ship_payload("/", "mq", 1, 2, blob, token="sesame"))
        assert int.from_bytes(reply[0], "big") == 3
    finally:
        await fed_b.stop()
        await b_srv.stop()


async def test_authed_link_ships_end_to_end():
    """A link configured with the remote's token comes up and ships;
    the token rides fed.hello, the cursor mirror and the data plane."""
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="cluster-b", port=0,
                              auth_token="sesame")
    await fed_b.start()
    a_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await a_srv.start()
    fed_a = FederationService(
        a_srv.broker, node_name="cluster-a", port=0,
        retry_s=0.05, idle_s=0.02,
        links=[{"name": "to-b", "host": "127.0.0.1", "port": fed_b.port,
                "queues": ["fq"], "token": "sesame"}])
    await fed_a.start()
    try:
        await eventually(lambda: fed_a.links[0].state == "up",
                         what="authed link up")
        # and a wrong token never comes up (refused at fed.hello)
        fed_bad = FederationService(
            a_srv.broker, node_name="cluster-bad", port=0,
            retry_s=0.05, idle_s=0.02,
            links=[{"name": "to-b", "host": "127.0.0.1",
                    "port": fed_b.port, "queues": ["fq"],
                    "token": "wrong"}])
        await fed_bad.start()
        bad = fed_bad.links[0]
        await eventually(
            lambda: bad.last_error is not None and "auth" in bad.last_error,
            what="bad-token link refused")
        assert bad.state == "down"
        await fed_bad.stop()
    finally:
        await fed_a.stop()
        await a_srv.stop()
        await fed_b.stop()
        await b_srv.stop()


async def test_outbox_sheds_publishes_before_tx_batches(monkeypatch):
    """At the outbox bound, single DLX forwards are shed before whole
    committed Tx batches, and drops are counted per kind."""
    from chanamq_tpu.federation import link as link_module

    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed = FederationService(
        b_srv.broker, node_name="b", port=0,
        links=[{"name": "l", "host": "127.0.0.1", "port": 1,
                "queues": []}])
    link = fed.links[0]  # never started: staging is pure local state
    try:
        monkeypatch.setattr(link_module, "_OUTBOX_MAX", 4)
        header, body = b"h", b"b"
        link.queue_tx([("ex", "rk", header, body)])
        link.queue_publish("ex", "rk", header, body)
        link.queue_tx([("ex", "rk", header, body)])
        link.queue_publish("ex", "rk", header, body)
        # outbox full at 4: the next stage sheds the OLDEST PUBLISH,
        # not the older tx batch at the head
        link.queue_tx([("ex", "rk", header, body)])
        kinds = [item[0] for item in link.outbox]
        assert kinds == ["tx", "tx", "publish", "tx"]
        metrics = b_srv.broker.metrics
        assert metrics.federation_outbox_dropped_publish == 1
        assert metrics.federation_outbox_dropped_tx == 0
        assert metrics.federation_outbox_dropped == 1
        # further pressure sheds the remaining publish first; once the
        # outbox is all tx, the oldest batch goes — counted as such
        link.queue_tx([("ex", "rk", header, body)])
        link.queue_tx([("ex", "rk", header, body)])
        assert [item[0] for item in link.outbox] == ["tx"] * 4
        assert metrics.federation_outbox_dropped_publish == 2
        assert metrics.federation_outbox_dropped_tx == 1
        assert metrics.federation_outbox_dropped == 3
    finally:
        await b_srv.stop()


async def test_resume_rejects_non_stream_queue():
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        await b_srv.broker.declare_queue("/", "classic", durable=False)
        with pytest.raises(RpcError) as exc:
            await fed_b._h_resume({"vhost": "/", "queue": "classic"})
        assert exc.value.code == "bad-type"
    finally:
        await fed_b.stop()
        await b_srv.stop()


# ---------------------------------------------------------------------------
# DLX forwarding + federated Tx
# ---------------------------------------------------------------------------


async def test_dead_letter_forwards_to_federated_exchange():
    a_srv, fed_a, b_srv, fed_b = await start_pair(
        queues=(), exchanges=("fed_dlx",))
    try:
        # remote cluster owns the DLX target
        b_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        b_ch = await b_conn.channel()
        await b_ch.exchange_declare("fed_dlx", "fanout")
        await b_ch.queue_declare("dead")
        await b_ch.queue_bind("dead", "fed_dlx", "")
        # local cluster dead-letters into it via maxlen overflow; the
        # exchange exists only remotely, so the local copy drops NOT_FOUND
        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await conn.channel()
        await ch.queue_declare("src", arguments={
            "x-max-length": 1, "x-dead-letter-exchange": "fed_dlx"})
        ch.basic_publish(b"first", routing_key="src")
        ch.basic_publish(b"second", routing_key="src")
        await eventually(
            lambda: a_srv.broker.metrics.federation_dlx_forwarded >= 1,
            what="dlx staged")
        msg = None

        async def fetch():
            nonlocal msg
            msg = await b_ch.basic_get("dead", no_ack=True)
            return msg is not None

        deadline = asyncio.get_event_loop().time() + 10
        while msg is None:
            assert asyncio.get_event_loop().time() < deadline, \
                "forwarded dead-letter never arrived"
            await fetch()
            if msg is None:
                await asyncio.sleep(0.05)
        assert bytes(msg.body) == b"first"
        # x-death history survives the wire (raw header forwarded)
        death = msg.properties.headers["x-death"][0]
        assert death["queue"] == "src" and death["reason"] == "maxlen"
        await conn.close()
        await b_conn.close()
    finally:
        await stop_pair(a_srv, fed_a, b_srv, fed_b)


async def test_tx_commit_ships_one_batch():
    a_srv, fed_a, b_srv, fed_b = await start_pair(
        queues=(), exchanges=("fed_ex",))
    try:
        b_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        b_ch = await b_conn.channel()
        await b_ch.exchange_declare("fed_ex", "fanout")
        await b_ch.queue_declare("txq")
        await b_ch.queue_bind("txq", "fed_ex", "")
        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await conn.channel()
        await ch.exchange_declare("fed_ex", "fanout")
        await ch.tx_select()
        for i in range(3):
            ch.basic_publish(f"tx{i}".encode(), exchange="fed_ex",
                             routing_key="")
        await asyncio.sleep(0.1)
        # uncommitted publishes must not cross the link
        assert a_srv.broker.metrics.federation_tx_batches == 0
        await ch.tx_commit()
        assert a_srv.broker.metrics.federation_tx_batches == 1
        assert a_srv.broker.metrics.federation_tx_publishes == 3
        await eventually(
            lambda: b_srv.broker.metrics.federation_tx_applied == 1,
            what="tx batch applied")
        got = []
        while len(got) < 3:
            msg = await b_ch.basic_get("txq", no_ack=True)
            if msg is None:
                await asyncio.sleep(0.02)
                continue
            got.append(bytes(msg.body).decode())
        assert got == ["tx0", "tx1", "tx2"]
        await conn.close()
        await b_conn.close()
    finally:
        await stop_pair(a_srv, fed_a, b_srv, fed_b)


async def test_tx_batch_replay_is_idempotent():
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        ch = await conn.channel()
        await ch.queue_declare("txq")
        body = b"payload"
        header = BasicProperties(delivery_mode=2).encode_header(len(body))
        publishes = [("", "txq", header, body)] * 2
        payload = _tx_payload("from-a", "boot-1", 1, publishes)
        reply = await fed_b._h_tx(payload)
        assert int.from_bytes(reply[0], "big") == 1
        # a retried batch (lost reply) acks without re-publishing
        reply = await fed_b._h_tx(payload)
        assert int.from_bytes(reply[0], "big") == 1
        assert b_srv.broker.metrics.federation_tx_applied == 1
        assert b_srv.broker.metrics.federation_duplicate_forwards == 1
        queue = b_srv.broker.get_queue("/", "txq")
        assert queue.message_count == 2
        await conn.close()
    finally:
        await fed_b.stop()
        await b_srv.stop()


async def test_tx_dedup_scoped_by_shipper_epoch():
    """A restarted shipper's sequences restart at 1 under a fresh epoch;
    the receiver must apply them instead of swallowing everything below
    the previous incarnation's high-water mark."""
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        ch = await conn.channel()
        await ch.queue_declare("txq")
        body = b"payload"
        header = BasicProperties(delivery_mode=2).encode_header(len(body))
        publishes = [("", "txq", header, body)]
        # first incarnation ships seqs 1..3
        for seq in (1, 2, 3):
            await fed_b._h_tx(
                _tx_payload("from-a", "boot-1", seq, publishes))
        # shipper restarts: new epoch, seq restarts at 1 — must APPLY,
        # not ack as a duplicate of boot-1's seq 1
        reply = await fed_b._h_tx(
            _tx_payload("from-a", "boot-2", 1, publishes))
        assert int.from_bytes(reply[0], "big") == 1
        assert b_srv.broker.metrics.federation_tx_applied == 4
        queue = b_srv.broker.get_queue("/", "txq")
        assert queue.message_count == 4
        # within the new epoch, retries still dedup
        await fed_b._h_tx(_tx_payload("from-a", "boot-2", 1, publishes))
        assert queue.message_count == 4
        await conn.close()
    finally:
        await fed_b.stop()
        await b_srv.stop()


async def test_forwarded_publish_replay_is_idempotent():
    """FED_PUBLISH carries the same per-link (epoch, seq) identity as
    Tx batches: a retry after a lost ack must not duplicate the DLX
    message, and a fresh epoch opens a new dedup scope."""
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="b", port=0)
    await fed_b.start()
    try:
        conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        ch = await conn.channel()
        await ch.queue_declare("dead")
        body = b"corpse"
        header = BasicProperties(delivery_mode=2).encode_header(len(body))
        payload = _pub_payload("from-a", "boot-1", 1, "", "dead",
                               header, body)
        await fed_b._h_publish(payload)
        await fed_b._h_publish(payload)  # retry after a lost ack
        queue = b_srv.broker.get_queue("/", "dead")
        assert queue.message_count == 1
        assert b_srv.broker.metrics.federation_duplicate_forwards == 1
        # new shipper incarnation: seq 1 again, but a different message
        await fed_b._h_publish(_pub_payload(
            "from-a", "boot-2", 1, "", "dead", header, body))
        assert queue.message_count == 2
        await conn.close()
    finally:
        await fed_b.stop()
        await b_srv.stop()


# ---------------------------------------------------------------------------
# observability: admin endpoint, Prometheus gauges, SLI samples
# ---------------------------------------------------------------------------


async def http_req(port, path, method="GET", body=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(262144), 5)
    writer.close()
    head, _, resp = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, resp


async def test_admin_federation_endpoint_and_prometheus():
    a_srv, fed_a, b_srv, fed_b = await start_pair()
    try:
        admin = AdminServer(a_srv.broker, port=0)
        await admin.start()
        await eventually(lambda: fed_a.links[0].state == "up",
                         what="link up")
        status, resp = await http_req(admin.bound_port, "/admin/federation")
        assert status == 200
        stats = json.loads(resp)
        assert stats["node"] == "cluster-a"
        assert stats["links"][0]["name"] == "to-b"
        assert stats["links"][0]["state"] == "up"
        assert any(e["event"] == "link.up" for e in stats["events"])
        status, resp = await http_req(
            admin.bound_port, "/admin/federation", "POST",
            body={"action": "wake", "link": "to-b"})
        assert status == 200 and json.loads(resp)["woke"] == ["to-b"]
        status, _ = await http_req(
            admin.bound_port, "/admin/federation", "POST",
            body={"action": "wake", "link": "nope"})
        assert status == 404
        status, _ = await http_req(
            admin.bound_port, "/admin/federation", "POST",
            body={"action": "explode"})
        assert status == 400
        status, resp = await http_req(admin.bound_port, "/metrics")
        text = resp.decode()
        assert 'chanamq_federation_link_lag{link="to-b"}' in text
        assert 'chanamq_federation_link_up{link="to-b"} 1' in text
        await admin.stop()
    finally:
        await stop_pair(a_srv, fed_a, b_srv, fed_b)


async def test_admin_federation_409_when_disabled():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    admin = AdminServer(srv.broker, port=0)
    await admin.start()
    try:
        status, _ = await http_req(admin.bound_port, "/admin/federation")
        assert status == 409
    finally:
        await admin.stop()
        await srv.stop()


async def test_sli_sampler_reports_federation_lag():
    from chanamq_tpu.slo import SLISampler

    a_srv, fed_a, b_srv, fed_b = await start_pair()
    try:
        await eventually(lambda: fed_a.links[0].state == "up",
                         what="link up")
        sampler = SLISampler(a_srv.broker, federation_lag_records=1000)
        samples = sampler.sample(True)
        assert samples["federation-lag@to-b"] == (1.0, 0.0)
        assert samples["federation-lag"] == (1.0, 0.0)
        # a down link burns the budget even with zero record lag
        fed_a.links[0].state = "down"
        samples = sampler.sample(True)
        assert samples["federation-lag@to-b"] == (0.0, 1.0)
        assert samples["federation-lag"] == (0.0, 1.0)
    finally:
        await stop_pair(a_srv, fed_a, b_srv, fed_b)
