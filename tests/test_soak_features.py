"""Mixed-feature interaction soak: tx batches + e2e exchange graph + DLX +
length caps + manual-ack rejects + binding churn, all on one broker, with a
message-conservation assertion at the end. Catches interactions the
per-feature suites can't (e.g. a tx commit racing a maxlen drop racing a
dead-letter republish)."""

import asyncio

import pytest

from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient

pytestmark = pytest.mark.asyncio

BATCH = 50
BATCHES = 40  # 2000 publishes, every 5th batch rolled back


async def test_mixed_feature_soak():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    setup = await c.channel()
    # topology: topic source --e2e--> fanout mirror; main queue capped with
    # DLX; dead queue collects rejects and overflow victims
    await setup.exchange_declare("soak_src", "topic")
    await setup.exchange_declare("soak_fan", "fanout")
    await setup.exchange_declare("soak_dlx", "fanout")
    await setup.exchange_bind("soak_fan", "soak_src", "job.#")
    await setup.queue_declare("q_dead")
    await setup.queue_bind("q_dead", "soak_dlx", "")
    await setup.queue_declare("q_main", arguments={
        "x-max-length": 500, "x-dead-letter-exchange": "soak_dlx"})
    await setup.queue_bind("q_main", "soak_src", "job.*")
    await setup.queue_declare("q_mirror")
    await setup.queue_bind("q_mirror", "soak_fan", "")

    acked = 0
    rejected = 0
    mirror_seen = 0
    committed = 0
    producer_done = asyncio.Event()

    async def producer():
        nonlocal committed
        pc = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await pc.channel()
        await ch.tx_select()
        for b in range(BATCHES):
            for i in range(BATCH):
                ch.basic_publish(b"payload-%02d-%02d" % (b, i),
                                 exchange="soak_src",
                                 routing_key=f"job.k{i % 5}")
            if b % 5 == 4:
                await ch.tx_rollback()
            else:
                await ch.tx_commit()
                committed += BATCH
            await asyncio.sleep(0)
        await pc.close()
        producer_done.set()

    async def settle(progress, deadline_s=8.0, quiet_ticks=3):
        """Wait for the producer, then until `progress()` stops moving."""
        await producer_done.wait()
        deadline = asyncio.get_event_loop().time() + deadline_s
        last, quiet = progress(), 0
        while (quiet < quiet_ticks
               and asyncio.get_event_loop().time() < deadline):
            await asyncio.sleep(0.15)
            cur = progress()
            quiet = quiet + 1 if cur == last else 0
            last = cur

    async def main_consumer():
        nonlocal acked, rejected
        cc = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await cc.channel()
        await ch.basic_qos(prefetch_count=64)
        n = 0

        def on_msg(msg):
            nonlocal acked, rejected, n
            n += 1
            if n % 7 == 0:
                ch.basic_reject(msg.delivery_tag, requeue=False)  # -> DLX
                rejected += 1
            else:
                ch.basic_ack(msg.delivery_tag)
                acked += 1

        await ch.basic_consume("q_main", on_msg)
        await settle(lambda: n)
        await cc.close()

    async def mirror_consumer():
        nonlocal mirror_seen
        cc = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await cc.channel()

        def on_msg(msg):
            nonlocal mirror_seen
            mirror_seen += 1

        await ch.basic_consume("q_mirror", on_msg, no_ack=True)
        await settle(lambda: mirror_seen)
        await cc.close()

    async def churn():
        ch = await c.channel()
        for _ in range(6):
            await asyncio.sleep(0.2)
            await ch.queue_unbind("q_mirror", "soak_fan", "")
            await asyncio.sleep(0.05)
            await ch.queue_bind("q_mirror", "soak_fan", "")

    await asyncio.gather(producer(), main_consumer(), mirror_consumer(),
                         churn())
    # let in-flight dead-letter republishes and requeues settle
    await asyncio.sleep(0.5)

    # the soak actually moved messages down every path
    assert acked > 0
    assert rejected > 0
    assert mirror_seen > 0  # e2e fanout delivered during the churn windows

    # conservation on the capped DLX'd queue: every committed message either
    # reached the consumer and was acked, was rejected/overflowed into
    # q_dead, or is still sitting ready in one of the two queues
    ok_main = await setup.queue_declare("q_main", passive=True)
    ok_dead = await setup.queue_declare("q_dead", passive=True)
    rejected_or_dropped = ok_dead.message_count
    assert rejected_or_dropped > 0
    assert committed == (acked + rejected_or_dropped + ok_main.message_count), (
        committed, acked, rejected_or_dropped, ok_main.message_count)
    assert committed == BATCH * BATCHES * 4 // 5
    # the broker survived the churn and the graph still routes
    ch = await c.channel()
    ch.basic_publish(b"final", exchange="soak_src", routing_key="job.k0")
    for _ in range(50):
        m = await ch.basic_get("q_mirror", no_ack=True)
        if m is not None and m.body == b"final":
            break
        await asyncio.sleep(0.02)
    else:
        raise AssertionError("post-soak publish did not route through e2e")
    # every dead message carries a coherent x-death header
    sample = await ch.basic_get("q_dead", no_ack=True)
    assert sample is not None
    death = sample.properties.headers["x-death"][0]
    assert death["queue"] == "q_main"
    assert death["reason"] in ("rejected", "maxlen")
    await c.close()
    await srv.stop()
