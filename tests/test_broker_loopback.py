"""End-to-end loopback conformance tests: in-repo client vs broker over real
sockets. The conformance gate of SURVEY.md §7.2 step 3 — equivalent flows to
the reference's SimplePublisher/SimpleConsumer plus the ack/nack/QoS/confirm/
TTL semantics the reference exercised manually."""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError


pytestmark = pytest.mark.asyncio


@pytest.fixture
async def server():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


async def collect(n, timeout=5.0):
    """Helper returning (callback, awaitable-for-n-messages)."""
    received = []
    done = asyncio.get_event_loop().create_future()

    def cb(msg):
        received.append(msg)
        if len(received) >= n and not done.done():
            done.set_result(None)

    async def wait():
        await asyncio.wait_for(done, timeout)
        return received

    return cb, wait


async def test_handshake_and_server_properties(client):
    assert client.server_properties["product"] == "chanamq-tpu"


async def test_declare_publish_consume_autoack(client):
    ch = await client.channel()
    await ch.exchange_declare("test_ex", "direct", durable=True)
    ok = await ch.queue_declare("test_q", durable=True,
                                arguments={"x-message-ttl": 60000})
    assert ok.queue == "test_q"
    await ch.queue_bind("test_q", "test_ex", "quote")

    # the reference's SimplePublisher publishes 3 property shapes:
    # persistent, with-expiration, transient (SimplePublisher.scala:36-53)
    shapes = [
        BasicProperties(delivery_mode=2, content_type="text/plain"),
        BasicProperties(delivery_mode=1, expiration="30000"),
        BasicProperties(),
    ]
    cb, wait = await collect(len(shapes))
    await ch.basic_consume("test_q", cb, no_ack=True)
    for i, props in enumerate(shapes):
        ch.basic_publish(f"msg-{i}".encode(), exchange="test_ex",
                         routing_key="quote", properties=props)
    received = await wait()
    assert [m.body for m in received] == [b"msg-0", b"msg-1", b"msg-2"]
    assert received[0].properties.delivery_mode == 2
    assert received[0].exchange == "test_ex"
    assert received[0].routing_key == "quote"
    assert not received[0].redelivered


async def test_default_exchange_routes_by_queue_name(client):
    ch = await client.channel()
    await ch.queue_declare("direct_q")
    cb, wait = await collect(1)
    await ch.basic_consume("direct_q", cb, no_ack=True)
    ch.basic_publish(b"via-default", routing_key="direct_q")
    received = await wait()
    assert received[0].body == b"via-default"


async def test_basic_get_and_ack(client):
    ch = await client.channel()
    await ch.queue_declare("get_q")
    ch.basic_publish(b"one", routing_key="get_q")
    ch.basic_publish(b"two", routing_key="get_q")
    await asyncio.sleep(0.05)
    m1 = await ch.basic_get("get_q")
    assert m1.body == b"one"
    assert m1.message_count == 1  # one left
    ch.basic_ack(m1.delivery_tag)
    m2 = await ch.basic_get("get_q", no_ack=True)
    assert m2.body == b"two"
    m3 = await ch.basic_get("get_q")
    assert m3 is None  # get-empty


async def test_fanout_exchange(client):
    ch = await client.channel()
    await ch.exchange_declare("fan", "fanout")
    await ch.queue_declare("fan_q1")
    await ch.queue_declare("fan_q2")
    await ch.queue_bind("fan_q1", "fan", "")
    await ch.queue_bind("fan_q2", "fan", "ignored")
    ch.basic_publish(b"blast", exchange="fan", routing_key="anything")
    await asyncio.sleep(0.05)
    m1 = await ch.basic_get("fan_q1", no_ack=True)
    m2 = await ch.basic_get("fan_q2", no_ack=True)
    assert m1.body == b"blast" and m2.body == b"blast"


async def test_topic_exchange_wildcards(client):
    ch = await client.channel()
    await ch.exchange_declare("topics", "topic")
    for q, pattern in [
        ("t_star", "stock.*.nyse"),
        ("t_hash", "stock.#"),
        ("t_exact", "stock.ibm.nyse"),
    ]:
        await ch.queue_declare(q)
        await ch.queue_bind(q, "topics", pattern)
    ch.basic_publish(b"x", exchange="topics", routing_key="stock.ibm.nyse")
    await asyncio.sleep(0.05)
    assert (await ch.basic_get("t_star", no_ack=True)).body == b"x"
    assert (await ch.basic_get("t_hash", no_ack=True)).body == b"x"
    assert (await ch.basic_get("t_exact", no_ack=True)).body == b"x"
    # non-matching key
    ch.basic_publish(b"y", exchange="topics", routing_key="bond.ibm.nyse")
    await asyncio.sleep(0.05)
    assert await ch.basic_get("t_star", no_ack=True) is None
    assert await ch.basic_get("t_hash", no_ack=True) is None


async def test_headers_exchange(client):
    ch = await client.channel()
    await ch.exchange_declare("hx", "headers")
    await ch.queue_declare("h_all")
    await ch.queue_declare("h_any")
    await ch.queue_bind("h_all", "hx", "",
                        arguments={"x-match": "all", "type": "report", "fmt": "pdf"})
    await ch.queue_bind("h_any", "hx", "",
                        arguments={"x-match": "any", "type": "report", "fmt": "doc"})
    ch.basic_publish(
        b"m", exchange="hx",
        properties=BasicProperties(headers={"type": "report", "fmt": "pdf"}))
    await asyncio.sleep(0.05)
    assert (await ch.basic_get("h_all", no_ack=True)).body == b"m"
    assert (await ch.basic_get("h_any", no_ack=True)).body == b"m"  # type matched
    ch.basic_publish(
        b"n", exchange="hx",
        properties=BasicProperties(headers={"type": "memo", "fmt": "pdf"}))
    await asyncio.sleep(0.05)
    assert await ch.basic_get("h_all", no_ack=True) is None  # fmt ok, type no
    assert await ch.basic_get("h_any", no_ack=True) is None


async def test_ack_nack_requeue_redelivered(client):
    ch = await client.channel()
    await ch.queue_declare("ack_q")
    cb, wait = await collect(1)
    await ch.basic_consume("ack_q", cb)
    ch.basic_publish(b"payload", routing_key="ack_q")
    (first,) = await wait()
    assert not first.redelivered
    # nack with requeue -> redelivered copy arrives
    cb2, wait2 = await collect(2)
    # re-point the consumer callback list by consuming the redelivery
    received2 = []

    ch.basic_nack(first.delivery_tag, requeue=True)
    await asyncio.sleep(0.1)
    # the same consumer receives the redelivery (appended to first list)
    m = await ch.basic_get("ack_q")  # should be empty: consumer got it
    assert m is None


async def test_reject_without_requeue_drops(client):
    ch = await client.channel()
    await ch.queue_declare("rej_q")
    cb, wait = await collect(1)
    await ch.basic_consume("rej_q", cb)
    ch.basic_publish(b"bad", routing_key="rej_q")
    (msg,) = await wait()
    ch.basic_reject(msg.delivery_tag, requeue=False)
    await asyncio.sleep(0.05)
    ok = await ch.queue_declare("rej_q", passive=True)
    assert ok.message_count == 0


async def test_recover_requeue(client):
    ch = await client.channel()
    await ch.queue_declare("rec_q")
    received = []
    got2 = asyncio.get_event_loop().create_future()

    def cb(msg):
        received.append(msg)
        if len(received) == 2 and not got2.done():
            got2.set_result(None)

    await ch.basic_consume("rec_q", cb)
    ch.basic_publish(b"m", routing_key="rec_q")
    await asyncio.sleep(0.1)
    assert len(received) == 1
    await ch.basic_recover(requeue=True)
    await asyncio.wait_for(got2, 5)
    assert received[1].redelivered
    ch.basic_ack(received[1].delivery_tag)


async def test_qos_prefetch_limits_unacked(client):
    ch = await client.channel()
    await ch.queue_declare("qos_q")
    await ch.basic_qos(prefetch_count=2)
    received = []

    def cb(msg):
        received.append(msg)

    await ch.basic_consume("qos_q", cb)
    for i in range(5):
        ch.basic_publish(f"m{i}".encode(), routing_key="qos_q")
    await asyncio.sleep(0.2)
    assert len(received) == 2  # prefetch window full
    ch.basic_ack(received[0].delivery_tag)
    await asyncio.sleep(0.1)
    assert len(received) == 3  # one slot freed, one more delivered
    # ack all -> the rest flows
    ch.basic_ack(received[-1].delivery_tag, multiple=True)
    await asyncio.sleep(0.1)
    assert len(received) == 5


async def test_publisher_confirms(client):
    ch = await client.channel()
    await ch.confirm_select()
    await ch.queue_declare("conf_q")
    for i in range(10):
        await ch.basic_publish_confirmed(f"c{i}".encode(), routing_key="conf_q")
    assert not ch.unconfirmed
    ok = await ch.queue_declare("conf_q", passive=True)
    assert ok.message_count == 10


async def test_mandatory_unroutable_returns(client):
    ch = await client.channel()
    await ch.exchange_declare("mand_ex", "direct")
    ch.basic_publish(b"lost", exchange="mand_ex", routing_key="nowhere",
                     mandatory=True)
    await asyncio.sleep(0.1)
    assert len(ch.returns) == 1
    assert ch.returns[0].reply_code == 312  # NO_ROUTE
    assert ch.returns[0].body == b"lost"


async def test_immediate_no_consumers_returns(client):
    ch = await client.channel()
    await ch.queue_declare("imm_q")
    ch.basic_publish(b"now-or-never", routing_key="imm_q", immediate=True)
    await asyncio.sleep(0.1)
    assert len(ch.returns) == 1
    assert ch.returns[0].reply_code == 313  # NO_CONSUMERS


async def test_per_message_ttl_expires(client):
    ch = await client.channel()
    await ch.queue_declare("ttl_q")
    ch.basic_publish(b"fleeting", routing_key="ttl_q",
                     properties=BasicProperties(expiration="50"))
    await asyncio.sleep(0.02)
    ok = await ch.queue_declare("ttl_q", passive=True)
    assert ok.message_count == 1
    await asyncio.sleep(0.15)
    assert await ch.basic_get("ttl_q", no_ack=True) is None


async def test_queue_ttl_argument_expires(client):
    ch = await client.channel()
    await ch.queue_declare("qttl_q", arguments={"x-message-ttl": 50})
    ch.basic_publish(b"x", routing_key="qttl_q")
    await asyncio.sleep(0.2)
    assert await ch.basic_get("qttl_q", no_ack=True) is None


async def test_queue_purge_and_delete(client):
    ch = await client.channel()
    await ch.queue_declare("purge_q")
    for _ in range(3):
        ch.basic_publish(b"x", routing_key="purge_q")
    await asyncio.sleep(0.05)
    assert await ch.queue_purge("purge_q") == 3
    ch.basic_publish(b"y", routing_key="purge_q")
    await asyncio.sleep(0.05)
    assert await ch.queue_delete("purge_q") == 1
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.queue_declare("purge_q", passive=True)
    assert exc_info.value.reply_code == 404


async def test_exclusive_queue_locked_to_connection(server, client):
    ch = await client.channel()
    await ch.queue_declare("excl_q", exclusive=True)
    other = await AMQPClient.connect("127.0.0.1", server.bound_port)
    try:
        ch2 = await other.channel()
        with pytest.raises(ChannelClosedError) as exc_info:
            await ch2.queue_declare("excl_q", passive=True)
        assert exc_info.value.reply_code == 405  # RESOURCE_LOCKED
    finally:
        await other.close()


async def test_exclusive_queue_dies_with_connection(server, client):
    temp = await AMQPClient.connect("127.0.0.1", server.bound_port)
    ch = await temp.channel()
    await ch.queue_declare("ephemeral_q", exclusive=True)
    await temp.close()
    await asyncio.sleep(0.1)
    ch2 = await client.channel()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch2.queue_declare("ephemeral_q", passive=True)
    assert exc_info.value.reply_code == 404


async def test_auto_delete_queue_on_last_consumer_cancel(client):
    ch = await client.channel()
    await ch.queue_declare("auto_q", auto_delete=True)
    tag = await ch.basic_consume("auto_q", lambda m: None)
    await ch.basic_cancel(tag)
    await asyncio.sleep(0.1)
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.queue_declare("auto_q", passive=True)
    assert exc_info.value.reply_code == 404


async def test_unacked_requeued_on_channel_close(client):
    ch = await client.channel()
    await ch.queue_declare("requeue_q")
    cb, wait = await collect(1)
    await ch.basic_consume("requeue_q", cb)
    ch.basic_publish(b"inflight", routing_key="requeue_q")
    await wait()
    await ch.close()
    await asyncio.sleep(0.1)
    ch2 = await client.channel()
    msg = await ch2.basic_get("requeue_q", no_ack=True)
    assert msg is not None
    assert msg.body == b"inflight"
    assert msg.redelivered


async def test_channel_error_does_not_kill_connection(client):
    ch = await client.channel()
    with pytest.raises(ChannelClosedError):
        await ch.queue_declare("missing_q", passive=True)
    # connection still usable
    ch2 = await client.channel()
    ok = await ch2.queue_declare("alive_q")
    assert ok.queue == "alive_q"


async def test_large_message_fragmentation(server, client):
    ch = await client.channel()
    await ch.queue_declare("big_q")
    body = bytes(range(256)) * 4096  # 1 MiB >> frame_max 128 KiB
    cb, wait = await collect(1, timeout=10)
    await ch.basic_consume("big_q", cb, no_ack=True)
    ch.basic_publish(body, routing_key="big_q")
    received = await wait()
    assert received[0].body == body


async def test_multiple_vhosts_isolated(server):
    await server.broker.create_vhost("other")
    c1 = await AMQPClient.connect("127.0.0.1", server.bound_port, vhost="/")
    c2 = await AMQPClient.connect("127.0.0.1", server.bound_port, vhost="other")
    try:
        ch1 = await c1.channel()
        ch2 = await c2.channel()
        await ch1.queue_declare("shared_name")
        ch1.basic_publish(b"for-default", routing_key="shared_name")
        # same queue name in the other vhost is a different queue
        await ch2.queue_declare("shared_name")
        await asyncio.sleep(0.05)
        assert await ch2.basic_get("shared_name", no_ack=True) is None
    finally:
        await c1.close()
        await c2.close()


async def test_concurrent_consumers_round_robin(client):
    ch = await client.channel()
    await ch.queue_declare("rr_q")
    seen_by = {"a": 0, "b": 0}

    def make_cb(name):
        def cb(msg):
            seen_by[name] += 1
            ch.basic_ack(msg.delivery_tag)
        return cb

    await ch.basic_consume("rr_q", make_cb("a"))
    await ch.basic_consume("rr_q", make_cb("b"))
    for i in range(20):
        ch.basic_publish(b"x", routing_key="rr_q")
    await asyncio.sleep(0.3)
    assert seen_by["a"] + seen_by["b"] == 20
    assert seen_by["a"] == 10 and seen_by["b"] == 10  # fair round-robin


async def test_publish_cache_detects_props_mutation(client):
    """The client's publish-template cache must re-encode when a reused
    properties object is mutated between publishes (mutating a shared props
    object per message is a common client pattern)."""
    ch = await client.channel()
    await ch.queue_declare("mutq")
    props = BasicProperties(delivery_mode=1, correlation_id="a")
    ch.basic_publish(b"m1", routing_key="mutq", properties=props)
    props.delivery_mode = 2
    props.correlation_id = "b"
    ch.basic_publish(b"m2", routing_key="mutq", properties=props)
    await client.drain()
    m1 = await ch.basic_get("mutq", no_ack=True)
    m2 = await ch.basic_get("mutq", no_ack=True)
    assert m1.properties.delivery_mode == 1
    assert m1.properties.correlation_id == "a"
    assert m2.properties.delivery_mode == 2
    assert m2.properties.correlation_id == "b"


async def test_vhost_isolation(server):
    """Same-named queues and exchanges in different vhosts are fully
    separate (reference: VirtualHost model + entity ids prefixed with the
    vhost, VhostEntity.scala:20-131)."""
    await server.broker.create_vhost("tenant")
    ca = await AMQPClient.connect("127.0.0.1", server.bound_port)
    cb = await AMQPClient.connect("127.0.0.1", server.bound_port,
                                  vhost="tenant")
    cha, chb = await ca.channel(), await cb.channel()
    await cha.queue_declare("iso_q")
    await chb.queue_declare("iso_q")
    cha.basic_publish(b"for-root", routing_key="iso_q")
    chb.basic_publish(b"for-tenant", routing_key="iso_q")
    await asyncio.sleep(0.1)
    assert (await cha.basic_get("iso_q", no_ack=True)).body == b"for-root"
    assert (await chb.basic_get("iso_q", no_ack=True)).body == b"for-tenant"
    assert await cha.basic_get("iso_q", no_ack=True) is None
    assert await chb.basic_get("iso_q", no_ack=True) is None
    await cha.exchange_declare("iso_ex", "fanout")
    with pytest.raises(Exception):
        await chb.exchange_declare("iso_ex", "fanout", passive=True)
    await ca.close()
    await cb.close()


async def test_consumer_cancel_notify_on_queue_delete(client):
    """Deleting a queue under a live consumer sends a server-side
    Basic.Cancel to clients that announced consumer_cancel_notify
    (RabbitMQ extension; the reference never cancels)."""
    assert client.server_properties["capabilities"]["consumer_cancel_notify"]
    ch = await client.channel()
    await ch.queue_declare("ccn_q")
    tag = await ch.basic_consume("ccn_q", lambda m: None)
    ch2 = await client.channel()
    await ch2.queue_delete("ccn_q")
    for _ in range(50):
        if ch.cancelled_consumers:
            break
        await asyncio.sleep(0.02)
    assert ch.cancelled_consumers == [tag]


async def test_consumer_cancel_notify_across_connections(server):
    """The cancel notification reaches a consumer on a DIFFERENT connection
    than the one deleting the queue."""
    from chanamq_tpu.client import AMQPClient as _C

    c1 = await _C.connect("127.0.0.1", server.bound_port)
    c2 = await _C.connect("127.0.0.1", server.bound_port)
    try:
        ch1 = await c1.channel()
        await ch1.queue_declare("ccn2_q")
        tag = await ch1.basic_consume("ccn2_q", lambda m: None)
        ch2 = await c2.channel()
        await ch2.queue_delete("ccn2_q")
        for _ in range(50):
            if ch1.cancelled_consumers:
                break
            await asyncio.sleep(0.02)
        assert ch1.cancelled_consumers == [tag]
    finally:
        await c1.close()
        await c2.close()


async def test_consumer_ack_timeout_closes_channel_and_requeues():
    """chana.mq.consumer.timeout (RabbitMQ consumer_timeout): a delivery
    unacked past the deadline closes the offending channel with 406 and
    requeues the messages; other channels are untouched."""
    from chanamq_tpu.broker.broker import Broker

    broker = Broker(message_sweep_interval_s=0.1, consumer_timeout_ms=300)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        stuck = await c.channel()
        healthy = await c.channel()
        await stuck.queue_declare("at_q")
        got = []
        await stuck.basic_consume("at_q", got.append)  # never acks
        stuck.basic_publish(b"hung", routing_key="at_q")
        for _ in range(50):
            if got:
                break
            await asyncio.sleep(0.02)
        assert got, "delivery never arrived"
        # wait past timeout + sweep: the stuck channel dies with 406
        err = None
        for _ in range(100):
            try:
                await stuck.queue_declare("at_q", passive=True)
            except ChannelClosedError as exc:
                err = exc
                break
            await asyncio.sleep(0.05)
        assert err is not None and err.reply_code == 406
        assert "timeout" in err.reply_text
        # the message requeued and the healthy channel can take it
        m = None
        for _ in range(100):
            m = await healthy.basic_get("at_q", no_ack=True)
            if m is not None:
                break
            await asyncio.sleep(0.02)
        assert m is not None and m.body == b"hung" and m.redelivered
        await c.close()
    finally:
        await srv.stop()


async def test_prompt_acks_never_hit_ack_timeout():
    from chanamq_tpu.broker.broker import Broker

    broker = Broker(message_sweep_interval_s=0.05, consumer_timeout_ms=400)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("ok_q")

        def on_msg(m):
            ch.basic_ack(m.delivery_tag)

        await ch.basic_consume("ok_q", on_msg)
        for _ in range(10):
            ch.basic_publish(b"quick", routing_key="ok_q")
            await asyncio.sleep(0.08)
        # channel survived well past the timeout window
        ok = await ch.queue_declare("ok_q", passive=True)
        assert ok.queue == "ok_q"
        await c.close()
    finally:
        await srv.stop()


async def test_ack_timeout_covers_tx_parked_settles():
    """A consumer that acks inside a transaction it never commits still
    pins the message — the ack timeout must see the tx-parked delivery and
    close the channel (implicit rollback requeues it)."""
    from chanamq_tpu.broker.broker import Broker

    broker = Broker(message_sweep_interval_s=0.1, consumer_timeout_ms=300)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("txat_q")
        ch.basic_publish(b"parked", routing_key="txat_q")
        msg = None
        for _ in range(50):
            msg = await ch.basic_get("txat_q")
            if msg is not None:
                break
            await asyncio.sleep(0.02)
        assert msg is not None
        await ch.tx_select()
        ch.basic_ack(msg.delivery_tag)  # parked in the tx, never committed
        err = None
        for _ in range(100):
            try:
                await ch.queue_declare("txat_q", passive=True)
            except ChannelClosedError as exc:
                err = exc
                break
            await asyncio.sleep(0.05)
        assert err is not None and err.reply_code == 406
        # implicit rollback requeued it
        ch2 = await c.channel()
        m = None
        for _ in range(100):
            m = await ch2.basic_get("txat_q", no_ack=True)
            if m is not None:
                break
            await asyncio.sleep(0.02)
        assert m is not None and m.body == b"parked" and m.redelivered
        await c.close()
    finally:
        await srv.stop()
