"""Binding-table compiler + batch match kernels for the tensorized router.

This module is broker-free and pure: it turns one exchange's binding list
(the output of ``Matcher.bindings()``) into a ``CompiledExchange`` — host
dictionaries for the parts where a hash lookup already wins, and dense
tokenized matrices for the parts where a data-parallel kernel wins — and
evaluates whole publish batches against it.

Compilation strategy (driven by 1-core measurements, see BENCH_r08.json):

- **Exact patterns** (direct bindings; topic patterns without wildcards)
  stay a host dict ``routing_key -> queue names``. A dict probe is ~0.1µs;
  no kernel beats that, and a dense table over a million exact patterns
  would be a memory blowout for zero gain.
- **Always-match rows** (fanout bindings, the lone ``#`` topic pattern,
  empty x-match=all headers bindings) fold into one host set.
- **Wildcard topic patterns** and **headers bindings** become tokenized
  int32 matrices plus uint32 queue-bitmask rows, evaluated for the whole
  batch in ONE kernel call: match booleans ``[B, N]`` are expanded against
  the mask rows and OR-reduced into per-message destination bitmasks
  ``[B, mask_words]``. The same kernel body runs under ``jax.jit``
  (backend="jax") or plain numpy (backend="python" — the runtime-selectable
  pure-Python fallback; also what parity tests diff against jit).

Token encoding: literal words get vocab ids >= 0; ``STAR`` marks ``*``,
``PAD`` fills a row past its pattern's length, and message words absent
from the vocab (or past the message's length) are ``MISS``. The positional
match condition is ``(pat == tok) | (pat < 0)``: a negative pattern cell is
STAR or PAD and matches any position, while MISS (< 0 too, but only ever on
the *message* side) never equals a literal id. Length predicates do the
rest: a no-``#`` pattern needs ``m == plen``; a single-``#`` pattern splits
into a left-aligned prefix and a RIGHT-aligned suffix (compared against the
right-aligned last words of the message, so no dynamic gather is needed)
and requires ``m >= plen + slen``.

Not everything compiles. Patterns with more than one ``#``, headers
bindings with unhashable values, and tables past the wildcard/queue caps
raise ``Uncompilable`` — the caller keeps the Python matcher as the
always-available fallback for that exchange.

All array dims (pattern rows, prefix/suffix width, batch size, header
counts) are padded up to power-of-two buckets so jit retraces stay bounded
as tables and batches grow.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import numpy as np

STAR = -1   # pattern cell: '*' (matches exactly one word)
PAD = -2    # pattern cell: beyond this pattern's length
MISS = -3   # message cell: out-of-vocab word, or beyond the message length

# a pattern prefix/suffix deeper than this is compiled nowhere: fall back
MAX_PATTERN_WORDS = 32

_EMPTY: frozenset = frozenset()

# decoded (mask -> names) and routed (key -> names) memo caps, per compiled
# snapshot; snapshots are immutable so entries never go stale, the cap only
# bounds memory against hostile key cardinality
_MEMO_CAP = 8192


class Uncompilable(Exception):
    """This binding table cannot be tensorized; use the Python matcher."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


def _bucket(n: int, floor: int = 4) -> int:
    """Next power-of-two >= max(n, floor): bounds distinct jit trace shapes."""
    b = floor
    while b < n:
        b <<= 1
    return b


class CompiledExchange:
    """Immutable compiled snapshot of one exchange's binding table."""

    __slots__ = ("kind", "generation", "exact", "always", "bit_names",
                 "wild", "headers", "_route_memo")

    def __init__(self, kind: str, generation: int) -> None:
        self.kind = kind
        self.generation = generation
        # routing_key -> frozenset of queue names (exact patterns)
        self.exact: dict[str, frozenset] = {}
        # queues every message matches (fanout / '#' / empty x-match=all)
        self.always: frozenset = _EMPTY
        # bitmask bit index -> queue name (kernel destinations only)
        self.bit_names: tuple = ()
        self.wild: Optional[dict] = None      # topic wildcard tables
        self.headers: Optional[dict] = None   # headers-exchange tables
        # bounded result memo: topic keys it by bare routing key (the
        # match is a pure function of the key within this compiled
        # generation), headers by the kernel's mask bytes
        self._route_memo: dict = {}

    @property
    def kernel_rows(self) -> int:
        if self.wild is not None:
            return self.wild["n"]
        if self.headers is not None:
            return self.headers["n"]
        return 0

    # -- mask decode -------------------------------------------------------

    def _decode_mask(self, row: np.ndarray) -> frozenset:
        names = []
        bit_names = self.bit_names
        for wi in range(row.shape[0]):
            w = int(row[wi])
            base = wi << 5
            while w:
                low = w & -w
                names.append(bit_names[base + low.bit_length() - 1])
                w ^= low
        return frozenset(names)


def compile_exchange(
    kind: str,
    bindings: Iterable[tuple[str, str, Optional[dict]]],
    *,
    generation: int = 0,
    max_wildcards: int = 512,
    max_queues: int = 4096,
) -> CompiledExchange:
    """Compile one exchange's ``Matcher.bindings()`` list. Raises
    ``Uncompilable`` when the table can't be tensorized faithfully."""
    kind = kind.lower()
    ce = CompiledExchange(kind, generation)
    if kind == "direct":
        exact: dict[str, set] = {}
        for key, queue, _ in bindings:
            exact.setdefault(key, set()).add(queue)
        ce.exact = {k: frozenset(v) for k, v in exact.items()}
        return ce
    if kind == "fanout":
        ce.always = frozenset(q for _, q, _ in bindings)
        return ce
    if kind == "topic":
        _compile_topic(ce, bindings, max_wildcards, max_queues)
        return ce
    if kind == "headers":
        _compile_headers(ce, bindings, max_wildcards, max_queues)
        return ce
    raise Uncompilable(f"unknown exchange type {kind!r}")


# -- topic -----------------------------------------------------------------


def _compile_topic(ce, bindings, max_wildcards: int, max_queues: int) -> None:
    exact: dict[str, set] = {}
    always: set = set()
    wild: dict[str, set] = {}  # pattern -> queues
    for key, queue, _ in bindings:
        toks = key.split(".")
        nhash = toks.count("#")
        if nhash == 0 and "*" not in toks:
            exact.setdefault(key, set()).add(queue)
        elif toks == ["#"]:
            always.add(queue)  # '#' alone matches every key
        elif nhash > 1:
            raise Uncompilable("multi-# pattern")
        else:
            wild.setdefault(key, set()).add(queue)
    ce.exact = {k: frozenset(v) for k, v in exact.items()}
    ce.always = frozenset(always)
    _build_wild_table(ce, wild, max_wildcards, max_queues)


def _build_wild_table(ce, wild: dict, max_wildcards: int,
                      max_queues: int) -> None:
    """Tokenize wildcard topic patterns (pattern -> queue-name set) into
    the kernel matrices. Shared by the single-exchange topic compile and
    the e2e closure compile (compile_effective)."""
    if not wild:
        return
    if len(wild) > max_wildcards:
        raise Uncompilable("wildcard pattern count over cap")
    bit_names = tuple(sorted({q for qs in wild.values() for q in qs}))
    if len(bit_names) > max_queues:
        raise Uncompilable("kernel queue count over cap")
    bit_of = {q: i for i, q in enumerate(bit_names)}
    vocab: dict[str, int] = {}
    rows = []
    for pattern, queues in wild.items():
        toks = pattern.split(".")
        if "#" in toks:
            hi = toks.index("#")
            pre_toks, suf_toks, has_hash = toks[:hi], toks[hi + 1:], True
        else:
            pre_toks, suf_toks, has_hash = toks, [], False
        if len(pre_toks) > MAX_PATTERN_WORDS or len(suf_toks) > MAX_PATTERN_WORDS:
            raise Uncompilable("pattern too deep")
        pre = [STAR if t == "*" else vocab.setdefault(t, len(vocab))
               for t in pre_toks]
        suf = [STAR if t == "*" else vocab.setdefault(t, len(vocab))
               for t in suf_toks]
        rows.append((pre, suf, has_hash, queues))
    n = _bucket(len(rows))
    p = _bucket(max((len(r[0]) for r in rows), default=1), 2)
    s = _bucket(max((len(r[1]) for r in rows), default=1), 2)
    mask_words = (len(bit_names) + 31) >> 5
    pre_t = np.full((n, p), PAD, dtype=np.int32)
    suf_t = np.full((n, s), PAD, dtype=np.int32)
    plen = np.zeros(n, dtype=np.int32)
    slen = np.zeros(n, dtype=np.int32)
    has_h = np.zeros(n, dtype=bool)
    masks = np.zeros((n, mask_words), dtype=np.uint32)
    for i, (pre, suf, hh, queues) in enumerate(rows):
        pre_t[i, :len(pre)] = pre
        # RIGHT-aligned: compared against the message's last-S words
        if suf:
            suf_t[i, s - len(suf):] = suf
        plen[i] = len(pre)
        slen[i] = len(suf)
        has_h[i] = hh
        for q in queues:
            b = bit_of[q]
            masks[i, b >> 5] |= np.uint32(1 << (b & 31))
        # padding rows past len(rows) keep all-zero masks: harmless
    ce.bit_names = bit_names
    ce.wild = {"n": len(rows), "vocab": vocab, "p": p, "s": s,
               "pre": pre_t, "suf": suf_t, "plen": plen, "slen": slen,
               "has_hash": has_h, "masks": masks, "mask_words": mask_words}


def compile_effective(
    exact: dict,
    always: Iterable[str],
    wild: dict,
    *,
    generation: int = 0,
    max_wildcards: int = 512,
    max_queues: int = 4096,
) -> CompiledExchange:
    """Compile a FLATTENED e2e closure (TensorRouter._closure_bindings):
    ``exact`` maps routing keys (string equality — covers direct bindings
    and wildcard-free topic patterns) to queue-name sets, ``always`` is
    the unconditional set (fanout members, lone-'#' patterns), ``wild``
    maps genuine topic wildcard patterns to queue-name sets. Compiled as
    kind "topic" because the topic evaluation path (exact dict + always +
    wildcard kernel) is the universal shape the closure folds into."""
    ce = CompiledExchange("topic", generation)
    ce.exact = {k: frozenset(v) for k, v in exact.items()}
    ce.always = frozenset(always)
    _build_wild_table(ce, dict(wild), max_wildcards, max_queues)
    return ce


def topic_match(pattern: str, key: str) -> bool:
    """One AMQP topic pattern against one concrete key, as a pure
    function ('*' = exactly one word, '#' = zero or more). Used at
    closure-compile time to evaluate hop-predicate conjunctions against
    known keys — never on the publish path."""
    pt = pattern.split(".")
    kt = key.split(".")
    memo: dict[tuple[int, int], bool] = {}

    def m(i: int, j: int) -> bool:
        got = memo.get((i, j))
        if got is not None:
            return got
        if i == len(pt):
            out = j == len(kt)
        elif pt[i] == "#":
            # zero words, or absorb one and stay on the '#'
            out = m(i + 1, j) or (j < len(kt) and m(i, j + 1))
        elif j == len(kt):
            out = False
        else:
            out = (pt[i] == "*" or pt[i] == kt[j]) and m(i + 1, j + 1)
        memo[(i, j)] = out
        return out

    return m(0, 0)


def _topic_kernel(xp, pre_t, suf_t, plen, slen, has_h, masks,
                  pre_m, suf_m, mlen):
    # [B,N,P]: positional match; a negative pattern cell (STAR/PAD) always
    # matches, and MISS on the message side never equals a literal id
    pm = (pre_t[None, :, :] == pre_m[:, None, :]) | (pre_t[None, :, :] < 0)
    sm = (suf_t[None, :, :] == suf_m[:, None, :]) | (suf_t[None, :, :] < 0)
    need = plen[None, :] + slen[None, :]
    len_ok = xp.where(has_h[None, :],
                      mlen[:, None] >= need,
                      mlen[:, None] == plen[None, :])
    ok = pm.all(axis=2) & sm.all(axis=2) & len_ok                 # [B,N]
    hit = masks[None, :, :] * ok[:, :, None].astype(xp.uint32)    # [B,N,W]
    return xp.bitwise_or.reduce(hit, axis=1)                      # [B,W]


def _tokenize_topic(wild: dict, keys: list, b: int):
    p, s, vocab = wild["p"], wild["s"], wild["vocab"]
    pre_m = np.full((b, p), MISS, dtype=np.int32)
    suf_m = np.full((b, s), MISS, dtype=np.int32)
    mlen = np.zeros(b, dtype=np.int32)
    get = vocab.get
    for i, key in enumerate(keys):
        words = key.split(".") if key else [""]
        m = len(words)
        mlen[i] = m
        for j in range(min(m, p)):
            pre_m[i, j] = get(words[j], MISS)
        for j in range(min(m, s)):
            suf_m[i, s - 1 - j] = get(words[m - 1 - j], MISS)
    return pre_m, suf_m, mlen


# -- headers ---------------------------------------------------------------


def _compile_headers(ce, bindings, max_wildcards: int, max_queues: int) -> None:
    always: set = set()
    rows = []  # (required {h: v}, is_all, queue)
    for _, queue, args in bindings:
        args = dict(args or {})
        is_all = str(args.pop("x-match", "all")).lower() != "any"
        if not args:
            if is_all:
                always.add(queue)  # empty all-binding matches everything
            continue  # empty any-binding can never match: no row
        for h, v in args.items():
            try:
                hash(v)
            except TypeError:
                raise Uncompilable("unhashable headers binding value")
        if len(args) > MAX_PATTERN_WORDS:
            raise Uncompilable("headers binding too wide")
        rows.append((args, is_all, queue))
    ce.always = frozenset(always)
    if not rows:
        return
    if len(rows) > max_wildcards:
        raise Uncompilable("headers binding count over cap")
    bit_names = tuple(sorted({q for _, _, q in rows}))
    if len(bit_names) > max_queues:
        raise Uncompilable("kernel queue count over cap")
    bit_of = {q: i for i, q in enumerate(bit_names)}
    vocab: dict[tuple, int] = {}  # (header, value) -> pair id
    n = _bucket(len(rows))
    r = _bucket(max(len(a) for a, _, _ in rows), 2)
    mask_words = (len(bit_names) + 31) >> 5
    req = np.full((n, r), PAD, dtype=np.int32)
    rcount = np.zeros(n, dtype=np.int32)
    is_all_v = np.zeros(n, dtype=bool)
    masks = np.zeros((n, mask_words), dtype=np.uint32)
    for i, (args, is_all, queue) in enumerate(rows):
        pids = [vocab.setdefault((h, v), len(vocab)) for h, v in args.items()]
        req[i, :len(pids)] = pids
        rcount[i] = len(pids)
        is_all_v[i] = is_all
        b = bit_of[queue]
        masks[i, b >> 5] |= np.uint32(1 << (b & 31))
    ce.bit_names = bit_names
    ce.headers = {"n": len(rows), "vocab": vocab, "r": r, "req": req,
                  "rcount": rcount, "is_all": is_all_v, "masks": masks,
                  "mask_words": mask_words}


def _headers_kernel(xp, req, rcount, is_all, masks, pids):
    # req [N,R] vs message pair ids pids [B,H]
    eq = req[None, :, :, None] == pids[:, None, None, :]           # [B,N,R,H]
    hitp = eq.any(axis=3) & (req[None, :, :] != PAD)               # [B,N,R]
    cnt = hitp.sum(axis=2, dtype=xp.int32)
    ok = xp.where(is_all[None, :], cnt == rcount[None, :], cnt > 0)
    hit = masks[None, :, :] * ok[:, :, None].astype(xp.uint32)
    return xp.bitwise_or.reduce(hit, axis=1)


def _tokenize_headers(table: dict, headers_list: list, b: int):
    vocab = table["vocab"]
    get = vocab.get
    per_msg = []
    hmax = 1
    for headers in headers_list:
        pids = []
        if headers:
            for h, v in headers.items():
                try:
                    pid = get((h, v))
                except TypeError:
                    continue  # unhashable message value never equals a
                    # (hashable) compiled binding value
                if pid is not None:
                    pids.append(pid)
        per_msg.append(pids)
        if len(pids) > hmax:
            hmax = len(pids)
    h = _bucket(hmax, 2)
    out = np.full((b, h), MISS, dtype=np.int32)
    for i, pids in enumerate(per_msg):
        out[i, :len(pids)] = pids
    return out


# -- batch evaluation ------------------------------------------------------

_JIT_TOPIC = None
_JIT_HEADERS = None


def _jit_kernels():
    global _JIT_TOPIC, _JIT_HEADERS
    if _JIT_TOPIC is None:
        import jax
        import jax.numpy as jnp

        _JIT_TOPIC = jax.jit(
            lambda *a: _topic_kernel(jnp, *a))
        _JIT_HEADERS = jax.jit(
            lambda *a: _headers_kernel(jnp, *a))
    return _JIT_TOPIC, _JIT_HEADERS


def route_batch(
    compiled: CompiledExchange,
    items: list,
    backend: str = "jax",
) -> list:
    """Route a batch through a compiled snapshot.

    ``items`` is a list of ``(routing_key, headers-or-None)``; the return
    is an aligned list of frozensets of queue names. backend="jax" runs the
    match kernels under jit; backend="python" runs the identical kernel
    body on numpy (no jax import at all)."""
    kind = compiled.kind
    if kind == "fanout":
        always = compiled.always
        return [always] * len(items)
    memo = compiled._route_memo
    if kind == "direct":
        exact = compiled.exact
        return [exact.get(k, _EMPTY) for k, _ in items]

    if kind == "topic":
        # a topic result is a pure function of the routing key, so the
        # memo is keyed on the key alone: steady-state routing (bounded
        # key cardinality, the common AMQP shape) is one dict hit per
        # message and only never-seen keys pay tokenize + kernel
        wild = compiled.wild
        out = [None] * len(items)
        miss: dict = {}  # unique unseen keys -> their positions
        for i, (key, _) in enumerate(items):
            names = memo.get(key)
            if names is None:
                miss.setdefault(key, []).append(i)
            else:
                out[i] = names
        if not miss:
            return out
        if len(memo) + len(miss) >= _MEMO_CAP:
            memo.clear()
        if wild is None:
            for key, idxs in miss.items():
                names = compiled.exact.get(key, _EMPTY) | compiled.always
                memo[key] = names
                for i in idxs:
                    out[i] = names
            return out
        uniq = list(miss)
        b = _bucket(len(uniq), 16)
        pre_m, suf_m, mlen = _tokenize_topic(wild, uniq, b)
        if backend == "jax":
            kern, _ = _jit_kernels()
            rows = np.asarray(kern(
                wild["pre"], wild["suf"], wild["plen"], wild["slen"],
                wild["has_hash"], wild["masks"], pre_m, suf_m, mlen))
        else:
            rows = _topic_kernel(
                np, wild["pre"], wild["suf"], wild["plen"], wild["slen"],
                wild["has_hash"], wild["masks"], pre_m, suf_m, mlen)
        for j, key in enumerate(uniq):
            names = (compiled.exact.get(key, _EMPTY) | compiled.always
                     | compiled._decode_mask(rows[j]))
            memo[key] = names
            for i in miss[key]:
                out[i] = names
        return out

    if kind == "headers":
        table = compiled.headers
        if table is None:
            return [compiled.always] * len(items)
        b = _bucket(len(items), 16)
        pids = _tokenize_headers(table, [h for _, h in items], b)
        if backend == "jax":
            _, kern = _jit_kernels()
            rows = np.asarray(kern(
                table["req"], table["rcount"], table["is_all"],
                table["masks"], pids))
        else:
            rows = _headers_kernel(
                np, table["req"], table["rcount"], table["is_all"],
                table["masks"], pids)
        out = []
        for i in range(len(items)):
            row = rows[i]
            mk = row.tobytes()
            names = memo.get(mk)
            if names is None:
                names = compiled.always | compiled._decode_mask(row)
                if len(memo) >= _MEMO_CAP:
                    memo.clear()
                memo[mk] = names
            out.append(names)
        return out

    raise Uncompilable(f"unknown exchange type {kind!r}")
