"""WalStore: the log-structured write-ahead engine in front of the store.

Layering (log + memtable + index): every durable mutation appends one
framed record to the shard's WAL buffer and to an in-RAM *memtable* —
the op list pending application to the inner SqliteStore, plus an
overlay of recent message blobs that serves hydration reads without an
executor round trip.  Durability lives in the WAL: one commit loop
batches the buffer across ALL channels, queues and subsystems on a
time/byte window (``chana.mq.wal.flush-ms`` / ``flush-bytes``) and
performs a single write+fsync per batch; publisher confirms,
replication sync-gates and stream-seal completions ride
``mark()``/``flush(intervals)``, resolving at the WAL commit boundary —
one fsync amortizes over every channel that wrote inside the window
(the cross-channel group commit the reference's per-op Cassandra writes
could never do, and the journal/ledger split BookKeeper uses for the
same reason).

The SQLite index is written *lazily*: a drain folds the memtable to its
net effect first (``_coalesce_ops`` — a row both created and destroyed
inside the window never touches SQLite at all, so a steady
consume-as-fast-as-publish workload leaves the index almost idle) and
then hands the survivors to the inner FIFO in program order.  Reads are
linearizable against writes because every read either hits the overlay
or forces a drain (``_settle``) before enqueuing behind the forwarded
ops on the inner FIFO.  Drains run at each checkpoint and whenever the
memtable passes ``chana.mq.wal.memtable-bytes``.

A background checkpointer drains the memtable, waits for the inner
store to commit it, fsyncs the SQLite file (``PRAGMA
wal_checkpoint(TRUNCATE)`` — under synchronous=NORMAL that is the only
fsync SQLite does), persists the covered LSN in ``cluster_kv`` and then
unlinks whole sealed WAL segments below it.  Recovery replays the WAL
tail above the last checkpoint into the inner store — every journaled op
is idempotent (INSERT OR REPLACE / DELETE) so replay-over-checkpoint
converges; a torn tail is truncated, a mid-log CRC failure stops replay
there and quarantines the rest (codec.scan_frames documents why).

The same checkpoint pass runs stream-segment maintenance: key compaction
for queues declared with ``x-stream-compact`` (newest record per routing
key survives, offsets preserved — blobs become sparse) and tiered
offload of cold sealed segments (blob bytes move to a side file, the
SQLite index row stays, reads rehydrate transparently).

Failure semantics: a failed WAL commit records its LSN range so only the
barriers whose windows overlap it raise (same per-caller attribution
contract as SqliteStore seq intervals); a failed inner write surfaces
through ``error_count`` (readiness) and blocks the checkpoint from
advancing — the WAL keeps the truth until the index catches up.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace as dc_replace
from typing import Optional

from .. import profile, trace
from ..store.api import StoreService
from ..utils.metrics import Metrics
from .codec import (
    OP_INDEX, WalCodecError, decode_payload, encode_insert_message,
    encode_insert_published, encode_insert_queue_msg, encode_record,
    queue_prefix,
)
from .segment import (
    SegmentWriter, ensure_dir, fsync_dir, list_segments, quarantine,
    read_segment, truncate_segment,
)
from .tier import StreamTier, compact_records, compacted_blob

log = logging.getLogger("chanamq.wal")


def _stream_segment_mod():
    """Lazy import of streams.segment: the streams package can only load
    AFTER the broker package (pre-existing broker<->streams cycle), and
    the WAL must stay importable standalone — so pull broker in first."""
    from .. import broker  # noqa: F401
    from ..streams import segment
    return segment


CHECKPOINT_KEY = "wal_checkpoint"

# commit-failure LSN ranges kept for barrier attribution before the
# floor swallows the oldest (same bounding idea as SqliteStore._FAILED_CAP)
_FAILED_CAP = 256
# traces carried per commit batch for wal-commit spans (bounded: a batch
# under load covers thousands of appends, sampling covers the rest)
_TRACE_CAP = 128
# drained batches below this run the coalescer inline; larger ones go to
# an executor thread so the fold never stalls the event loop
_COALESCE_INLINE = 64

# ops that commute with every key the coalescer tracks (message ids,
# queue-log rows, unack rows) — they pass through without resetting the
# live maps; any op NOT listed in the handlers below acts as a barrier
_COALESCE_PASS = frozenset((
    "worker_id_floor", "update_stream_cursor", "insert_stream_segment",
    "insert_queue_meta", "insert_exchange", "insert_bind",
    "insert_exchange_bind", "insert_vhost",
))


def _coalesce_ops(ops: list) -> "tuple[list, int]":
    """Fold a drained memtable batch to its net effect on the index.

    A message blob, queue-log row or unack row that was both created and
    destroyed inside the batch never touches SQLite at all — the WAL
    already holds the full history for recovery, so the index only needs
    the net state at each drain boundary (reads force a drain first, so
    intermediate states are never observable).  Ops without a handler or
    pass-through entry are barriers: the live maps reset so no
    create/destroy pair spanning one is elided — e.g. a delete_queue
    between them must still see its rows archived.

    Returns ``(net_ops, elided_count)``.  Pure data walk over tuples the
    event loop no longer mutates, so it may run on an executor thread.
    """
    dead: set = set()
    repl: dict = {}          # idx -> replacement args (pruned lists)
    repl_op: dict = {}       # idx -> (name, args) full rewrite (fused splits)
    live_msg: dict = {}      # msg_id -> [insert idx, refer-count idx|None]
    live_row: dict = {}      # (vhost, queue) -> {offset: insert idx}
    live_unack: dict = {}    # (vhost, queue, msg_id) -> insert idx
    unack_items: dict = {}   # insert idx -> (vhost, queue, {mid: tuple}, n0)
    last_lc: dict = {}       # (vhost, queue) -> idx of latest watermark
    fused: dict = {}         # insert_published idx -> [blob_dead, row_dead]

    def kill(j: int, part: int) -> None:
        # a fused record dies only once BOTH its halves are destroyed;
        # a half-dead survivor is split back into the living half at the end
        st = fused.get(j)
        if st is None:
            dead.add(j)
        else:
            st[part] = True
            if st[0] and st[1]:
                dead.add(j)

    for i, (name, args) in enumerate(ops):
        if name == "insert_message":
            live_msg[args[0].id] = [i, None]
        elif name == "insert_published":
            msg = args[0]
            live_msg[msg.id] = [i, None]
            rows = live_row.get((args[1], args[2]))
            if rows is None:
                rows = live_row[(args[1], args[2])] = {}
            rows[args[3]] = i
            fused[i] = [False, False]
        elif name == "update_message_refer_count":
            chain = live_msg.get(args[0])
            if chain is not None:
                if chain[1] is not None:
                    dead.add(chain[1])  # only the latest count matters
                chain[1] = i
        elif name == "delete_message":
            chain = live_msg.pop(args[0], None)
            if chain is not None:
                kill(chain[0], 0)
                if chain[1] is not None:
                    dead.add(chain[1])
                dead.add(i)
        elif name == "delete_messages":
            kept_ids = []
            for mid in args[0]:
                chain = live_msg.pop(mid, None)
                if chain is None:
                    kept_ids.append(mid)
                else:
                    kill(chain[0], 0)
                    if chain[1] is not None:
                        dead.add(chain[1])
            if not kept_ids:
                dead.add(i)
            elif len(kept_ids) < len(args[0]):
                repl[i] = (kept_ids,)
        elif name == "insert_queue_msg":
            rows = live_row.get((args[0], args[1]))
            if rows is None:
                rows = live_row[(args[0], args[1])] = {}
            rows[args[2]] = i
        elif name == "delete_queue_msg":
            rows = live_row.get((args[0], args[1]))
            j = rows.pop(args[2], None) if rows is not None else None
            if j is not None:
                kill(j, 1)
                dead.add(i)
        elif name == "delete_queue_msgs_offsets":
            vhost, queue, offsets = args
            rows = live_row.get((vhost, queue))
            if rows is None:
                continue
            kept_offs = []
            for off in offsets:
                j = rows.pop(off, None)
                if j is None:
                    kept_offs.append(off)
                else:
                    kill(j, 1)
            if not kept_offs:
                dead.add(i)
            elif len(kept_offs) < len(offsets):
                repl[i] = (vhost, queue, kept_offs)
        elif name == "update_queue_last_consumed":
            key = (args[0], args[1])
            prev = last_lc.get(key)
            if prev is not None:
                dead.add(prev)
            last_lc[key] = i
            # the index-side write also deletes queue-log rows at or below
            # the watermark, so any such row created earlier in this batch
            # is dead on arrival (in-order consumption settles this way;
            # offset-keyed deletes only cover priority/requeue paths)
            rows = live_row.get(key)
            if rows:
                wm = args[2]
                killed = [off for off in rows if off <= wm]
                for off in killed:
                    kill(rows.pop(off), 1)
        elif name == "insert_queue_unacks":
            vhost, queue, unacks = args
            items = {u[0]: u for u in unacks}
            unack_items[i] = (vhost, queue, items, len(unacks))
            for mid in items:
                live_unack[(vhost, queue, mid)] = i
        elif name == "delete_queue_unacks":
            vhost, queue, msg_ids = args
            kept_mids = []
            for mid in msg_ids:
                j = live_unack.pop((vhost, queue, mid), None)
                if j is None:
                    kept_mids.append(mid)
                else:
                    items = unack_items[j][2]
                    items.pop(mid, None)
                    if not items:
                        dead.add(j)
            if not kept_mids:
                dead.add(i)
            elif len(kept_mids) < len(msg_ids):
                repl[i] = (vhost, queue, kept_mids)
        elif name not in _COALESCE_PASS:
            # barrier: elisions may not span this op (pruning already
            # recorded for earlier ops stays valid — those rows died
            # strictly before the barrier)
            live_msg.clear()
            live_row.clear()
            live_unack.clear()
            last_lc.clear()
    for i, (vhost, queue, items, n0) in unack_items.items():
        if i not in dead and len(items) < n0:
            repl[i] = (vhost, queue, list(items.values()))
    for i, st in fused.items():
        if i in dead or st[0] == st[1]:
            continue  # fully live or fully dead: forward as-is / drop
        a = ops[i][1]
        if st[0]:  # blob destroyed, row survives
            repl_op[i] = ("insert_queue_msg",
                          (a[1], a[2], a[3], a[0].id, a[4], a[5]))
        else:      # row destroyed, blob survives
            repl_op[i] = ("insert_message", (a[0],))
    if not dead and not repl and not repl_op:
        return ops, 0
    net = []
    for i, (name, args) in enumerate(ops):
        if i in dead:
            continue
        ro = repl_op.get(i)
        net.append(ro if ro is not None else (name, repl.get(i, args)))
    return net, len(ops) - len(net)


class WalStore(StoreService):
    """Write-ahead wrapper around an inner :class:`SqliteStore`."""

    def __init__(
        self, inner, dir_path: Optional[str] = None, *,
        flush_ms: float = 2.0, flush_bytes: int = 1 << 20,
        segment_bytes: int = 64 << 20, sync: str = "fsync",
        checkpoint_ms: float = 1000.0, memtable_bytes: int = 64 << 20,
        tier_keep_segments: int = 0,
        compact_streams: bool = False, metrics: Optional[Metrics] = None,
    ) -> None:
        if sync not in ("fsync", "os"):
            raise ValueError(f"bad wal sync mode {sync!r}")
        self._inner = inner
        self.path = getattr(inner, "path", None)
        self.dir = dir_path or (str(self.path) + ".wal")
        self.flush_ms = float(flush_ms)
        self.flush_bytes = int(flush_bytes)
        self.segment_bytes = int(segment_bytes)
        self.sync_mode = sync
        self.checkpoint_ms = float(checkpoint_ms)
        self.memtable_bytes = int(memtable_bytes)
        self.tier_keep = int(tier_keep_segments)
        self.compact_streams = bool(compact_streams)
        self.metrics = metrics if metrics is not None else Metrics()
        self.tier = StreamTier(os.path.join(self.dir, "tier"))

        # -- log state (event-loop side) --
        self._lsn = 0            # last appended LSN
        self._buf: list[bytes] = []
        self._buf_bytes = 0
        self._buf_last_lsn = 0
        self._buf_traces: list = []
        self._durable_lsn = 0    # last LSN on stable storage
        self._resolved_lsn = 0   # last LSN whose commit was attempted
        self._checkpoint_lsn = 0
        # barrier waiters: (target_lsn, future, intervals|None)
        self._waiters: list = []
        # commit-failure attribution: (lo, hi] LSN ranges that never hit disk
        self._failed: list[tuple[int, int]] = []
        self._failed_floor = 0
        self._reported_lsn = 0   # consume-once watermark for flush(None)
        self._errors = 0
        self._closed = False
        self._wake = asyncio.Event()
        self._writer: Optional[SegmentWriter] = None
        # sealed but not yet checkpoint-truncated: (first, last, path, size)
        self._sealed: list[tuple[int, int, str, int]] = []
        self._sealed_bytes = 0
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="wal")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._commit_task: Optional[asyncio.Task] = None
        self._checkpoint_task: Optional[asyncio.Task] = None
        # -- memtable (event-loop side) --
        # ops appended but not yet handed to the inner index, in program
        # order, plus a two-generation overlay of recent message blobs:
        # the current generation mirrors _pending, the previous one is
        # already in the inner FIFO but kept hot for one more drain
        # interval so backlog hydration stays a dict hit
        self._pending: list = []
        self._pending_bytes = 0
        self._mem_msgs: dict = {}   # msg_id -> StoredMessage | None (dead)
        self._mem_prev: dict = {}
        self._drain_task: Optional[asyncio.Task] = None
        self._drain_kicked = False
        # per-queue constant payload chunk for the row fast paths
        # (vhost, queue) -> encoded string pair; rebuilt from scratch if
        # it ever outgrows a sane queue count
        self._qprefix: dict = {}
        # blob held back from insert_message_nowait so the queue-log row
        # that immediately follows (push_local -> queue.push) fuses with
        # it into one insert_published record; every other observation
        # point flushes it first (see _flush_stash)
        self._stash = None
        # open transaction scope: while not None, _ingest diverts every
        # (op, args) here instead of framing it, and tx_seal() folds the
        # lot into ONE tx_batch record (see tx_begin)
        self._tx_buf: Optional[list] = None
        # stream maintenance bookkeeping
        self._compact_flag: dict[tuple[str, str], bool] = {}
        self._compacted_thru: dict[tuple[str, str], int] = {}
        self.recovered_records = 0

    @property
    def memtable_pending_bytes(self) -> int:
        """Accounted-memory gauge for the flow ladder: bytes staged in the
        memtable awaiting the next index drain (Broker._flow_tick polls
        this once per sweep)."""
        return self._pending_bytes

    def __getattr__(self, name):
        # anything WalStore doesn't reimplement (diagnostics such as
        # ``synchronous``/``_submit``, the cluster_kv helpers) falls
        # through to the index store
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # -- health aggregation -------------------------------------------------

    @property
    def error_count(self) -> int:
        """Own commit/checkpoint failures + the inner store's background
        write failures — telemetry readiness reads one number."""
        return self._errors + int(getattr(self._inner, "error_count", 0))

    def _fire_done(self, task) -> None:
        # base class assigns self.error_count (here a read-only property)
        self._fired_tasks.discard(task)
        if not task.cancelled() and task.exception():
            self._errors += 1
            log.error("background store write failed: %r", task.exception())

    # -- append + barriers --------------------------------------------------

    def _append(self, op: str, args: tuple) -> int:
        if self._stash is not None:
            self._flush_stash()
        if self._closed:
            raise RuntimeError("wal is closed")
        t0 = time.perf_counter_ns()
        lsn = self._lsn + 1
        frame = encode_record(lsn, OP_INDEX[op], args)
        self._ingest(lsn, op, args, frame)
        act = trace.ACTIVE
        if act is not None:
            tr = act.current
            if tr is not None:
                tr.span(trace.WAL_APPEND, t0, time.perf_counter_ns(),
                        act.node)
                if len(self._buf_traces) < _TRACE_CAP:
                    self._buf_traces.append(tr)
        prof = profile.ACTIVE
        if prof is not None:
            # reuses the span's existing t0 stamp: one extra stamp + two
            # array adds per append on the durable path only
            prof.stage_ns[profile.WAL_APPEND] += (
                time.perf_counter_ns() - t0)
            prof.stage_calls[profile.WAL_APPEND] += 1
        return lsn

    def _ingest(self, lsn: int, op: str, args: tuple, frame: bytes) -> None:
        """Shared append bookkeeping once a frame's bytes exist: stage for
        the commit loop, stage for the memtable drain, count, wake."""
        if self._tx_buf is not None:
            # open transaction scope: the op joins the scope buffer and its
            # individually framed bytes are discarded — tx_seal() re-frames
            # the whole scope as one atomic tx_batch record. The memtable is
            # NOT staged here either, so an aborted scope leaves no trace
            # (the scope is synchronous: no read can interleave mid-scope).
            self._tx_buf.append((op, args))
            return
        self._lsn = lsn
        self._buf.append(frame)
        n = len(frame)
        self._buf_bytes += n
        self._buf_last_lsn = lsn
        self._pending.append((op, args))
        self._pending_bytes += n
        if (self._pending_bytes >= self.memtable_bytes
                and not self._drain_kicked and self._loop is not None):
            # memtable overgrew between checkpoints: drain early so RAM
            # stays bounded by ~2 generations of memtable-bytes
            self._drain_kicked = True
            self._fire(self._drain())
        m = self.metrics
        m.wal_appends += 1
        m.wal_append_bytes += n
        if not self._wake.is_set():
            self._wake.set()

    def mark(self) -> int:
        """LSN of the last appended record — callers capture windows around
        their appends and pass (before, after] intervals to flush()."""
        if self._stash is not None:
            self._flush_stash()
        return self._lsn

    def _failed_overlap(self, lo: int, hi: int) -> bool:
        """Does the (lo, hi] window touch a failed-commit LSN range?"""
        if lo < self._failed_floor:
            return True  # conservative: range details were dropped
        for flo, fhi in reversed(self._failed):
            if flo < hi and fhi > lo:
                return True
        return False

    def _covered_failure(self, target: int, intervals) -> bool:
        if intervals is None:
            lo = self._reported_lsn
            if target > self._reported_lsn:
                self._reported_lsn = target
            return self._failed_overlap(lo, target)
        return any(self._failed_overlap(a, b) for a, b in intervals)

    def _barrier(self, target: int, intervals):
        loop = self._loop or asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        if self._resolved_lsn >= target:
            if self._covered_failure(target, intervals):
                fut.set_exception(RuntimeError(
                    "wal commit failed under this durability barrier"))
            else:
                fut.set_result(None)
            return fut
        self._waiters.append((target, fut, intervals))
        if not self._wake.is_set():
            self._wake.set()
        return fut

    def flush(self, intervals=None):
        """Durability barrier at the WAL commit boundary.

        Attributed form (publisher confirms, push replies): resolves when
        every LSN inside the caller's windows is fsync-durable, raising iff
        a failed commit overlaps them.  Global form (shutdown, tests) also
        barriers the inner store so index-write failures surface."""
        if intervals is not None:
            if not intervals:
                loop = self._loop or asyncio.get_running_loop()
                fut: asyncio.Future = loop.create_future()
                fut.set_result(None)
                return fut
            return self._barrier(max(hi for _, hi in intervals), intervals)
        if self._stash is not None:
            self._flush_stash()
        target = self._lsn

        async def wait() -> None:
            await self._barrier(target, None)
            await self._settle()
            await self._inner.flush()

        return wait()

    def _resolve_waiters(self) -> None:
        if not self._waiters:
            return
        keep = []
        for target, fut, intervals in self._waiters:
            if target > self._resolved_lsn:
                keep.append((target, fut, intervals))
            elif not fut.cancelled():
                if self._covered_failure(target, intervals):
                    fut.set_exception(RuntimeError(
                        "wal commit failed under this durability barrier"))
                else:
                    fut.set_result(None)
        self._waiters = keep

    # -- memtable drain -------------------------------------------------------

    def _forward(self, ops: list) -> None:
        """Hand ops to the inner store in program order.  The inner FIFO
        enqueues synchronously, so any read submitted afterwards sees
        them; awaited-form inner calls are fired — failures land in
        error_count and the next checkpoint's inner.flush() raises on
        them before the checkpoint LSN can advance."""
        inner = self._inner
        fire = self._fire
        for name, args in ops:
            if name == "insert_message":
                inner.insert_message_nowait(args[0])
            elif name == "insert_queue_msg":
                inner.insert_queue_msg_nowait(*args)
            elif name == "insert_published":
                inner.insert_message_nowait(args[0])
                inner.insert_queue_msg_nowait(
                    args[1], args[2], args[3], args[0].id, args[4], args[5])
            elif name == "insert_queue_unacks":
                inner.insert_queue_unacks_nowait(*args)
            elif name == "worker_id_floor":
                fire(inner.worker_id_floor(args[0]))
            else:
                fire(getattr(inner, name)(*args))

    async def _settle(self) -> None:
        """Read barrier: every appended op becomes visible to the inner
        FIFO before the caller's read enqueues behind it.  Cheap when the
        memtable is empty (the overlay absorbs the hot hydration reads,
        so this mostly runs for control-plane and recovery reads)."""
        while self._drain_task is not None:
            try:
                await asyncio.shield(self._drain_task)
            except Exception:
                pass  # the drain's creator observed and counted it
        if self._stash is not None:
            self._flush_stash()
        if self._pending:
            ops = self._pending
            self._pending = []
            self._pending_bytes = 0
            self._mem_prev.update(self._mem_msgs)
            self._mem_msgs = {}
            self._forward(ops)

    async def _drain(self) -> None:
        """Full drain with coalescing — the checkpoint-path form."""
        while self._drain_task is not None:
            try:
                await asyncio.shield(self._drain_task)
            except Exception:
                pass
        if not self._pending:
            return
        self._drain_task = asyncio.ensure_future(self._drain_run())
        try:
            await self._drain_task
        finally:
            self._drain_task = None

    async def _drain_run(self) -> None:
        self._drain_kicked = False
        if self._stash is not None:
            self._flush_stash()
        ops = self._pending
        self._pending = []
        self._pending_bytes = 0
        # rotate the overlay: the outgoing generation keeps serving reads
        # for one more interval (its rows reach the inner FIFO below, but
        # a dict hit beats the executor round trip); the one before ages out
        self._mem_prev = self._mem_msgs
        self._mem_msgs = {}
        if len(ops) >= _COALESCE_INLINE:
            loop = self._loop or asyncio.get_running_loop()
            net, elided = await loop.run_in_executor(None, _coalesce_ops, ops)
        else:
            net, elided = _coalesce_ops(ops)
        self._forward(net)
        m = self.metrics
        m.wal_memtable_drains += 1
        m.wal_memtable_elided += elided

    def _mem_get(self, msg_id):
        gen = self._mem_msgs
        if msg_id in gen:
            return gen[msg_id], True
        gen = self._mem_prev
        if msg_id in gen:
            return gen[msg_id], True
        return None, False

    # -- commit loop ---------------------------------------------------------

    async def _commit_loop(self) -> None:
        try:
            while not self._closed:
                await self._wake.wait()
                self._wake.clear()
                if self._closed:
                    return
                if not self._buf:
                    if self._stash is None:
                        self._resolve_waiters()
                        continue
                    self._flush_stash()
                # group window: let concurrent channels pile into the batch
                # unless the byte cap says the batch is already worth a trip
                if self._buf_bytes < self.flush_bytes and self.flush_ms > 0:
                    await asyncio.sleep(self.flush_ms / 1000.0)
                await self._commit_once()
        except asyncio.CancelledError:
            pass

    async def _commit_once(self) -> None:
        if self._stash is not None:
            self._flush_stash()
        frames = self._buf
        if not frames:
            self._resolve_waiters()
            return
        self._buf = []
        self._buf_bytes = 0
        traces = self._buf_traces
        self._buf_traces = []
        target = self._buf_last_lsn
        data = b"".join(frames)
        writer = self._writer
        fsync = self.sync_mode == "fsync"
        seg_cap = self.segment_bytes

        def job() -> Optional[SegmentWriter]:
            writer.append(data, target)
            writer.sync(fsync)
            if writer.size >= seg_cap:
                return writer.roll(fsync)
            return None

        loop = self._loop or asyncio.get_running_loop()
        t0 = time.perf_counter_ns()
        try:
            rolled = await loop.run_in_executor(self._executor, job)
        except Exception as exc:
            lo = self._resolved_lsn
            self._resolved_lsn = target
            self._failed.append((lo, target))
            if len(self._failed) > _FAILED_CAP:
                _, hi = self._failed.pop(0)
                self._failed_floor = max(self._failed_floor, hi)
            self._errors += 1
            self.metrics.wal_commit_errors += 1
            log.error("wal commit failed (lsn %d..%d): %r",
                      lo + 1, target, exc)
            self._resolve_waiters()
            return
        t1 = time.perf_counter_ns()
        self._durable_lsn = target
        self._resolved_lsn = target
        m = self.metrics
        m.wal_commits += 1
        if fsync:
            m.wal_fsyncs += 1
        m.wal_commit_us.observe_us((t1 - t0) / 1000.0)
        if rolled is not None:
            self._sealed.append(
                (writer.first_lsn, writer.last_lsn, writer.path, writer.size))
            self._sealed_bytes += writer.size
            self._writer = rolled
            m.wal_segments_sealed += 1
        if traces:
            act = trace.ACTIVE
            node = act.node if act is not None else "local"
            for tr in traces:
                tr.span(trace.WAL_COMMIT, t0, t1, node)
        prof = profile.ACTIVE
        if prof is not None:
            # commit wall time is executor-side fsync work; one call per
            # batch commit, so ns/calls reads as µs per commit batch
            prof.stage_ns[profile.WAL_COMMIT] += t1 - t0
            prof.stage_calls[profile.WAL_COMMIT] += 1
        self._resolve_waiters()

    # -- checkpoint + segment truncation -------------------------------------

    async def _checkpoint_loop(self) -> None:
        try:
            while not self._closed:
                await asyncio.sleep(self.checkpoint_ms / 1000.0)
                if self._closed:
                    return
                try:
                    await self._checkpoint_once()
                except Exception as exc:
                    self._errors += 1
                    self.metrics.wal_checkpoint_errors += 1
                    log.error("wal checkpoint failed: %r", exc)
                try:
                    await self._maintain_streams()
                except Exception as exc:
                    self._errors += 1
                    log.error("wal stream maintenance failed: %r", exc)
        except asyncio.CancelledError:
            pass

    async def _checkpoint_once(self) -> None:
        target = self._lsn
        if target == self._checkpoint_lsn and not self._sealed:
            return
        # drain the memtable (coalesced — churn that lived and died inside
        # the interval never reaches SQLite), then barrier the inner store:
        # after this the index durably covers every LSN <= target...
        await self._drain()
        await self._inner.flush()
        await self._inner.put_kv(CHECKPOINT_KEY, target)
        if self.sync_mode == "fsync":
            # ...and this makes it POWER-durable: under synchronous=NORMAL
            # SQLite only fsyncs at wal_checkpoint, so without it a power
            # cut after segment truncation could lose acknowledged data
            await self._inner.checkpoint_sync()
        self._checkpoint_lsn = target
        self.metrics.wal_checkpoints += 1
        drop = [s for s in self._sealed if s[1] <= target]
        if not drop:
            return
        self._sealed = [s for s in self._sealed if s[1] > target]
        loop = self._loop or asyncio.get_running_loop()

        def unlink() -> None:
            for _first, _last, path, _size in drop:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            fsync_dir(self.dir)

        await loop.run_in_executor(self._executor, unlink)
        for _first, _last, _path, size in drop:
            self._sealed_bytes -= size
        self.metrics.wal_segments_truncated += len(drop)

    # -- recovery -------------------------------------------------------------

    async def _recover(self) -> None:
        loop = self._loop
        checkpoint = await self._inner.get_kv(CHECKPOINT_KEY) or 0
        self._checkpoint_lsn = checkpoint
        segs = list_segments(self.dir)
        m = self.metrics
        last_lsn = checkpoint
        replayed = 0
        pending: list = []
        stop = False
        for i, (_first, path) in enumerate(segs):
            payloads, good, status = await loop.run_in_executor(
                self._executor, read_segment, path)
            if status == "corrupt" or (status == "torn"
                                       and i != len(segs) - 1):
                # mid-log damage: ordering below it is untrusted — stop
                # replay here and quarantine this + every later segment
                m.wal_recover_corrupt += 1
                log.error("wal segment %s is corrupt; replay stops here "
                          "(%d record(s) salvaged)", path, len(payloads))
                stop = True
            elif status == "torn":
                # crash cut the final append: drop the tail, keep the rest
                m.wal_recover_torn += 1
                log.warning("wal segment %s has a torn tail; truncating "
                            "at %d bytes", path, good)
                await loop.run_in_executor(
                    self._executor, truncate_segment, path, good)
            for payload in payloads:
                try:
                    lsn, op, args = decode_payload(payload)
                except WalCodecError as exc:
                    m.wal_recover_corrupt += 1
                    log.error("wal record decode failed in %s: %r", path, exc)
                    stop = True
                    break
                if lsn > last_lsn:
                    if op < len(_REPLAY_OPS):
                        pending.append(_REPLAY_OPS[op](self._inner, args))
                        replayed += 1
                    last_lsn = lsn
                if len(pending) >= 1000:
                    await asyncio.gather(*pending)
                    pending = []
            if stop:
                for _flsn, later in segs[i:]:
                    quarantine(later)
                break
        if pending:
            await asyncio.gather(*pending)
        self._lsn = last_lsn
        self.recovered_records = replayed
        m.wal_recovered_records += replayed
        if replayed or segs:
            # re-checkpoint so the replayed tail is in the index and the
            # old segments can go; recovery is idempotent if we die here
            await self._inner.flush()
            await self._inner.put_kv(CHECKPOINT_KEY, last_lsn)
            if self.sync_mode == "fsync":
                await self._inner.checkpoint_sync()
            self._checkpoint_lsn = last_lsn

            def cleanup() -> None:
                for _flsn, path in segs:
                    if os.path.exists(path):
                        os.unlink(path)
                fsync_dir(self.dir)

            if not stop:
                await loop.run_in_executor(self._executor, cleanup)
        self._durable_lsn = last_lsn
        self._resolved_lsn = last_lsn
        self._reported_lsn = last_lsn
        if replayed:
            log.info("wal recovery replayed %d record(s) over checkpoint %d",
                     replayed, checkpoint)

    # -- lifecycle ------------------------------------------------------------

    async def open(self) -> None:
        self._loop = asyncio.get_running_loop()
        await self._inner.open()
        await self._loop.run_in_executor(self._executor, ensure_dir, self.dir)
        await self._recover()
        self._writer = await self._loop.run_in_executor(
            self._executor, SegmentWriter, self.dir, self._lsn + 1)
        await self._loop.run_in_executor(None, self.tier.scan)
        self._commit_task = asyncio.ensure_future(self._commit_loop())
        self._checkpoint_task = asyncio.ensure_future(self._checkpoint_loop())

    async def close(self) -> None:
        if self._closed:
            await self._inner.close()
            return
        self._closed = True
        for task in (self._commit_task, self._checkpoint_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        self._commit_task = self._checkpoint_task = None
        if self._writer is not None:
            await self._commit_once()  # land whatever the window held
            try:
                await self._checkpoint_once()
            except Exception:
                pass
            writer = self._writer
            self._writer = None
            loop = asyncio.get_running_loop()
            fsync = self.sync_mode == "fsync"
            fully_checkpointed = self._checkpoint_lsn >= self._lsn

            def finish() -> None:
                writer.close(fsync)
                if fully_checkpointed:
                    # clean shutdown: the index covers the whole log, the
                    # active segment carries nothing recovery would replay
                    try:
                        os.unlink(writer.path)
                    except OSError:
                        pass
                    fsync_dir(self.dir)

            await loop.run_in_executor(self._executor, finish)
        self._resolve_waiters()
        self._executor.shutdown(wait=False)
        await self._inner.close()

    async def approx_data_bytes(self) -> Optional[int]:
        base = await self._inner.approx_data_bytes()
        wal = self._sealed_bytes + (
            self._writer.size if self._writer is not None else 0)
        return (base or 0) + wal + self.tier.data_bytes

    # -- memtable plumbing ---------------------------------------------------

    def _through(self, name: str, *args):
        """Journal an awaited-form write: the WAL frame is the durable
        copy, the memtable carries it to the index at the next drain, and
        the returned barrier resolves (or raises) at the fsync covering
        this record — same attribution contract the nowait paths get via
        flush(intervals)."""
        lsn = self._append(name, args)
        return self._barrier(lsn, [(lsn - 1, lsn)])

    # -- transaction scope (Tx.Commit atomicity) ----------------------------
    #
    # A group-commit batch is one fsync but MANY frames: scan_frames
    # truncates at the first torn frame, so a SIGKILL mid-write can leave a
    # durable prefix of a multi-record transaction — partial commit on
    # replay.  The scope closes that hole: between tx_begin() and tx_seal()
    # every append diverts into a buffer and the seal frames the lot as one
    # tx_batch record (one CRC — fully durable or fully torn).  The scope
    # MUST stay synchronous (no awaits between begin and seal): reads,
    # drains, checkpoints and the commit loop all assume they never observe
    # a half-open scope, which a single event-loop turn guarantees.

    def tx_begin(self) -> None:
        """Open an atomic append scope. Raises if one is already open."""
        if self._tx_buf is not None:
            raise RuntimeError("wal transaction scope already open")
        if self._stash is not None:
            self._flush_stash()
        self._tx_buf = []

    def tx_abort(self) -> None:
        """Drop an open scope: nothing was framed, staged or forwarded —
        the WAL and memtable look as if the scope never opened."""
        if self._tx_buf is None:
            return
        if self._stash is not None:
            self._flush_stash()  # diverted into the buffer being dropped
        self._tx_buf = None

    def tx_seal(self) -> int:
        """Close the scope: frame every diverted op as ONE tx_batch record,
        stage the sub-ops in the memtable, and return the record's LSN
        (== mark(); callers barrier on flush([(mark0, lsn)]))."""
        if self._stash is not None:
            self._flush_stash()
        ops, self._tx_buf = self._tx_buf, None
        if not ops:
            return self._lsn
        lsn = self._lsn + 1
        sub = [(OP_INDEX[name], args) for name, args in ops]
        frame = encode_record(lsn, OP_INDEX["tx_batch"], (sub,))
        self._lsn = lsn
        self._buf.append(frame)
        n = len(frame)
        self._buf_bytes += n
        self._buf_last_lsn = lsn
        self._pending.extend(ops)
        self._pending_bytes += n
        if (self._pending_bytes >= self.memtable_bytes
                and not self._drain_kicked and self._loop is not None):
            self._drain_kicked = True
            self._fire(self._drain())
        m = self.metrics
        m.wal_appends += 1
        m.wal_append_bytes += n
        m.wal_tx_batches += 1
        m.wal_tx_batch_ops += len(ops)
        if not self._wake.is_set():
            self._wake.set()
        return lsn

    # fire-and-forget hot path: append only, no future machinery — the
    # memtable overlay keeps the blob readable until the drain lands it.
    # insert_message_nowait holds the blob back (stash): the queue-log
    # row that follows in the same synchronous block fuses with it into
    # ONE insert_published record, so the common persistent publish
    # frames and CRCs once.  Fast paths use the hand-rolled frame
    # builders; tracing or an unprovable shape falls back to _append,
    # which also owns the wal-append span.
    def _flush_stash(self) -> None:
        """Journal a held-back blob as a plain insert_message record.

        Must run before anything observes the log position or the
        pending-op list: _append (any other op), mark(), flush(), commit
        gather, memtable drains/settles, and close all call this first.
        """
        stash, self._stash = self._stash, None
        if stash is None:
            return
        frame = encode_insert_message(self._lsn + 1, stash)
        if frame is None:
            frame = encode_record(
                self._lsn + 1, OP_INDEX["insert_message"], (stash,))
        self._ingest(self._lsn + 1, "insert_message", (stash,), frame)

    def _vq_prefix(self, vhost: str, queue: str) -> bytes:
        vq = self._qprefix.get((vhost, queue))
        if vq is None:
            if len(self._qprefix) >= 4096:
                self._qprefix.clear()
            vq = queue_prefix(vhost, queue)
            self._qprefix[(vhost, queue)] = vq
        return vq

    def insert_message_nowait(self, msg) -> None:
        if self._stash is not None:
            self._flush_stash()
        if trace.ACTIVE is None and not self._closed:
            self._stash = msg
            self._mem_msgs[msg.id] = msg
            if not self._wake.is_set():
                self._wake.set()  # the commit gather flushes the stash
            return
        self._append("insert_message", (msg,))
        self._mem_msgs[msg.id] = msg

    def insert_queue_msg_nowait(self, vhost, queue, offset, msg_id,
                                body_size, expire_at_ms) -> None:
        stash = self._stash
        if stash is not None and stash.id == msg_id:
            self._stash = None
            if (trace.ACTIVE is None and not self._closed
                    and type(vhost) is str and type(queue) is str):
                frame = encode_insert_published(
                    self._lsn + 1, stash, self._vq_prefix(vhost, queue),
                    offset, body_size, expire_at_ms)
                if frame is not None:
                    self._ingest(self._lsn + 1, "insert_published",
                                 (stash, vhost, queue, offset, body_size,
                                  expire_at_ms), frame)
                    return
            self._append("insert_message", (stash,))
            self._append("insert_queue_msg",
                         (vhost, queue, offset, msg_id, body_size,
                          expire_at_ms))
            return
        if stash is not None:
            self._flush_stash()
        if (trace.ACTIVE is None and not self._closed
                and type(vhost) is str and type(queue) is str):
            frame = encode_insert_queue_msg(
                self._lsn + 1, self._vq_prefix(vhost, queue), offset,
                msg_id, body_size, expire_at_ms)
            if frame is not None:
                self._ingest(self._lsn + 1, "insert_queue_msg",
                             (vhost, queue, offset, msg_id, body_size,
                              expire_at_ms), frame)
                return
        self._append("insert_queue_msg",
                     (vhost, queue, offset, msg_id, body_size, expire_at_ms))

    def insert_queue_unacks_nowait(self, vhost, queue, unacks) -> None:
        unacks = [tuple(u) for u in unacks]
        self._append("insert_queue_unacks", (vhost, queue, unacks))

    # -- messages --

    def insert_message(self, msg):
        self._mem_msgs[msg.id] = msg
        return self._through("insert_message", msg)

    async def select_message(self, msg_id):
        val, hit = self._mem_get(msg_id)
        if hit:
            self.metrics.wal_memtable_hits += 1
            return val
        await self._settle()
        return await self._inner.select_message(msg_id)

    async def select_messages(self, msg_ids):
        out = {}
        for mid in msg_ids:
            val, hit = self._mem_get(mid)
            if not hit:
                # one cold id sends the whole batch to the index (after a
                # settle it covers the overlay's rows too — no merge needed)
                await self._settle()
                return await self._inner.select_messages(list(msg_ids))
            if val is not None:
                out[mid] = val
        self.metrics.wal_memtable_hits += len(out)
        return out

    async def select_message_metas(self, msg_ids):
        await self._settle()
        return await self._inner.select_message_metas(msg_ids)

    def delete_message(self, msg_id):
        self._mem_msgs[msg_id] = None
        return self._through("delete_message", msg_id)

    def delete_messages(self, msg_ids):
        ids = list(msg_ids)
        mem = self._mem_msgs
        for mid in ids:
            mem[mid] = None
        return self._through("delete_messages", ids)

    def update_message_refer_count(self, msg_id, count):
        val, hit = self._mem_get(msg_id)
        if hit and val is not None:
            self._mem_msgs[msg_id] = dc_replace(val, refer_count=count)
        return self._through("update_message_refer_count", msg_id, count)

    # -- queue meta + log --

    def insert_queue_meta(self, q):
        return self._through("insert_queue_meta", q)

    async def select_queue(self, vhost, name):
        await self._settle()
        return await self._inner.select_queue(vhost, name)

    async def all_queues(self, vhost=None):
        await self._settle()
        return await self._inner.all_queues(vhost)

    def insert_queue_msg(self, vhost, queue, offset, msg_id, body_size,
                         expire_at_ms):
        return self._through("insert_queue_msg", vhost, queue, offset,
                             msg_id, body_size, expire_at_ms)

    def delete_queue_msg(self, vhost, queue, offset):
        return self._through("delete_queue_msg", vhost, queue, offset)

    async def iter_queue_msgs(self, vhost, queue, after_offset, limit):
        await self._settle()
        return await self._inner.iter_queue_msgs(
            vhost, queue, after_offset, limit)

    def replace_queue_msgs(self, vhost, queue, msgs):
        return self._through("replace_queue_msgs", vhost, queue,
                             [tuple(m) for m in msgs])

    def replace_queue_unacks(self, vhost, queue, unacks):
        return self._through("replace_queue_unacks", vhost, queue,
                             [tuple(u) for u in unacks])

    def update_queue_last_consumed(self, vhost, queue, last_consumed):
        return self._through("update_queue_last_consumed", vhost, queue,
                             last_consumed)

    def insert_queue_unacks(self, vhost, queue, unacks):
        return self._through("insert_queue_unacks", vhost, queue,
                             [tuple(u) for u in unacks])

    def delete_queue_msgs_offsets(self, vhost, queue, offsets):
        return self._through("delete_queue_msgs_offsets", vhost, queue,
                             list(offsets))

    def delete_queue_unacks(self, vhost, queue, msg_ids):
        return self._through("delete_queue_unacks", vhost, queue,
                             list(msg_ids))

    def archive_queue(self, vhost, queue):
        return self._through("archive_queue", vhost, queue)

    def delete_queue(self, vhost, queue):
        self._compact_flag.pop((vhost, queue), None)
        return self._through("delete_queue", vhost, queue)

    def purge_queue_msgs(self, vhost, queue):
        return self._through("purge_queue_msgs", vhost, queue)

    # -- streams --

    def insert_stream_segment(self, vhost, queue, base_offset, last_offset,
                              first_ts_ms, last_ts_ms, size_bytes, blob):
        return self._through(
            "insert_stream_segment", vhost, queue, base_offset, last_offset,
            first_ts_ms, last_ts_ms, size_bytes, blob)

    async def select_stream_segment(self, vhost, queue, base_offset):
        await self._settle()
        blob = await self._inner.select_stream_segment(
            vhost, queue, base_offset)
        if blob is None:
            # index row may live on with its bytes offloaded to the tier
            loop = self._loop or asyncio.get_running_loop()
            blob = await loop.run_in_executor(
                None, self.tier.read, vhost, queue, base_offset)
            if blob is not None:
                self.metrics.wal_tier_rehydrations += 1
        return blob

    async def stream_segment_metas(self, vhost, queue):
        await self._settle()
        return await self._inner.stream_segment_metas(vhost, queue)

    def delete_stream_segments(self, vhost, queue, base_offsets):
        base_offsets = list(base_offsets)
        self.tier.forget(vhost, queue, base_offsets)
        return self._through(
            "delete_stream_segments", vhost, queue, base_offsets)

    def update_stream_cursor(self, vhost, queue, name, committed_offset):
        return self._through("update_stream_cursor", vhost, queue, name,
                             committed_offset)

    async def select_stream_cursors(self, vhost, queue):
        await self._settle()
        return await self._inner.select_stream_cursors(vhost, queue)

    def delete_stream_data(self, vhost, queue):
        self._compact_flag.pop((vhost, queue), None)
        self._compacted_thru.pop((vhost, queue), None)
        self.tier.forget_queue(vhost, queue)
        return self._through("delete_stream_data", vhost, queue)

    # -- exchanges + binds --

    def insert_exchange(self, ex):
        return self._through("insert_exchange", ex)

    async def select_exchange(self, vhost, name):
        await self._settle()
        return await self._inner.select_exchange(vhost, name)

    async def all_exchanges(self, vhost=None):
        await self._settle()
        return await self._inner.all_exchanges(vhost)

    def delete_exchange(self, vhost, name):
        return self._through("delete_exchange", vhost, name)

    def insert_bind(self, vhost, exchange, queue, routing_key, arguments):
        return self._through("insert_bind", vhost, exchange, queue,
                             routing_key, arguments)

    def delete_bind(self, vhost, exchange, queue, routing_key):
        return self._through("delete_bind", vhost, exchange, queue,
                             routing_key)

    def delete_queue_binds(self, vhost, queue):
        return self._through("delete_queue_binds", vhost, queue)

    def insert_exchange_bind(self, vhost, source, destination, routing_key,
                             arguments):
        return self._through("insert_exchange_bind", vhost, source,
                             destination, routing_key, arguments)

    def delete_exchange_bind(self, vhost, source, destination, routing_key):
        return self._through("delete_exchange_bind", vhost, source,
                             destination, routing_key)

    def delete_exchange_binds_dest(self, vhost, destination):
        return self._through("delete_exchange_binds_dest", vhost, destination)

    # -- worker ids + vhosts --

    async def allocate_worker_id(self) -> int:
        # the id comes from the inner counter; journaling the floor makes
        # the allocation crash-safe — replay re-raises next_worker_id so an
        # id handed out just before SIGKILL can never be handed out again
        wid = await self._inner.allocate_worker_id()
        lsn = self._append("worker_id_floor", (wid,))
        await self._barrier(lsn, [(lsn - 1, lsn)])
        return wid

    def insert_vhost(self, name, active=True):
        return self._through("insert_vhost", name, active)

    async def all_vhosts(self):
        await self._settle()
        return await self._inner.all_vhosts()

    def delete_vhost(self, name):
        return self._through("delete_vhost", name)

    # -- stream maintenance: key compaction + tiered offload ------------------

    async def _queue_compacts(self, vhost: str, queue: str) -> bool:
        key = (vhost, queue)
        flag = self._compact_flag.get(key)
        if flag is None:
            args = await self._inner.queue_arguments(vhost, queue)
            flag = bool(args and args.get("x-stream-compact"))
            self._compact_flag[key] = flag
        return flag

    async def _maintain_streams(self) -> None:
        if self.tier_keep <= 0 and not self.compact_streams:
            return
        await self._settle()  # sealed-segment inserts may still be pending
        index = await self._inner.stream_segment_index()
        by_queue: dict[tuple[str, str], list] = {}
        for vhost, queue, base, size, has_blob in index:
            by_queue.setdefault((vhost, queue), []).append(
                (base, size, bool(has_blob)))
        for (vhost, queue), segs in by_queue.items():
            segs.sort()
            if self._closed:
                return
            if self.compact_streams and await self._queue_compacts(
                    vhost, queue):
                await self._compact_queue(vhost, queue, segs)
            if self.tier_keep > 0:
                await self._offload_queue(vhost, queue, segs)

    async def _compact_queue(self, vhost: str, queue: str,
                             segs: list) -> None:
        """Newest-first key walk over the queue's hot sealed blobs; only
        runs when a segment newer than the last pass exists (one new seal
        re-reads the queue's hot set — bounded by the cache-sized window
        the offloader leaves hot)."""
        unpack_records = _stream_segment_mod().unpack_records
        hot = [(base, size) for base, size, has_blob in segs if has_blob]
        if not hot:
            return
        key = (vhost, queue)
        if hot[-1][0] <= self._compacted_thru.get(key, -1):
            return
        seen: set = set()
        for base, _size in reversed(hot):
            blob = await self._inner.select_stream_segment(vhost, queue, base)
            if blob is None:
                continue
            try:
                records = unpack_records(blob)
            except Exception as exc:
                log.error("compaction skipped %s/%s seg %d: %r",
                          vhost, queue, base, exc)
                continue
            kept, dropped = compact_records(records, seen)
            if dropped:
                new_blob, new_size = compacted_blob(kept)
                await self._inner.replace_stream_segment_blob(
                    vhost, queue, base, new_blob, new_size)
                self.metrics.wal_compactions += 1
                self.metrics.wal_compacted_records += dropped
        self._compacted_thru[key] = hot[-1][0]

    async def _offload_queue(self, vhost: str, queue: str,
                             segs: list) -> None:
        """Evict blob bytes of all but the newest tier-keep hot segments
        into tier side files; the index row stays so cursors still see the
        segment and reads rehydrate from the tier file."""
        hot = [base for base, _size, has_blob in segs if has_blob]
        loop = self._loop or asyncio.get_running_loop()
        for base in hot[:-self.tier_keep] if len(hot) > self.tier_keep else []:
            if self._closed:
                return
            blob = await self._inner.select_stream_segment(vhost, queue, base)
            if blob is None:
                continue
            # durable order: tier file is fsynced before the SQLite blob
            # drops, so a crash between the two leaves both copies at worst
            await loop.run_in_executor(
                None, self.tier.write, vhost, queue, base, blob)
            await self._inner.evict_stream_blob(vhost, queue, base)
            self.metrics.wal_tier_offloads += 1


def _make_replay(name: str):
    if name == "worker_id_floor":
        return lambda inner, args: inner.worker_id_floor(args[0])
    if name == "tx_batch":
        def replay_tx(inner, args):
            # args = ([(op_index, sub_args), ...],): apply every sub-op —
            # the record is one frame, so recovery sees all of them or
            # none (the all-or-nothing contract Tx.Commit rides on).
            # _REPLAY_OPS resolves late: it exists by the time any replay
            # runs, and a tx_batch never nests another tx_batch.
            return asyncio.gather(*[
                _REPLAY_OPS[op](inner, sub_args)
                for op, sub_args in args[0] if op < len(_REPLAY_OPS)])
        return replay_tx
    if name == "insert_published":
        def replay_published(inner, args):
            msg, vhost, queue, offset, body_size, expire_at_ms = args
            return asyncio.gather(
                inner.insert_message(msg),
                inner.insert_queue_msg(vhost, queue, offset, msg.id,
                                       body_size, expire_at_ms))
        return replay_published

    def replay(inner, args, _name=name):
        return getattr(inner, _name)(*args)

    return replay


# replay table indexed by wire op — one closure per op, no per-record getattr
from .codec import OPS as _OPS  # noqa: E402

_REPLAY_OPS = tuple(_make_replay(name) for name in _OPS)
