"""StreamQueue: an append-only log queue with non-destructive cursors.

Selected with ``x-queue-type: stream`` at declare time. Differences from
the classic `Queue` (RabbitMQ-streams semantics):

- publishes APPEND records (offset, timestamp, header+body) to an active
  in-memory segment; at a size/age threshold the segment seals and
  spills to the store as one blob (streams/segment.py);
- consumers are independent CURSORS attaching at ``x-stream-offset``
  (``first`` | ``last`` | ``next`` | absolute offset | timestamp); every
  cursor sees every record, reading through the same prefetch/QoS credit
  machinery as classic consumers;
- ack never deletes data — it COMMITS the cursor's offset, persisted
  server-side (keyed by consumer tag) so a reconnecting consumer
  resumes where it left off;
- retention is by ``x-max-length-bytes`` / ``x-max-age``, enforced as
  whole-segment truncation of the oldest sealed segments only.

The class subclasses `Queue` to share the consumer registry, exclusive
ownership, and admin surface, but replaces the ready-deque machinery
(push/dispatch/ack/requeue/get) with cursor reads over the segment log.
"""

from __future__ import annotations

import asyncio
import datetime as _dt
import logging
from typing import TYPE_CHECKING, Any, Optional, Union

from .. import trace
from ..amqp.properties import BasicProperties
from ..amqp.value_codec import Timestamp
from ..broker.entities import Delivery, Message, Queue, QueuedMessage, now_ms
from .segment import (
    Segment, StreamRecord, pack_records, unpack_records_indexed,
)
from .groups import GROUP_CURSOR_PREFIX, validate_group_args  # noqa: F401

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker
    from ..broker.channel import Consumer
    from .groups import StreamGroup

log = logging.getLogger("chanamq.streams")

# sentinel: the record lives in an evicted sealed segment whose blob is
# being (re)loaded from the store — the cursor resumes on load completion
_LOADING = object()

# sentinel: the offset existed but key compaction (chanamq_tpu/wal/)
# dropped its record from the sealed blob — readers skip to offset+1
_COMPACTED = object()

# cursor name backing basic.get reads (shares the committed-offset table
# with consumer cursors, so gets also survive restarts)
GET_CURSOR = "%get%"

VALID_QUEUE_TYPES = ("classic", "stream")


class StreamCursor:
    """One attached consumer's read position in the log."""

    __slots__ = ("name", "consumer", "next", "skip_ts_ms")

    def __init__(self, name: str, consumer: "Consumer", next_offset: int,
                 skip_ts_ms: Optional[int] = None) -> None:
        self.name = name
        self.consumer = consumer
        self.next = next_offset  # next offset to deliver
        # timestamp attach: records older than this are skipped without
        # delivery until the first match, then the filter clears
        self.skip_ts_ms = skip_ts_ms


def parse_offset_spec(value: Any) -> tuple[str, Optional[int]]:
    """Validate + normalize an ``x-stream-offset`` consume argument.

    Returns (kind, arg): ("next"|"first"|"last", None), ("offset", n) or
    ("timestamp", epoch_ms). AMQP 'T' fields and datetimes are
    timestamps; plain ints are absolute offsets (RabbitMQ's dialect).
    Raises ValueError on anything else.
    """
    if value is None:
        return ("next", None)
    if isinstance(value, Timestamp):
        return ("timestamp", int(value) * 1000)
    if isinstance(value, _dt.datetime):
        return ("timestamp", int(value.timestamp() * 1000))
    if isinstance(value, bool):
        raise ValueError("x-stream-offset must be first/last/next, an "
                         "offset (int) or a timestamp")
    if isinstance(value, int):
        if value < 0:
            raise ValueError("x-stream-offset offset must be >= 0")
        return ("offset", value)
    if isinstance(value, str):
        if value in ("first", "last", "next"):
            return (value, None)
        raise ValueError(
            f"unknown x-stream-offset {value!r} (first/last/next)")
    raise ValueError("x-stream-offset must be first/last/next, an offset "
                     "(int) or a timestamp")


class StreamQueue(Queue):
    """Append-only segmented log queue (``x-queue-type: stream``)."""

    is_stream = True
    # {record offset: Trace} for federated records that arrived with a
    # W3C context (ISSUE 20): the federation apply populates it, the
    # first materialization of that record consumes it, so mirror-side
    # deliver/settle spans join the producer's trace. Class-level None
    # keeps the untraced dispatch path at one falsy attribute check.
    fed_traces: "dict | None" = None

    def __init__(
        self,
        broker: "Broker",
        vhost: str,
        name: str,
        *,
        durable: bool = True,
        exclusive_owner: Optional[int] = None,
        auto_delete: bool = False,
        ttl_ms: Optional[int] = None,
        arguments: Optional[dict[str, Any]] = None,
    ) -> None:
        super().__init__(
            broker, vhost, name, durable=durable,
            exclusive_owner=exclusive_owner, auto_delete=auto_delete,
            ttl_ms=ttl_ms, arguments=arguments)
        args = self.arguments
        # segment sealing thresholds: per-queue override, else broker
        # defaults (chana.mq.stream.* config block)
        self.segment_bytes: int = int(
            args.get("x-stream-max-segment-size-bytes")
            or broker.stream_segment_bytes)
        self.segment_age_ms: int = int(broker.stream_segment_age_s * 1000)
        self.max_age_ms: Optional[int] = _parse_max_age_ms(
            args.get("x-max-age"))
        # retention byte cap reuses the x-max-length-bytes argument the
        # base class already parsed (self.max_length_bytes), but enforced
        # as whole-segment truncation, never record drops
        self.delivery_batch: int = broker.stream_delivery_batch
        self.cache_segments: int = broker.stream_cache_segments

        # the log: sealed segments (ascending base offset) + active tail
        self._segments: list[Segment] = []
        self._seg_bases: list[int] = []  # parallel bisect index
        self._active: list[StreamRecord] = []
        self._active_base = self.next_offset
        self._active_bytes = 0
        self._active_first_ts: Optional[int] = None
        # cursors: live attachments by consumer tag + committed offsets
        # (committed survives detach and, durably, restarts)
        self._cursors: dict[str, StreamCursor] = {}
        self.committed: dict[str, int] = {}
        # consumer groups (x-group): shared read position per group name,
        # plus member-tag -> group for settle-path delegation
        self._groups: dict[str, "StreamGroup"] = {}
        self._member_groups: dict[str, "StreamGroup"] = {}
        self._cursor_dirty: set[str] = set()
        self._cursor_flush_scheduled = False
        # segment blob loads in flight (base offsets)
        self._loading: set[int] = set()
        # in-session basic.get read position (None = derive from committed)
        self._get_pos: Optional[int] = None
        # self.ready_bytes (inherited gauge) tracks RETAINED bytes

    # -- introspection ----------------------------------------------------

    @property
    def first_offset(self) -> int:
        """Oldest retained offset (== next_offset when the log is empty)."""
        if self._segments:
            return self._segments[0].base_offset
        return self._active_base

    @property
    def message_count(self) -> int:  # type: ignore[override]
        return self.next_offset - self.first_offset

    @property
    def retained_bytes(self) -> int:
        return self.ready_bytes

    @property
    def segment_count(self) -> int:
        """Sealed segments + the active one when it holds records."""
        return len(self._segments) + (1 if self._active else 0)

    @property
    def cache_bytes(self) -> int:
        """Resident stream bytes: the active segment plus every sealed
        segment whose record blob is cached in RAM. Polled once per broker
        sweep tick as the flow accountant's ``stream_cache`` component —
        computed, not incrementally tracked, so it can never drift from
        the seal/evict/hydrate paths it observes."""
        total = self._active_bytes
        for seg in self._segments:
            if seg.records is not None:
                total += seg.size_bytes
        return total

    def cursor_lag(self, name: str) -> int:
        """Records between a cursor's committed offset and the log tail."""
        committed = self.committed.get(name)
        floor = self.first_offset - 1
        if committed is None or committed < floor:
            committed = floor
        return max(0, (self.next_offset - 1) - committed)

    # -- append (publish) --------------------------------------------------

    def push(self, message: Message, body_size: Optional[int] = None):  # type: ignore[override]
        """Append one record to the active segment. Never drops, never
        passivates, never dead-letters: retention is the only deleter."""
        self.last_used = now_ms()
        ts = self.last_used
        body = message.body if message.body is not None else b""
        rec = StreamRecord(self.next_offset, ts, message.exchange,
                           message.routing_key, message.header_payload(),
                           body)
        self.next_offset += 1
        if not self._active:
            self._active_first_ts = ts
        self._active.append(rec)
        size = rec.wire_size
        self._active_bytes += size
        self.ready_bytes += size
        metrics = self.broker.metrics
        metrics.stream_appends += 1
        metrics.stream_append_bytes += size
        # per-queue rate counter only: a stream append never contributes to
        # the broker depth gauge (records retire by retention, not consume)
        self.n_published += 1
        if (self._active_bytes >= self.segment_bytes
                or (self.segment_age_ms
                    and ts - self._active_first_ts >= self.segment_age_ms)):
            self._seal_active()
        # the stream owns its own copy of the bytes (the record): release
        # this queue's share of the routed Message immediately
        self.broker.unrefer(message)
        self.schedule_dispatch()
        return rec

    def _seal_active(self) -> None:
        if not self._active:
            return
        records = self._active
        seg = Segment(self._active_base, records[-1].offset,
                      records[0].ts_ms, records[-1].ts_ms,
                      self._active_bytes, records)
        self._segments.append(seg)
        self._seg_bases.append(seg.base_offset)
        if self.durable and not self.deleted:
            self.broker.store_bg(self.broker.store.insert_stream_segment(
                self.vhost, self.name, seg.base_offset, seg.last_offset,
                seg.first_ts_ms, seg.last_ts_ms, seg.size_bytes,
                pack_records(records)))
        self.broker.metrics.stream_segments_sealed += 1
        self._active = []
        self._active_base = self.next_offset
        self._active_bytes = 0
        self._active_first_ts = None
        self._enforce_retention()
        self._evict_cache()
        federation = self.broker.federation
        if federation is not None:
            # sealed segments are the federation shipping unit: wake any
            # link mirroring this stream
            federation.on_seal(self)

    def _enforce_retention(self, now: Optional[int] = None) -> None:
        """Truncate whole sealed segments from the head while over the
        x-max-length-bytes cap or past x-max-age. The active segment is
        never truncated."""
        dropped: list[int] = []
        cap = self.max_length_bytes
        age = self.max_age_ms
        if age is not None and now is None:
            now = now_ms()
        while self._segments:
            seg = self._segments[0]
            if not ((cap is not None and self.ready_bytes > cap)
                    or (age is not None and seg.last_ts_ms < now - age)):
                break
            self._segments.pop(0)
            self._seg_bases.pop(0)
            self.ready_bytes -= seg.size_bytes
            dropped.append(seg.base_offset)
        if dropped:
            self.broker.metrics.stream_segments_truncated += len(dropped)
            if self.durable and not self.deleted:
                self.broker.store_bg(self.broker.store.delete_stream_segments(
                    self.vhost, self.name, dropped))

    def _evict_cache(self, keep: Optional[Segment] = None) -> None:
        """Bound resident sealed records: only the newest cache_segments
        (plus the one just loaded for a replaying cursor) stay in RAM."""
        resident = [s for s in self._segments if s.records is not None]
        excess = len(resident) - self.cache_segments
        for seg in resident:
            if excess <= 0:
                break
            if seg is keep:
                continue
            seg.records = None
            excess -= 1

    # -- record lookup -----------------------------------------------------

    def _find_segment(self, offset: int) -> Optional[Segment]:
        import bisect
        idx = bisect.bisect_right(self._seg_bases, offset) - 1
        if idx < 0:
            return None
        seg = self._segments[idx]
        return seg if offset <= seg.last_offset else None

    def _record_at(self, offset: int):
        """StreamRecord at `offset`, None when past the tail, or _LOADING
        while an evicted segment's blob is fetched from the store."""
        if offset >= self._active_base:
            idx = offset - self._active_base
            return self._active[idx] if idx < len(self._active) else None
        seg = self._find_segment(offset)
        if seg is None:
            return None  # truncated gap: caller clamps to first_offset
        if seg.records is None:
            self._start_segment_load(seg)
            return _LOADING
        rec = seg.records[offset - seg.base_offset]
        return rec if rec is not None else _COMPACTED

    def _start_segment_load(self, seg: Segment) -> None:
        if seg.base_offset in self._loading or self.deleted:
            return
        self._loading.add(seg.base_offset)
        asyncio.get_event_loop().create_task(self._load_segment(seg))

    async def _load_segment(self, seg: Segment) -> None:
        failed = False
        try:
            blob = await self.broker.store.select_stream_segment(
                self.vhost, self.name, seg.base_offset)
            if blob is not None and seg.records is None:
                seg.records = unpack_records_indexed(
                    blob, seg.base_offset, seg.last_offset)
                self._evict_cache(keep=seg)
        except Exception:
            failed = True
            log.exception("stream %s: segment %d load failed; retrying",
                          self.name, seg.base_offset)
        finally:
            self._loading.discard(seg.base_offset)
        if failed:
            asyncio.get_event_loop().call_later(1.0, self.schedule_dispatch)
        else:
            self.schedule_dispatch()

    def _record_message(self, rec: StreamRecord,
                        decode_props: bool = False) -> Message:
        """Materialize a deliverable Message from a record. refer_count=1
        so the delivery settle paths unrefer it symmetrically with classic
        queues (a no-op here: never persisted, never accounted)."""
        if decode_props:
            _, _, props = BasicProperties.decode_header(rec.header_raw)
        else:
            props = _NO_PROPS
        msg = Message(0, props, rec.body, rec.exchange, rec.routing_key,
                      header_raw=rec.header_raw)
        msg.refer_count = 1
        if self.fed_traces:
            tr = self.fed_traces.pop(rec.offset, None)
            if tr is not None:
                msg.trace = tr
        return msg

    # -- dispatch ----------------------------------------------------------

    def schedule_dispatch(self) -> None:  # type: ignore[override]
        if self._dispatch_scheduled or self.deleted:
            return
        if not self._cursors and not self._groups:
            return
        self._dispatch_scheduled = True
        asyncio.get_event_loop().call_soon(self._dispatch)

    def _dispatch(self) -> None:  # type: ignore[override]
        """One coalesced pass: every cursor reads up to delivery_batch
        records through its consumer's prefetch credit. A cursor parked on
        an evicted segment kicks an async blob load and resumes on the
        next pass."""
        self._dispatch_scheduled = False
        if self.deleted:
            return
        more = False
        metrics = self.broker.metrics
        for cursor in list(self._cursors.values()):
            consumer = cursor.consumer
            delivered = 0
            while delivered < self.delivery_batch:
                if cursor.next < self.first_offset:
                    # fell behind retention: jump to the oldest retained
                    cursor.next = self.first_offset
                rec = self._record_at(cursor.next)
                if rec is None or rec is _LOADING:
                    break
                if rec is _COMPACTED:
                    # key compaction dropped this offset from the sealed
                    # blob; the cursor walks the hole without delivering
                    cursor.next += 1
                    continue
                if cursor.skip_ts_ms is not None:
                    if rec.ts_ms < cursor.skip_ts_ms:
                        cursor.next = rec.offset + 1
                        continue
                    cursor.skip_ts_ms = None
                if not consumer.can_take(len(rec.body)):
                    break
                qm = QueuedMessage(self._record_message(rec), rec.offset,
                                   None, body_size=len(rec.body))
                delivery = consumer.deliver(self, qm)
                metrics.stream_records_delivered += 1
                self.n_delivered += 1
                cursor.next = rec.offset + 1
                delivered += 1
                if delivery is None:  # no_ack: consumed + committed now
                    self._commit(cursor.name, rec.offset)
                    self.broker.unrefer(qm.message)
                else:
                    self.outstanding[(cursor.name, rec.offset)] = delivery
                    if self._counted:
                        self.broker.queue_unacked += 1
            if delivered >= self.delivery_batch:
                more = True  # budget exhausted, not credit: keep going
        for group in list(self._groups.values()):
            if group.dispatch(self.delivery_batch):
                more = True
        if more:
            self.schedule_dispatch()

    # -- cursor commit (ack) -----------------------------------------------

    def _commit(self, name: str, offset: int) -> None:
        current = self.committed.get(name)
        if current is not None and offset <= current:
            return
        self.committed[name] = offset
        self.broker.metrics.stream_cursor_commits += 1
        federation = self.broker.federation
        if federation is not None:
            # mirror the commit so a failed-over consumer group resumes
            # contiguously on the remote cluster (coalesced per link)
            federation.on_cursor_commit(self, name, offset)
        if self.durable:
            self._cursor_dirty.add(name)
            if not self._cursor_flush_scheduled:
                # one persisted write per cursor per loop tick, value
                # re-read at flush (same coalescing as the classic
                # lastConsumed watermark)
                self._cursor_flush_scheduled = True
                asyncio.get_event_loop().call_soon(self._flush_cursors)

    def _flush_cursors(self) -> None:
        self._cursor_flush_scheduled = False
        dirty, self._cursor_dirty = self._cursor_dirty, set()
        if self.deleted:
            return
        for name in dirty:
            offset = self.committed.get(name)
            if offset is not None:
                self.broker.store_bg(self.broker.store.update_stream_cursor(
                    self.vhost, self.name, name, offset))

    def note_outstanding(self, delivery: Delivery) -> None:  # type: ignore[override]
        # two cursors can hold the SAME offset unacked simultaneously, so
        # the key is (cursor, offset), never the bare offset
        self.outstanding[
            (delivery.consumer_tag or GET_CURSOR,
             delivery.queued.offset)] = delivery
        if self._counted:
            self.broker.queue_unacked += 1

    def ack(self, delivery: Delivery) -> None:  # type: ignore[override]
        name = delivery.consumer_tag or GET_CURSOR
        popped = self.outstanding.pop((name, delivery.queued.offset), None)
        if popped is not None and self._counted:
            self.broker.queue_unacked -= 1
        self.n_acked += 1
        group = self._member_groups.get(name)
        if group is not None:
            # group member: the shared floor commits, not a private cursor
            group.settle(delivery.queued.offset)
        else:
            self._commit(name, delivery.queued.offset)
        if trace.ACTIVE is not None:
            # a federated record's lifted trace (fed_traces) finishes at
            # the consumer's settle, same as a classic queue's ack path
            tr = delivery.queued.message.trace
            if tr is not None:
                trace.ACTIVE.on_settle(tr, self.broker.trace_node)
        self.broker.unrefer(delivery.queued.message)

    def drop(self, delivery: Delivery) -> None:  # type: ignore[override]
        # reject without requeue: the cursor moves past the record (the
        # data itself is immutable; only retention deletes)
        self.ack(delivery)

    def requeue(self, delivery: Delivery) -> None:  # type: ignore[override]
        """Nack-with-requeue / channel teardown: nothing re-enters a log —
        the record stays uncommitted, and a still-attached cursor rewinds
        to redeliver it."""
        name = delivery.consumer_tag or GET_CURSOR
        popped = self.outstanding.pop((name, delivery.queued.offset), None)
        if popped is not None and self._counted:
            self.broker.queue_unacked -= 1
        group = self._member_groups.get(name)
        if group is not None:
            group.requeue(name, delivery.queued.offset)
        else:
            cursor = self._cursors.get(name)
            if cursor is not None and delivery.queued.offset < cursor.next:
                cursor.next = delivery.queued.offset
        self.broker.unrefer(delivery.queued.message)
        self.schedule_dispatch()

    # -- get (polling read) ------------------------------------------------

    async def basic_get(self) -> Optional[QueuedMessage]:  # type: ignore[override]
        """Non-destructive single read from the shared get cursor; ack
        commits it like any consumer cursor."""
        self.last_used = now_ms()
        pos = self._get_pos
        if pos is None:
            committed = self.committed.get(GET_CURSOR)
            pos = self.first_offset if committed is None else committed + 1
        while True:
            if pos < self.first_offset:
                pos = self.first_offset
            rec = self._record_at(pos)
            if rec is _LOADING:
                seg = self._find_segment(pos)
                if seg is None:
                    return None
                blob = await self.broker.store.select_stream_segment(
                    self.vhost, self.name, seg.base_offset)
                if self.deleted or blob is None:
                    return None
                if seg.records is None:
                    seg.records = unpack_records_indexed(
                        blob, seg.base_offset, seg.last_offset)
                    self._evict_cache(keep=seg)
                continue  # re-read now that the segment is resident
            if rec is _COMPACTED:
                pos += 1  # compaction hole: step to the next offset
                continue
            break
        if rec is None:
            return None
        self._get_pos = pos + 1
        self.broker.metrics.stream_records_delivered += 1
        self.n_delivered += 1
        return QueuedMessage(self._record_message(rec, decode_props=True),
                             rec.offset, None, body_size=len(rec.body))

    # -- consumers (cursor attach / detach) ----------------------------------

    def add_consumer(self, consumer: "Consumer") -> None:  # type: ignore[override]
        group_name = (consumer.arguments or {}).get("x-group")
        if group_name:
            self._join_group(consumer, group_name)
            return
        kind, arg = parse_offset_spec(
            (consumer.arguments or {}).get("x-stream-offset"))
        skip_ts: Optional[int] = None
        if kind == "first":
            start = self.first_offset
        elif kind == "last":
            # the final retained record onward
            start = max(self.first_offset, self.next_offset - 1)
        elif kind == "offset":
            start = max(arg, self.first_offset)
        elif kind == "timestamp":
            start = self._offset_for_ts(arg)
            skip_ts = arg
        else:  # "next": new records only — unless this tag committed
            # before, then resume where it left off (server-tracked cursor)
            committed = self.committed.get(consumer.tag)
            start = (self.next_offset if committed is None
                     else max(committed + 1, self.first_offset))
        self._cursors[consumer.tag] = StreamCursor(
            consumer.tag, consumer, start, skip_ts)
        super().add_consumer(consumer)

    def _join_group(self, consumer: "Consumer", group_name: str) -> None:
        """x-group consume: attach to (or create) the named group instead
        of a private cursor. Validation (mode vocabulary, mode conflicts)
        already ran in connection._on_consume before ConsumeOk."""
        from .groups import StreamGroup

        group = self._groups.get(group_name)
        if group is None:
            mode = ((consumer.arguments or {}).get("x-group-type")
                    or "shared")
            group = StreamGroup(self, group_name, mode)
            # position: a previously committed group offset wins (the
            # group resumes across restarts / full member churn); else the
            # FOUNDING member's x-stream-offset seeds it
            committed = self.committed.get(group.cursor_name)
            if committed is not None:
                group.next = max(committed + 1, self.first_offset)
            else:
                kind, arg = parse_offset_spec(
                    (consumer.arguments or {}).get("x-stream-offset"))
                if kind == "first":
                    group.next = self.first_offset
                elif kind == "last":
                    group.next = max(self.first_offset, self.next_offset - 1)
                elif kind == "offset":
                    group.next = max(arg, self.first_offset)
                elif kind == "timestamp":
                    group.next = self._offset_for_ts(arg)
                    group.skip_ts_ms = arg
                else:  # "next"
                    group.next = self.next_offset
            self._groups[group_name] = group
            self.broker.metrics.stream_groups_created += 1
        group.add_member(consumer)
        self._member_groups[consumer.tag] = group
        super().add_consumer(consumer)

    def _offset_for_ts(self, ts_ms: int) -> int:
        """First offset whose record could be >= ts_ms, by segment
        metadata; the cursor's skip filter does the exact record match."""
        for seg in self._segments:
            if seg.last_ts_ms >= ts_ms:
                return seg.base_offset
        return self._active_base

    def remove_consumer(self, consumer: "Consumer") -> bool:  # type: ignore[override]
        group = self._member_groups.get(consumer.tag)
        if group is not None and group.members.get(consumer.tag) is consumer:
            group.remove_member(consumer.tag)
        cursor = self._cursors.get(consumer.tag)
        if cursor is not None and cursor.consumer is consumer:
            del self._cursors[consumer.tag]
        return super().remove_consumer(consumer)

    # -- maintenance (sweep / purge / shutdown / recovery) -------------------

    def _expire_head(self) -> None:  # type: ignore[override]
        """Per-sweep-tick hook: age-seal a quiet active segment and apply
        age retention (size retention runs inline on seal)."""
        now = now_ms()
        if (self._active and self.segment_age_ms
                and self._active_first_ts is not None
                and now - self._active_first_ts >= self.segment_age_ms):
            self._seal_active()
        elif self.max_age_ms is not None:
            self._enforce_retention(now)

    def purge(self) -> int:  # type: ignore[override]
        """queue.purge on a stream: truncate ALL sealed segments and the
        active one. Offsets keep counting; cursors clamp forward."""
        count = self.message_count
        dropped = self._seg_bases[:]
        self._segments.clear()
        self._seg_bases.clear()
        self._active = []
        self._active_base = self.next_offset
        self._active_bytes = 0
        self._active_first_ts = None
        self.ready_bytes = 0
        if dropped:
            self.broker.metrics.stream_segments_truncated += len(dropped)
            if self.durable and not self.deleted:
                self.broker.store_bg(self.broker.store.delete_stream_segments(
                    self.vhost, self.name, dropped))
        return count

    def flush_store_buffers(self) -> None:  # type: ignore[override]
        """Shutdown path: seal + spill the active segment and flush dirty
        cursor commits, so a clean restart retains every appended record."""
        if self._active:
            self._seal_active()
        if self._cursor_dirty:
            self._flush_cursors()

    def restore_segments(
        self, metas: list[tuple[int, int, int, int, int]]
    ) -> None:
        """Recovery: rebuild the sealed-segment index from store metadata
        (blobs stay on disk until a cursor reads into them)."""
        for base, last, first_ts, last_ts, size in metas:
            self._segments.append(
                Segment(base, last, first_ts, last_ts, size))
            self._seg_bases.append(base)
            self.ready_bytes += size
            if last >= self.next_offset:
                self.next_offset = last + 1
        self._active_base = self.next_offset
        self._enforce_retention()


_NO_PROPS = BasicProperties()


def _parse_max_age_ms(value: Any) -> Optional[int]:
    """x-max-age: a duration string ("7d", "12h", "30s", "500ms") or a
    number of seconds. Returns milliseconds, or None when unset.
    Raises ValueError on garbage (declare validation surfaces it)."""
    if value is None:
        return None
    if isinstance(value, bool):
        raise ValueError("x-max-age must be a duration")
    if isinstance(value, (int, float)):
        if value <= 0:
            raise ValueError("x-max-age must be positive")
        return int(value * 1000)
    if isinstance(value, str):
        from ..config import parse_duration_s
        seconds = parse_duration_s(value)
        if seconds is None or seconds <= 0:
            raise ValueError(f"bad x-max-age duration {value!r}")
        return int(seconds * 1000)
    raise ValueError("x-max-age must be a duration")
