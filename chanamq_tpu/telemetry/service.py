"""Telemetry service: per-entity sampling, probes, health, and alerts.

One asyncio task per broker (``broker.telemetry``), ticking every
``chana.mq.telemetry.interval``. Each tick it

- measures event-loop lag (sleep overshoot: how late the timer actually
  fired) and its own tick duration — a tick longer than the interval
  counts as *saturated*, the signal that sampling is falling behind;
- samples every local queue and connection into fixed-slot
  :class:`EntityRings` (rates from the per-entity monotonic counters the
  hot paths maintain; gauges read directly). Replica vhosts never appear
  in ``broker.vhosts`` so the walk only sees entities this node owns;
- evaluates the alert rules vectorized over the queue matrix plus the
  node probes (loop lag, replication lag, store errors) and records
  fire/resolve transitions into metrics counters, structured logs, and
  the trace runtime (alerts tag captured traces exactly like chaos
  faults do, via ``note_chaos_fire("alert:<rule>")``).

The sampler walk is O(local entities) *off* the message path; the
message path itself pays only the integer increments added in
broker/entities.py and broker/connection.py.

Cluster view: ``cluster_payload`` pulls every alive peer's
``local_payload`` over the control-plane RPC (``telemetry.pull``), so
/admin/timeseries, /admin/health?scope=cluster and /admin/alerts serve a
whole-cluster answer from any node.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Hashable, Optional

import numpy as np

from .. import events as event_bus
from .. import trace
from ..slo import SLISampler, SLOEngine
from .alerts import AlertEngine, AlertRule, default_rules
from .health import evaluate_health
from .store import CONN_FIELDS, QUEUE_FIELDS, EntityRings

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker

log = logging.getLogger("chanamq.telemetry")


class TelemetryService:
    """Per-entity sampler + probes + health + alert engine."""

    def __init__(
        self,
        broker: "Broker",
        *,
        interval_s: float = 1.0,
        ring_ticks: int = 120,
        max_queues: int = 512,
        max_connections: int = 256,
        top_k: int = 4,
        rules: Optional[list[AlertRule]] = None,
        alerts_enabled: bool = True,
        loop_lag_ready_ms: float = 1000.0,
        repl_lag_ready: int = 10000,
        store_error_window: int = 30,
        slo: Optional[SLOEngine] = None,
        federation_lag_records: int = 1000,
    ) -> None:
        self.broker = broker
        self.interval_s = interval_s
        self.top_k = top_k
        self.queues = EntityRings(max_queues, ring_ticks, QUEUE_FIELDS)
        self.conns = EntityRings(max_connections, ring_ticks, CONN_FIELDS)
        self.engine = AlertEngine(
            rules if rules is not None else default_rules())
        self.alerts_enabled = alerts_enabled
        self.federation_lag_records = federation_lag_records
        # SLO engine rides the same tick (None: feature off); the sampler
        # turns broker counters into per-tick (good, bad) SLI deltas
        self.slo: Optional[SLOEngine] = None
        self.slo_sampler: Optional[SLISampler] = None
        if slo is not None:
            self.set_slo(slo)

        # readiness thresholds (health.py reads these off the service)
        self.loop_lag_ready_ms = loop_lag_ready_ms
        self.repl_lag_ready = repl_lag_ready
        self.store_error_window = store_error_window

        # probe state (latest tick)
        self.tick = 0
        self.loop_lag_ms = 0.0
        self.loop_lag_max_ms = 0.0
        self.tick_us = 0.0
        self.store_errors_recent = 0
        # cached one-word health verdict for log stamping ("ready" /
        # "not-ready"); logjson reads this on every line, so it must be
        # an attribute lookup, never a full health evaluation
        self.health_state = "ready"

        # per-entity monotonic-counter snapshots from the previous tick
        self._q_prev: dict[Hashable, tuple[int, int, int]] = {}
        self._c_prev: dict[Hashable, tuple[int, int, int]] = {}
        # store-error totals per tick, oldest first (windowed delta)
        self._store_err_totals: list[int] = []
        self._task: Optional[asyncio.Task] = None
        self._last = 0.0

    def set_slo(self, engine: SLOEngine) -> None:
        """Install (or replace: POST /admin/slo/configure) the SLO engine.
        A replacement starts with fresh rings — budgets are a property of
        the spec set, so they reset with it."""
        self.slo = engine
        threshold = 250.0
        for spec in engine.specs:
            if spec.sli == "delivery-latency":
                threshold = spec.threshold_ms
                break
        self.slo_sampler = SLISampler(
            self.broker, threshold,
            federation_lag_records=self.federation_lag_records)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._task is not None:
            return
        self._last = time.monotonic()
        self._task = asyncio.get_event_loop().create_task(self._run())
        self._task.add_done_callback(self._on_run_done)
        log.info(
            "telemetry on: interval=%.3gs ring=%d ticks, "
            "%d queue + %d connection slots, %d alert rules%s",
            self.interval_s, self.queues.ticks, self.queues.slots,
            self.conns.slots, len(self.engine.rules),
            "" if self.alerts_enabled else " (alerts disabled)")

    async def stop(self) -> None:
        if self._task is None:
            return
        self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, Exception):
            pass
        self._task = None

    @staticmethod
    def _on_run_done(task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.error("telemetry sampler died: %s", exc, exc_info=exc)

    async def _run(self) -> None:
        while True:
            target = time.monotonic() + self.interval_s
            await asyncio.sleep(self.interval_s)
            now = time.monotonic()
            # sleep overshoot = how long the event loop kept the timer
            # waiting beyond its deadline: the loop-lag probe
            lag_ms = max(0.0, (now - target) * 1000.0)
            self.loop_lag_ms = lag_ms
            self.loop_lag_max_ms = max(self.loop_lag_max_ms, lag_ms)
            try:
                self.sample_tick(now - self._last)
            except Exception:
                log.exception("telemetry tick failed")
            self._last = now

    # -- one tick ----------------------------------------------------------

    def sample_tick(self, dt_s: float) -> None:
        """Sample all entities, refresh probes, evaluate alerts. Public so
        tests (and the soak) can drive deterministic ticks without timers."""
        t0 = time.perf_counter()
        dt = max(dt_s, 1e-6)
        broker = self.broker
        metrics = broker.metrics
        self.tick += 1

        self._sample_queues(dt)
        self._sample_connections(dt)
        self._refresh_store_errors()

        probes = self.node_probes()
        if self.alerts_enabled:
            self._evaluate_alerts(probes)

        health = evaluate_health(broker, self)
        self.health_state = "ready" if health["ready"] else "not-ready"

        if self.slo is not None and self.slo_sampler is not None:
            self._evaluate_slo(bool(health["ready"]))

        self.tick_us = (time.perf_counter() - t0) * 1e6
        metrics.telemetry_ticks += 1
        if self.tick_us > self.interval_s * 1e6:
            metrics.telemetry_saturated_ticks += 1
        metrics.telemetry_evicted_entities = (
            self.queues.evicted + self.conns.evicted)
        metrics.telemetry_dropped_entities = (
            self.queues.dropped + self.conns.dropped)

    def _sample_queues(self, dt: float) -> None:
        live: set = set()
        vec = np.zeros(len(QUEUE_FIELDS), dtype=np.float32)
        for vhost in self.broker.vhosts.values():
            for queue in vhost.queues.values():
                key = (vhost.name, queue.name)
                live.add(key)
                slot = self.queues.lease(key)
                if slot is None:
                    continue
                pub, dlv, ack = (queue.n_published, queue.n_delivered,
                                 queue.n_acked)
                p_pub, p_dlv, p_ack = self._q_prev.get(key, (pub, dlv, ack))
                vec[0] = (pub - p_pub) / dt
                vec[1] = (dlv - p_dlv) / dt
                vec[2] = (ack - p_ack) / dt
                vec[3] = len(queue.messages)
                vec[4] = len(queue.outstanding)
                vec[5] = len(queue.consumers)
                vec[6] = queue.ready_bytes
                self._q_prev[key] = (pub, dlv, ack)
                self.queues.push(slot, vec)
        self.queues.retire_absent(live)
        for key in [k for k in self._q_prev if k not in live]:
            del self._q_prev[key]

    def _sample_connections(self, dt: float) -> None:
        live: set = set()
        vec = np.zeros(len(CONN_FIELDS), dtype=np.float32)
        for conn in self.broker.connections:
            key = conn.id
            live.add(key)
            slot = self.conns.lease(key)
            if slot is None:
                continue
            pub, dlv, ack = (conn.published_msgs, conn.delivered_msgs,
                             conn.acked_msgs)
            p_pub, p_dlv, p_ack = self._c_prev.get(key, (pub, dlv, ack))
            unacked = 0
            credit = 0
            for ch in conn.channels.values():
                n = len(ch.unacked)
                unacked += n
                if ch.prefetch_count_consumer:
                    credit += max(0, ch.prefetch_count_consumer - n)
            vec[0] = (pub - p_pub) / dt
            vec[1] = (dlv - p_dlv) / dt
            vec[2] = (ack - p_ack) / dt
            vec[3] = len(conn.channels)
            vec[4] = unacked
            vec[5] = credit
            self._c_prev[key] = (pub, dlv, ack)
            self.conns.push(slot, vec)
        self.conns.retire_absent(live)
        for key in [k for k in self._c_prev if k not in live]:
            del self._c_prev[key]

    def _refresh_store_errors(self) -> None:
        total = int(getattr(self.broker.store, "error_count", 0))
        totals = self._store_err_totals
        totals.append(total)
        if len(totals) > self.store_error_window:
            del totals[: len(totals) - self.store_error_window]
        self.store_errors_recent = total - totals[0]

    def node_probes(self) -> dict[str, float]:
        broker = self.broker
        repl_lag = 0.0
        cluster = broker.cluster
        if cluster is not None and cluster.replication is not None:
            repl_lag = float(cluster.replication.total_lag())
        flow = broker.flow
        return {
            "loop_lag_ms": self.loop_lag_ms,
            "repl_lag_events": repl_lag,
            "store_errors": float(self.store_errors_recent),
            "memory_stage": float(flow.stage) if flow is not None else 0.0,
            # stage floor pinned by the predictive control plane; the
            # control-prearm-stuck rule watches for a floor that never
            # relaxes (forecast stuck pessimistic / relax path broken)
            "control_floor": float(flow.floor) if flow is not None else 0.0,
            # 1.0 while a graceful drain has blown its evacuation budget
            # (queues stuck pinned/failing) — the drain-stuck rule fires on
            # it so an operator knows the decommission needs a hand
            "drain_overdue": (
                cluster.lifecycle.drain_overdue()
                if cluster is not None else 0.0),
        }

    def _evaluate_alerts(self, probes: dict[str, float]) -> None:
        keys, latest = self.queues.latest_matrix()
        events = self.engine.evaluate(
            self.tick, keys, latest,
            lambda w: self.queues.delta_matrix(w)[1],
            self.broker.trace_node, probes)
        if not events:
            return
        self.engine.record(events)
        metrics = self.broker.metrics
        for ev in events:
            if ev["event"] == "fired":
                metrics.alerts_fired += 1
                log.warning(
                    "alert fired: %s on %s (%s=%.6g, threshold %.6g, "
                    "severity %s)", ev["rule"], ev["entity"], ev["metric"],
                    ev["value"], ev["threshold"], ev["severity"])
                # tag captured traces in the fire window, same machinery
                # chaos faults use — a slow trace overlapping an alert
                # carries the alert name in its tags
                if trace.ACTIVE is not None:
                    trace.ACTIVE.note_chaos_fire(f"alert:{ev['rule']}")
            else:
                metrics.alerts_resolved += 1
                log.info("alert resolved: %s on %s after %d ticks",
                         ev["rule"], ev["entity"], ev["ticks"])
        bus = event_bus.ACTIVE
        if bus is not None:
            for ev in events:
                verb = "fired" if ev["event"] == "fired" else "cleared"
                bus.emit(f"alert.{verb}.{ev['rule']}", dict(ev))

    def _evaluate_slo(self, ready: bool) -> None:
        """One SLO tick: sample SLIs, evaluate burn rates, surface burn /
        clear transitions (metrics counter, structured log, event bus)."""
        samples = self.slo_sampler.sample(ready)
        slo_events = self.slo.evaluate(self.tick, samples)
        if not slo_events:
            return
        metrics = self.broker.metrics
        bus = event_bus.ACTIVE
        for ev in slo_events:
            if ev["event"] == "burn":
                metrics.slo_violations_total += 1
                log.warning(
                    "slo burn-rate: %s/%s burning (short=%.3g long=%.3g "
                    "threshold=%.3g budget_remaining=%.4f)",
                    ev["slo"], ev["pair"], ev["burn_short"], ev["burn_long"],
                    ev["threshold"], ev["budget_remaining"])
                if bus is not None:
                    bus.emit(f"slo.burn-rate.{ev['slo']}", dict(ev))
            else:
                log.info("slo cleared: %s/%s after %d ticks",
                         ev["slo"], ev["pair"], ev["ticks"])
                if bus is not None:
                    bus.emit(f"slo.cleared.{ev['slo']}", dict(ev))

    # -- reads: metrics / admin / forecaster -------------------------------

    def gauges(self) -> dict:
        """Merged into Broker.metrics_snapshot (Prometheus + /admin/metrics)."""
        return {
            "telemetry_loop_lag_ms": round(self.loop_lag_ms, 3),
            "telemetry_loop_lag_max_ms": round(self.loop_lag_max_ms, 3),
            "telemetry_tick_us": round(self.tick_us, 1),
            "telemetry_queue_entities": len(self.queues),
            "telemetry_conn_entities": len(self.conns),
            "alerts_firing": len(self.engine.firing),
        }

    def health(self) -> dict:
        return evaluate_health(self.broker, self)

    def local_payload(self, window: int, top: int = 0) -> dict:
        """JSON-safe single-node snapshot: the telemetry.pull RPC body and
        the per-node building block of every /admin cluster view. top > 0
        limits queue series to the top-N by publish+deliver rate (full
        key list still included so drilldowns can 404 correctly)."""
        q_keys, latest = self.queues.latest_matrix()
        selected = q_keys
        if top and len(q_keys) > top:
            rate = latest[:, 0] + latest[:, 1]  # publish + deliver
            order = np.argsort(-rate, kind="stable")[:top]
            selected = [q_keys[i] for i in sorted(order)]
        queues = []
        for key in selected:
            series = self.queues.series(key, window)
            queues.append({
                "vhost": key[0], "name": key[1],
                "series": [] if series is None else series.tolist(),
            })
        connections = []
        for key in self.conns.keys():
            series = self.conns.series(key, window)
            connections.append({
                "id": key,
                "series": [] if series is None else series.tolist(),
            })
        return {
            "node": self.broker.trace_node,
            "tick": self.tick,
            "interval_s": self.interval_s,
            "fields": {"queue": list(QUEUE_FIELDS),
                       "connection": list(CONN_FIELDS)},
            "queues": queues,
            "queue_keys": [[k[0], k[1]] for k in q_keys],
            "connections": connections,
            "tenants": self.top_tenants(top or 0),
            "probes": self.node_probes(),
            "alerts": self.engine.snapshot(),
            "slo": self.slo.snapshot() if self.slo is not None else None,
            "health": self.health(),
            "stats": {"queues": self.queues.stats(),
                      "connections": self.conns.stats(),
                      "tick_us": round(self.tick_us, 1)},
        }

    async def cluster_payload(self, window: int, top: int = 0) -> dict:
        """Whole-cluster view: this node's payload plus every alive peer's,
        pulled over the control-plane RPC. Peer failures degrade to an
        error entry instead of failing the whole view."""
        me = self.broker.trace_node
        nodes: dict[str, dict] = {me: self.local_payload(window, top)}
        cluster = self.broker.cluster
        if cluster is not None and cluster.membership is not None:
            for peer in cluster.membership.alive_members():
                if peer == cluster.name:
                    continue
                try:
                    nodes[peer] = await cluster._call(
                        peer, "telemetry.pull",
                        {"window": window, "top": top}, timeout_s=2.0)
                except Exception as exc:
                    nodes[peer] = {"node": peer,
                                   "error": f"pull failed: {type(exc).__name__}"}
        return {"nodes": nodes, "origin": me}

    # -- forecaster feature tap --------------------------------------------

    def topk_features(self, k: int) -> np.ndarray:
        """2k extra features: (depth, publish_rate) for each of the top-k
        queues by publish+deliver rate, zero-padded, rank-ordered. NOTE:
        rank-ordered slots change meaning whenever the top-K set churns —
        the forecaster therefore samples through models.telemetry.TopKSlots
        (identity-pinned slots with explicit eviction/reset) instead; this
        rank-ordered view remains for ad-hoc "busiest right now" reads."""
        out = np.zeros(2 * k, dtype=np.float32)
        keys, latest = self.queues.latest_matrix()
        if not keys or k <= 0:
            return out
        rate = latest[:, 0] + latest[:, 1]
        order = np.argsort(-rate, kind="stable")[:k]
        for i, row in enumerate(order):
            out[2 * i] = latest[row, 3]      # depth
            out[2 * i + 1] = latest[row, 0]  # publish_rate
        return out

    def top_tenants(self, k: int) -> list[dict]:
        """Per-tenant rows for /admin/timeseries: live tenant snapshots
        ordered by published+delivered traffic (top-K when k > 0, all
        tenants otherwise). Empty when tenancy is off."""
        registry = getattr(self.broker, "tenancy", None)
        if registry is None:
            return []
        rows = [registry.tenants[name].snapshot()
                for name in sorted(registry.tenants)]
        rows.sort(key=lambda r: (-(r["published"] + r["delivered"]),
                                 r["name"]))
        return rows[:k] if k > 0 else rows

    def top_queues(self, k: int) -> list[dict]:
        """Top-k queues by publish+deliver rate with their latest vectors
        (the /admin/timeseries?top=K summary row)."""
        keys, latest = self.queues.latest_matrix()
        if not keys:
            return []
        rate = latest[:, 0] + latest[:, 1]
        order = np.argsort(-rate, kind="stable")[:k]
        return [
            {"vhost": keys[i][0], "name": keys[i][1],
             **{f: float(latest[i, j])
                for j, f in enumerate(QUEUE_FIELDS)}}
            for i in order
        ]
