"""Cluster interconnect fast path: the data plane.

The control plane (rpc.py) serializes every payload through the generic
AMQP field-table codec over ONE connection per peer — fine for queue
declares and membership gossip, ruinous for the per-message hot path
(BENCH_r05: the 2-node numbers ran at well under half of single-node
throughput). This module is the data plane the bench trajectory asked for,
in the spirit of RPCAcc's "strip generic serialization out of the RPC hot
path" and the Pulsar paper's broker-to-broker batching (PAPERS.md):

- **Binary zero-copy frames.** Message bodies and property headers travel
  as length-prefixed raw bytes. Encode never joins them into a frame (the
  writer takes a buffer list); decode slices them as memoryviews of the
  read buffer straight into ``Message.body``.
- **Adaptive micro-batching.** Pushes and ack settlements coalesce PER
  PEER across channels and connections inside a flush window
  (``chana.mq.cluster.flush-window-us``), cut short by byte/count caps or
  an explicit barrier demand — under load batches grow to the caps, under
  trickle the window bounds added latency.
- **Parallel streams.** ``chana.mq.cluster.streams`` connections per peer,
  each with its own bounded in-flight window; traffic stripes by queue so
  per-queue FIFO holds while one slow batch no longer head-of-line-blocks
  every other queue's deliveries.

Wire layout (shared head defined in rpc.py, kinds 4/5/6):

  push_many (request, method 1):
    u32 count | record*
    record: ss vhost | u8 nq | ss queue* | ss exchange | ss routing-key |
            u32 props-len | props | u32 body-len | body
  settle_many (request, method 2):
    u32 count | entry*
    entry: ss vhost | ss queue | u8 op (0=ack 1=drop 2=requeue) | ss tag |
           u32 credit | u32 n | u64 offset*
  deliver_many (event, method 3):
    ss vhost | ss queue | ss tag | u32 count | record*
    record: u64 offset | u8 flags (1=redelivered, 2=has-expiry) |
            u64 msg-id | [u64 expire-at-ms] | ss exchange | ss routing-key |
            u32 props-len | props | u32 body-len | body

(`ss` = u8 length-prefixed UTF-8 short string.)

All three payloads may carry an optional trace trailer AFTER the record
area (chanamq_tpu/trace/): decoders iterate exactly ``count`` records and
ignore trailing bytes, so peers without the trailer logic interoperate in
both directions. The trailer is tail-anchored (length + magic in the last
8 bytes) so a receiver lifts trace contexts before the lazy record
decoders run; see trace.encode_trailer/decode_trailer.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Iterator, Optional

from .. import chaos, profile, trace
from .rpc import (
    KIND_DEVENT,
    KIND_DREQUEST,
    KIND_DRESPONSE,
    FrameTooLarge,
    ReconnectBackoff,
    RpcError,
    RpcTimeout,
    _read_frame,
    as_transport,
    encode_data_frame,
)

log = logging.getLogger("chanamq.dataplane")


def _chaos_data_error(fault) -> RpcError:
    return RpcError(fault.code, fault.message)

METHOD_PUSH_MANY = 1
METHOD_SETTLE_MANY = 2
METHOD_DELIVER_MANY = 3

OP_ACK = 0
OP_DROP = 1
OP_REQUEUE = 2
OPS = ("ack", "drop", "requeue")
OP_IDS = {"ack": OP_ACK, "drop": OP_DROP, "requeue": OP_REQUEUE}

_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def _put_ss(buf: bytearray, text: str) -> None:
    data = text.encode("utf-8")
    if len(data) > 255:
        raise ValueError(f"short string too long: {len(data)}")
    buf.append(len(data))
    buf += data


class _Cursor:
    """Sequential decoder over one frame payload view. Bulk fields come
    back as sub-views (zero-copy); strings decode from their slice."""

    __slots__ = ("view", "pos")

    def __init__(self, view: memoryview) -> None:
        self.view = view
        self.pos = 0

    def u8(self) -> int:
        value = self.view[self.pos]
        self.pos += 1
        return value

    def u32(self) -> int:
        (value,) = _U32.unpack_from(self.view, self.pos)
        self.pos += 4
        return value

    def u64(self) -> int:
        (value,) = _U64.unpack_from(self.view, self.pos)
        self.pos += 8
        return value

    def ss(self) -> str:
        n = self.u8()
        text = str(self.view[self.pos:self.pos + n], "utf-8")
        self.pos += n
        return text

    def blob(self) -> memoryview:
        n = self.u32()
        view = self.view[self.pos:self.pos + n]
        if len(view) != n:
            raise RpcError("truncated", f"blob wanted {n}, got {len(view)}")
        self.pos += n
        return view


def encode_push_meta_head(
    vhost: str, queues: list[str], exchange: str, routing_key: str,
) -> bytes:
    """The route-constant prefix of one push record (vhost + queue names +
    exchange + routing key). Pure function of the route, so callers that
    publish the same route repeatedly cache it (the broker's cluster route
    cache) and skip the string encoding per message."""
    meta = bytearray()
    _put_ss(meta, vhost)
    meta.append(len(queues))
    for name in queues:
        _put_ss(meta, name)
    _put_ss(meta, exchange)
    _put_ss(meta, routing_key)
    return bytes(meta)


def encode_push_record(
    vhost: str, queues: list[str], exchange: str, routing_key: str,
    props_raw: bytes, body: bytes, head: Optional[bytes] = None,
) -> list:
    """One push as a buffer list [head, len, props, len, body]: the body
    (and props header) ride by reference — the publish frame's own bytes,
    never copied. head, when given, is a cached encode_push_meta_head."""
    if head is None:
        head = encode_push_meta_head(vhost, queues, exchange, routing_key)
    return [head, _U32.pack(len(props_raw)), props_raw,
            _U32.pack(len(body)), body]


def decode_push_many(view: memoryview) -> Iterator[tuple]:
    """Yields (vhost, queues, exchange, routing_key, props_view, body_view)
    with props/body as memoryview slices of the frame buffer."""
    cur = _Cursor(view)
    for _ in range(cur.u32()):
        vhost = cur.ss()
        queues = [cur.ss() for _ in range(cur.u8())]
        exchange = cur.ss()
        routing_key = cur.ss()
        props = cur.blob()
        body = cur.blob()
        yield vhost, queues, exchange, routing_key, props, body


def encode_settle_entry(
    vhost: str, queue: str, op: str, tag: str, credit: int,
    offsets: list[int],
) -> bytes:
    entry = bytearray()
    _put_ss(entry, vhost)
    _put_ss(entry, queue)
    entry.append(OP_IDS[op])
    _put_ss(entry, tag)
    entry += _U32.pack(credit)
    entry += _U32.pack(len(offsets))
    for offset in offsets:
        entry += _U64.pack(offset)
    return bytes(entry)


def decode_settle_many(view: memoryview) -> Iterator[tuple]:
    """Yields (vhost, queue, op, tag, credit, offsets)."""
    cur = _Cursor(view)
    for _ in range(cur.u32()):
        vhost = cur.ss()
        queue = cur.ss()
        op = OPS[cur.u8()]
        tag = cur.ss()
        credit = cur.u32()
        offsets = [cur.u64() for _ in range(cur.u32())]
        yield vhost, queue, op, tag, credit, offsets


def encode_deliver_head(vhost: str, queue: str, tag: str, count: int) -> bytes:
    head = bytearray()
    _put_ss(head, vhost)
    _put_ss(head, queue)
    _put_ss(head, tag)
    head += _U32.pack(count)
    return bytes(head)


# (exchange, routing_key) -> encoded short-string pair: deliveries off one
# queue repeat the same few routes, so the per-record string encode memoizes
_EXRK_MEMO: dict[tuple[str, str], bytes] = {}
_EXRK_MEMO_MAX = 1024


def encode_deliver_record(
    offset: int, redelivered: bool, msg_id: int, expire_at_ms: Optional[int],
    exchange: str, routing_key: str, props_raw: bytes, body: bytes,
) -> list:
    key = (exchange, routing_key)
    exrk = _EXRK_MEMO.get(key)
    if exrk is None:
        buf = bytearray()
        _put_ss(buf, exchange)
        _put_ss(buf, routing_key)
        exrk = bytes(buf)
        if len(_EXRK_MEMO) >= _EXRK_MEMO_MAX:
            _EXRK_MEMO.clear()
        _EXRK_MEMO[key] = exrk
    meta = bytearray(_U64.pack(offset))
    meta.append((1 if redelivered else 0) | (2 if expire_at_ms is not None else 0))
    meta += _U64.pack(msg_id)
    if expire_at_ms is not None:
        meta += _U64.pack(int(expire_at_ms))
    meta += exrk
    meta += _U32.pack(len(props_raw))
    meta += props_raw
    meta += _U32.pack(len(body))
    return [bytes(meta), body]


def decode_deliver_many(view: memoryview) -> tuple:
    """Returns (vhost, queue, tag, records-iterator); records yield
    (offset, redelivered, msg_id, expire_at_ms, exchange, routing_key,
    props_view, body_view)."""
    cur = _Cursor(view)
    vhost = cur.ss()
    queue = cur.ss()
    tag = cur.ss()
    count = cur.u32()

    def records() -> Iterator[tuple]:
        for _ in range(count):
            offset = cur.u64()
            flags = cur.u8()
            msg_id = cur.u64()
            expire_at_ms = cur.u64() if flags & 2 else None
            exchange = cur.ss()
            routing_key = cur.ss()
            props = cur.blob()
            body = cur.blob()
            yield (offset, bool(flags & 1), msg_id, expire_at_ms,
                   exchange, routing_key, props, body)

    return vhost, queue, tag, records()


# ---------------------------------------------------------------------------
# streams
# ---------------------------------------------------------------------------

class DataStream:
    """One data-plane connection to a peer with its own in-flight window.

    Requests pipeline up to ``inflight`` outstanding before the next send
    awaits a slot — a full window applies backpressure to that stream only;
    sibling streams (other queues) keep moving."""

    def __init__(
        self, host, port: int = 0, *, inflight: int = 32,
        timeout_s: float = 20.0, connect_timeout_s: float = 3.0,
        metrics=None,
    ) -> None:
        # host may be a Transport (UDS shard fast path) or a plain host
        # string with a port (the historical TCP signature)
        self.transport = as_transport(host, port)
        self.host = getattr(self.transport, "host", self.transport.label)
        self.port = getattr(self.transport, "port", 0)
        self.timeout_s = timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.metrics = metrics
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._waiters: dict[int, asyncio.Future] = {}
        self._next_corr = 1
        self._connect_lock = asyncio.Lock()
        self._backoff = ReconnectBackoff()
        self._window = asyncio.Semaphore(max(1, inflight))
        self.inflight = 0
        self.last_error: Optional[str] = None
        self.closed = False

    def backoff_state(self) -> dict:
        state = self._backoff.state()
        state["last_error"] = self.last_error
        return state

    async def _ensure_connected(self) -> asyncio.StreamWriter:
        if self._writer is not None and not self._writer.is_closing():
            return self._writer
        self._backoff.check()
        async with self._connect_lock:
            if self._writer is not None and not self._writer.is_closing():
                return self._writer
            self._backoff.check()
            try:
                if chaos.ACTIVE is not None:
                    fault = await chaos.ACTIVE.fire(
                        "data.connect", peer=self.transport.peer,
                        on_error=_chaos_data_error)
                    if fault is not None:
                        raise RpcError(fault.code, fault.message)
                reader, writer = await asyncio.wait_for(
                    self.transport.dial(), self.connect_timeout_s)
            except BaseException as exc:
                self._backoff.failed()
                self.last_error = repr(exc)
                raise
            self._backoff.succeeded()
            self._writer = writer
            self._reader_task = asyncio.get_event_loop().create_task(
                self._read_loop(reader, writer))
            return writer

    async def _read_loop(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                corr_id, kind, _method, payload = await _read_frame(reader)
                if chaos.ACTIVE is not None:
                    fault = chaos.ACTIVE.decide(
                        "data.read", peer=self.transport.peer)
                    if fault is not None:
                        if fault.kind == "latency":
                            await asyncio.sleep(fault.delay_s)
                        elif fault.kind == "drop":
                            continue  # response lost in flight
                        elif fault.kind in ("disconnect", "partition"):
                            break
                        else:  # error / corrupt: stream desync
                            raise FrameTooLarge(
                                f"chaos[{fault.rule}]: {fault.message}")
                if self.metrics is not None:
                    self.metrics.rpc_data_bytes_recv += len(payload) + 14
                if kind != KIND_DRESPONSE:
                    continue
                fut = self._waiters.pop(corr_id, None)
                if fut is None or fut.done():
                    continue
                if payload[0] == 0:
                    fut.set_result(payload[1:])
                else:
                    n = payload[1]
                    fut.set_exception(RpcError(
                        "remote", str(payload[2:2 + n], "utf-8", "replace")))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError) as exc:
            self.last_error = repr(exc)
        except FrameTooLarge as exc:
            log.warning("data stream %s desynced: %s; reconnecting",
                        self.transport.label, exc)
            self.last_error = repr(exc)
        finally:
            self._fail_waiters(
                RpcError("disconnected", self.transport.label))
            if self._writer is writer:
                self._writer = None
            try:
                writer.close()
            except Exception:
                pass

    def _fail_waiters(self, exc: Exception) -> None:
        for fut in self._waiters.values():
            if not fut.done():
                fut.set_exception(exc)
                # a cancelled request() may never await this waiter
                # (teardown): mark the exception retrieved
                fut.exception()
        self._waiters.clear()

    async def request(
        self, method_id: int, parts: list,
        timeout_s: Optional[float] = None,
    ) -> memoryview:
        """One pipelined request; blocks only when the in-flight window is
        full. Returns the response payload past the status byte."""
        await self._window.acquire()
        self.inflight += 1
        try:
            writer = await self._ensure_connected()
            if chaos.ACTIVE is not None:
                fault = await chaos.ACTIVE.fire(
                    "data.send", peer=self.transport.peer,
                    on_error=_chaos_data_error)
                if fault is not None:
                    if fault.kind == "drop":
                        # batch lost in flight: fail now, not after the
                        # full ask window
                        raise RpcTimeout(f"data:{method_id}")
                    writer.close()  # disconnect / corrupt
                    raise RpcError("disconnected", f"chaos[{fault.rule}]")
            corr_id = self._next_corr
            self._next_corr += 1
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._waiters[corr_id] = fut
            frame = encode_data_frame(corr_id, KIND_DREQUEST, method_id, parts)
            if self.metrics is not None:
                self.metrics.rpc_data_bytes_sent += sum(len(p) for p in frame)
            writer.writelines(frame)
            await writer.drain()
            try:
                result = await asyncio.wait_for(
                    fut, timeout_s or self.timeout_s)
            except asyncio.TimeoutError:
                self._waiters.pop(corr_id, None)
                raise RpcTimeout(f"data:{method_id}") from None
            self._backoff.note_clean()
            return result
        finally:
            self.inflight -= 1
            self._window.release()

    async def send_event(self, method_id: int, parts: list) -> None:
        writer = await self._ensure_connected()
        if chaos.ACTIVE is not None:
            fault = await chaos.ACTIVE.fire(
                "data.event", peer=self.transport.peer,
                on_error=_chaos_data_error)
            if fault is not None:
                return  # fire-and-forget: any transport fault = silent loss
        frame = encode_data_frame(0, KIND_DEVENT, method_id, parts)
        if self.metrics is not None:
            self.metrics.rpc_data_bytes_sent += sum(len(p) for p in frame)
        writer.writelines(frame)
        await writer.drain()

    async def close(self) -> None:
        self.closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
            self._writer = None
        self._fail_waiters(RpcError("closed", "stream closed"))


class PeerDataPlane:
    """All data-plane state toward one peer: N streams plus the per-stream
    push/settle accumulators the flush window drains.

    Push submissions return the SHARED future of the batch that will carry
    them — the origin's confirm barrier awaits exactly the batches covering
    its publishes while later batches keep filling (pipelined, per-stream
    windowed). Settles accumulate per (queue, op, tag) and ride the same
    flush; ``drain_settles`` fences them for control-plane ordering."""

    def __init__(
        self, host, port: int = 0, *, streams: int = 2,
        inflight_per_stream: int = 32, flush_window_us: int = 200,
        flush_max_bytes: int = 1 << 20, flush_max_count: int = 512,
        timeout_s: float = 20.0, metrics=None, node_tag: str = "",
    ) -> None:
        self.metrics = metrics
        # local node name for trace span attribution (cluster-push and
        # flush-wait happen on the submitting side)
        self.node_tag = node_tag
        self.transport = as_transport(host, port)
        # intra-node shard hop: peer is a sibling shard over a Unix socket
        self.intra_node = self.transport.kind == "uds"
        self.flush_window_s = max(0.0, flush_window_us / 1e6)
        self.flush_max_bytes = max(1, flush_max_bytes)
        self.flush_max_count = max(1, flush_max_count)
        self.streams = [
            DataStream(self.transport, inflight=inflight_per_stream,
                       timeout_s=timeout_s, metrics=metrics)
            for _ in range(max(1, streams))
        ]
        n = len(self.streams)
        # per-stream push accumulator: [parts, count, bytes, future]
        self._push: list[Optional[list]] = [None] * n
        # per-stream settle accumulator: ({(vhost, queue, op, tag):
        #   [offsets, credit]}, shared future, trace entries)
        self._settle: list[Optional[tuple]] = [None] * n
        self._settle_inflight: set[asyncio.Future] = set()
        self._timer: Optional[asyncio.TimerHandle] = None
        self.closed = False
        # pressure mode (flow ladder stage 3, set by ClusterNode): shrink
        # the effective flush caps so batches toward this peer stay small
        # — less buffered per hop, and the per-stream in-flight windows
        # throttle submitters sooner
        self.pressure = False

    def buffered_bytes(self) -> int:
        """Bytes sitting in the unflushed push accumulators toward this
        peer (the flow accountant's per-peer data-plane share)."""
        total = 0
        for acc in self._push:
            if acc is not None:
                total += acc[2]
        return total

    # -- stream striping ---------------------------------------------------

    def stream_for(self, vhost: str, queue: str, tag: str = "") -> int:
        """Sticky stream assignment: everything that must stay FIFO for one
        (queue, consumer) hashes to the same stream."""
        return hash((vhost, queue, tag)) % len(self.streams)

    # -- pushes ------------------------------------------------------------

    def submit_push(
        self, vhost: str, queues: list[str], exchange: str,
        routing_key: str, props_raw: bytes, body: bytes,
        head: Optional[bytes] = None, tr=None,
    ) -> asyncio.Future:
        """Buffer one push; returns the covering batch's completion future.
        The caller's barrier awaits it; caps may flush the batch before the
        window timer does. head: cached encode_push_meta_head, if any.
        tr: sampled trace riding this record — parked locally and shipped
        in the batch's trace trailer, keyed by record index."""
        idx = self.stream_for(vhost, queues[0] if queues else "")
        parts = encode_push_record(
            vhost, queues, exchange, routing_key, props_raw, body, head)
        nbytes = sum(len(p) for p in parts)
        acc = self._push[idx]
        if acc is None:
            self._push[idx] = acc = [
                [], 0, 0, asyncio.get_event_loop().create_future(), []]
            self._arm_timer()
        if tr is not None:
            acc[4].append((acc[1], tr))
            tr.pending_ns = time.perf_counter_ns()
            rt = trace.ACTIVE
            if rt is not None:
                rt.park(tr)
            if self.metrics is not None:
                self.metrics.trace_ctx_sent += 1
        acc[0].extend(parts)
        acc[1] += 1
        acc[2] += nbytes
        if self.metrics is not None:
            self.metrics.rpc_push_records += 1
            if self.intra_node:
                self.metrics.shard_cross_pushes += 1
        fut = acc[3]
        max_count, max_bytes = self.flush_max_count, self.flush_max_bytes
        if self.pressure:
            max_count = max(1, max_count // 8)
            max_bytes = max(1, max_bytes // 8)
        if acc[1] >= max_count or acc[2] >= max_bytes:
            if self.metrics is not None:
                if acc[1] >= max_count:
                    self.metrics.rpc_flush_count += 1
                else:
                    self.metrics.rpc_flush_bytes += 1
            self._flush_push(idx)
        return fut

    def _flush_push(self, idx: int) -> None:
        prof = profile.ACTIVE
        t_prof = time.thread_time_ns() if prof is not None else 0
        acc, self._push[idx] = self._push[idx], None
        if acc is None:
            return
        parts, count, _nbytes, fut, traces = acc
        payload = [_U32.pack(count), *parts]
        if traces:
            payload.append(trace.encode_trailer(traces))
        stream = self.streams[idx]
        if self.metrics is not None:
            self.metrics.rpc_push_batches += 1
        if prof is not None:
            # batch-granular: payload assembly cost for the whole push
            # batch (thread-CPU: the window joins the top-level busy sum);
            # ns/calls therefore reads as µs per pushed message
            prof.stage_ns[profile.CLUSTER_PUSH] += (
                time.thread_time_ns() - t_prof)
            prof.stage_calls[profile.CLUSTER_PUSH] += count

        async def _send() -> None:
            t_sent = time.perf_counter_ns() if traces else 0
            try:
                await stream.request(METHOD_PUSH_MANY, payload)
            except BaseException as exc:
                if not fut.done():
                    fut.set_exception(exc)
                return
            if traces:
                # batch-granular attribution: every trace in the batch
                # shares the queue wait (submit->send) and the round trip
                now = time.perf_counter_ns()
                node = self.node_tag
                intra = self.intra_node
                for _i, tr in traces:
                    tr.span(trace.CLUSTER_PUSH, tr.pending_ns, t_sent, node)
                    tr.span(trace.FLUSH_WAIT, t_sent, now, node)
                    if intra:
                        # same wall-clock interval seen as a shard hop:
                        # lets stitched traces separate intra-node cost
                        tr.span(trace.INTRA_SHARD_HOP,
                                tr.pending_ns, now, node)
            if not fut.done():
                fut.set_result(True)

        task = asyncio.get_event_loop().create_task(_send())
        # the batch future is always awaited via submit_push's return; keep
        # the send task from being GC'd mid-flight
        fut._dp_task = task  # type: ignore[attr-defined]

    # -- settles -----------------------------------------------------------

    def submit_settle(
        self, vhost: str, queue: str, op: str, offsets: list[int],
        tag: str, credit: int, tr=None,
    ) -> asyncio.Future:
        idx = self.stream_for(vhost, queue, tag)
        acc = self._settle[idx]
        if acc is None:
            self._settle[idx] = acc = (
                {}, asyncio.get_event_loop().create_future(), [])
            self._arm_timer()
        entries, fut, traces = acc
        if tr is not None:
            # settle entries coalesce, so the trailer keys by entry order
            # at flush time; idx here is a placeholder the flush rewrites
            traces.append((len(traces), tr))
            if self.metrics is not None:
                self.metrics.trace_ctx_sent += 1
        key = (vhost, queue, op, tag)
        entry = entries.get(key)
        if entry is None:
            entries[key] = entry = [[], 0]
        entry[0].extend(offsets)
        entry[1] += credit
        if self.metrics is not None:
            self.metrics.rpc_settle_records += len(offsets)
        return fut

    def _flush_settle(self, idx: int) -> None:
        acc, self._settle[idx] = self._settle[idx], None
        if acc is None:
            return
        entries, fut, traces = acc
        payload = [_U32.pack(len(entries))]
        for (vhost, queue, op, tag), (offsets, credit) in entries.items():
            payload.append(
                encode_settle_entry(vhost, queue, op, tag, credit, offsets))
        if traces:
            payload.append(trace.encode_trailer(traces))
        stream = self.streams[idx]
        if self.metrics is not None:
            self.metrics.rpc_settle_batches += 1
        self._settle_inflight.add(fut)
        fut.add_done_callback(self._settle_inflight.discard)

        async def _send() -> None:
            try:
                await stream.request(METHOD_SETTLE_MANY, payload)
            except BaseException as exc:
                log.warning("settle batch to %s failed: %r",
                            stream.transport.label, exc)
                if not fut.done():
                    # settles are best-effort like the old settle_bg (an
                    # unacked delivery requeues via failure detection), so
                    # the fence future resolves rather than raises
                    fut.set_result(False)
                return
            if not fut.done():
                fut.set_result(True)

        fut._dp_task = asyncio.get_event_loop().create_task(_send())  # type: ignore[attr-defined]

    async def drain_settles(self) -> None:
        """Flush buffered settles and await every in-flight settle batch:
        the control-plane ordering fence (an ack buffered before a cancel /
        delete / purge must be APPLIED on the owner before that RPC runs)."""
        for idx in range(len(self.streams)):
            if self._settle[idx] is not None:
                self._flush_settle(idx)
        if self._settle_inflight:
            await asyncio.gather(
                *list(self._settle_inflight), return_exceptions=True)

    # -- deliveries --------------------------------------------------------

    def send_deliver_many(
        self, vhost: str, queue: str, tag: str, records: list,
        count: int, traces=None,
    ) -> None:
        """Fire one deliver_many event (owner -> origin), striped so one
        consumer's deliveries stay ordered. records is a pre-encoded buffer
        list (see encode_deliver_record). traces: [(record_idx, Trace)]
        shipped as the trailing trace trailer."""
        idx = self.stream_for(vhost, queue, tag)
        payload = [encode_deliver_head(vhost, queue, tag, count), *records]
        if traces:
            payload.append(trace.encode_trailer(traces))
            if self.metrics is not None:
                self.metrics.trace_ctx_sent += len(traces)
        stream = self.streams[idx]
        if self.metrics is not None:
            self.metrics.rpc_deliver_records += count
            self.metrics.rpc_deliver_batches += 1

        async def _send() -> None:
            try:
                await stream.send_event(METHOD_DELIVER_MANY, payload)
            except (RpcError, OSError) as exc:
                # delivery loss is the design contract (unacked copies
                # requeue via failure detection; no_ack is at-most-once)
                log.debug("deliver_many to %s dropped: %r",
                          stream.transport.label, exc)

        asyncio.get_event_loop().create_task(_send())

    # -- flush window ------------------------------------------------------

    def _arm_timer(self) -> None:
        if self._timer is None and not self.closed:
            self._timer = asyncio.get_event_loop().call_later(
                self.flush_window_s, self._on_timer)

    def _on_timer(self) -> None:
        self._timer = None
        if self.metrics is not None and (
                any(a is not None for a in self._push)
                or any(a is not None for a in self._settle)):
            self.metrics.rpc_flush_window += 1
        self.flush_all()

    def flush_all(self, demand: bool = False) -> None:
        """Flush every stream's accumulators now. demand=True marks a
        barrier-initiated flush (confirm barrier, settle fence) in the
        counters."""
        if demand and self.metrics is not None and (
                any(a is not None for a in self._push)
                or any(a is not None for a in self._settle)):
            self.metrics.rpc_flush_demand += 1
        for idx in range(len(self.streams)):
            self._flush_push(idx)
            self._flush_settle(idx)

    # -- introspection / lifecycle ----------------------------------------

    def stats(self) -> dict:
        return {
            "transport": self.transport.kind,
            "streams": len(self.streams),
            "inflight": [s.inflight for s in self.streams],
            "backoff": [s.backoff_state() for s in self.streams],
            "buffered_push_records": sum(
                a[1] for a in self._push if a is not None),
            "buffered_push_bytes": sum(
                a[2] for a in self._push if a is not None),
            "buffered_settle_keys": sum(
                len(a[0]) for a in self._settle if a is not None),
            "settle_batches_inflight": len(self._settle_inflight),
        }

    async def close(self) -> None:
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.flush_all()
        for stream in self.streams:
            await stream.close()
