"""Tiered sealed-segment storage + key compaction for stream segments.

Generalizes the streams' sealed-blob RAM cache one level down: a cold
sealed stream segment's blob bytes are evicted from the SQLite row
(``blob=NULL``) into a side file under ``<wal dir>/tier/``, while the
segment index row stays queryable; a cursor replaying into an offloaded
segment rehydrates the blob from the tier file transparently
(WalStore.select_stream_segment).  Tier files carry a CRC32 trailer so
a short write or bit rot reads back as "absent" (the caller sees a
missing segment, never silent garbage).

Key compaction rewrites sealed segment blobs for stream queues declared
with ``x-stream-compact``: only the newest record per routing key
survives, Kafka-style.  Offsets are preserved — a compacted blob is
*sparse*, and the streams read path skips the holes — so committed
cursors remain valid across compaction.
"""

from __future__ import annotations

import os
import struct
from typing import TYPE_CHECKING
from urllib.parse import quote
from zlib import crc32

if TYPE_CHECKING:  # pragma: no cover - import cycle (streams -> broker)
    from ..streams.segment import StreamRecord

_U32 = struct.Struct("<I")


class StreamTier:
    """Side-file store for offloaded sealed stream-segment blobs."""

    def __init__(self, dir_path: str) -> None:
        self.dir = dir_path
        self.data_bytes = 0
        self._scanned = False

    def _queue_dir(self, vhost: str, queue: str) -> str:
        # percent-encode: vhost may contain "/" and the replica-NS marker
        return os.path.join(
            self.dir, quote(vhost, safe="") + "~" + quote(queue, safe=""))

    def _path(self, vhost: str, queue: str, base_offset: int) -> str:
        return os.path.join(self._queue_dir(vhost, queue),
                            f"{base_offset:020d}.seg")

    def scan(self) -> None:
        """Recount on-disk bytes (boot); cheap — tier trees are small."""
        total = 0
        for root, _dirs, files in os.walk(self.dir):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(root, name))
                except OSError:
                    pass
        self.data_bytes = total
        self._scanned = True

    def write(self, vhost: str, queue: str, base_offset: int,
              blob: bytes) -> None:
        """Durable offload: tmp + fsync + rename, CRC32 trailer. Runs on
        an executor thread (called via run_in_executor)."""
        qdir = self._queue_dir(vhost, queue)
        os.makedirs(qdir, exist_ok=True)
        path = self._path(vhost, queue, base_offset)
        tmp = path + ".tmp"
        data = blob + _U32.pack(crc32(blob))
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.data_bytes += len(data)

    def read(self, vhost: str, queue: str, base_offset: int):
        """Rehydrate a blob; None when absent or CRC-damaged."""
        try:
            with open(self._path(vhost, queue, base_offset), "rb") as f:
                data = f.read()
        except OSError:
            return None
        if len(data) < 4:
            return None
        blob, want = data[:-4], _U32.unpack(data[-4:])[0]
        return blob if crc32(blob) == want else None

    def has(self, vhost: str, queue: str, base_offset: int) -> bool:
        return os.path.exists(self._path(vhost, queue, base_offset))

    def forget(self, vhost: str, queue: str,
               base_offsets: "list[int]") -> None:
        for base in base_offsets:
            path = self._path(vhost, queue, base)
            try:
                self.data_bytes -= os.path.getsize(path)
                os.unlink(path)
            except OSError:
                pass

    def forget_queue(self, vhost: str, queue: str) -> None:
        qdir = self._queue_dir(vhost, queue)
        try:
            names = os.listdir(qdir)
        except OSError:
            return
        for name in names:
            path = os.path.join(qdir, name)
            try:
                self.data_bytes -= os.path.getsize(path)
                os.unlink(path)
            except OSError:
                pass
        try:
            os.rmdir(qdir)
        except OSError:
            pass


def compact_records(
    records: "list[StreamRecord]", seen_keys: "set[str]",
) -> "tuple[list[StreamRecord], int]":
    """One segment's compaction pass, newest-first against keys already
    seen in newer segments.  Returns (kept ascending, dropped count) and
    folds this segment's keys into seen_keys for the next (older) one."""
    kept: list[StreamRecord] = []
    dropped = 0
    for rec in reversed(records):
        if rec is None:
            continue  # already-sparse slot from a previous compaction
        if rec.routing_key in seen_keys:
            dropped += 1
        else:
            seen_keys.add(rec.routing_key)
            kept.append(rec)
    kept.reverse()
    return kept, dropped


def compacted_blob(kept: "list[StreamRecord]") -> "tuple[bytes, int]":
    from .engine import _stream_segment_mod  # lazy: import cycle

    blob = _stream_segment_mod().pack_records(kept)
    return blob, sum(r.wire_size for r in kept)
