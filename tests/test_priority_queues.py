"""Priority queues (x-max-priority declare argument).

EXCEEDS the reference (no priority support; the rebuild's plain queues are
strict FIFO like the reference's). RabbitMQ semantics: ready messages order
by (priority desc, publish order within a level), message priorities clamp
to the queue maximum, and — unique to this rebuild's durability design —
because consumption leaves offset order, settles delete their queue-log
rows individually instead of relying on the lastConsumed watermark, and
recovery re-sorts whatever rows remain by recovered priority.
"""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio


@pytest.fixture
async def server():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


def prio(n):
    return BasicProperties(priority=n, delivery_mode=2)


async def drain_all(ch, queue):
    out = []
    while True:
        m = await ch.basic_get(queue, no_ack=True)
        if m is None:
            return out
        out.append(m)


async def test_delivery_order_by_priority_then_fifo(client):
    ch = await client.channel()
    await ch.queue_declare("pq", arguments={"x-max-priority": 10})
    sends = [(b"a0", 0), (b"b5", 5), (b"c0", 0), (b"d9", 9), (b"e5", 5),
             (b"f9", 9), (b"g1", 1)]
    for body, p in sends:
        ch.basic_publish(body, routing_key="pq", properties=prio(p))
    ch2 = await client.channel()
    await ch2.queue_declare("pq", passive=True)  # ordering barrier
    got = [m.body for m in await drain_all(ch, "pq")]
    # priority desc, FIFO within each level
    assert got == [b"d9", b"f9", b"b5", b"e5", b"g1", b"a0", b"c0"]


async def test_no_priority_messages_default_to_zero(client):
    ch = await client.channel()
    await ch.queue_declare("pq0", arguments={"x-max-priority": 5})
    ch.basic_publish(b"plain", routing_key="pq0")  # no priority property
    ch.basic_publish(b"high", routing_key="pq0", properties=prio(3))
    ch2 = await client.channel()
    await ch2.queue_declare("pq0", passive=True)
    got = [m.body for m in await drain_all(ch, "pq0")]
    assert got == [b"high", b"plain"]


async def test_priority_clamps_to_queue_maximum(client):
    ch = await client.channel()
    await ch.queue_declare("pqc", arguments={"x-max-priority": 4})
    ch.basic_publish(b"over", routing_key="pqc", properties=prio(200))
    ch.basic_publish(b"atmax", routing_key="pqc", properties=prio(4))
    ch2 = await client.channel()
    await ch2.queue_declare("pqc", passive=True)
    got = [m.body for m in await drain_all(ch, "pqc")]
    # 200 clamps to 4: same level as "atmax", so FIFO between them
    assert got == [b"over", b"atmax"]


async def test_consumer_delivery_follows_priority(client):
    """Push dispatch (not just basic.get) serves the ready set in priority
    order when messages are queued ahead of the consumer."""
    ch = await client.channel()
    await ch.queue_declare("pqd", arguments={"x-max-priority": 9})
    for body, p in ((b"low1", 1), (b"high", 9), (b"low2", 1)):
        ch.basic_publish(body, routing_key="pqd", properties=prio(p))
    ch2 = await client.channel()
    await ch2.queue_declare("pqd", passive=True)
    got = []
    done = asyncio.get_event_loop().create_future()

    def cb(m):
        got.append(m.body)
        if len(got) == 3 and not done.done():
            done.set_result(None)

    await ch.basic_consume("pqd", cb, no_ack=True)
    await asyncio.wait_for(done, 5)
    assert got == [b"high", b"low1", b"low2"]


async def test_nack_requeue_returns_to_priority_position(client):
    ch = await client.channel()
    await ch.queue_declare("pqr", arguments={"x-max-priority": 9})
    for body, p in ((b"h1", 9), (b"h2", 9), (b"low", 1)):
        ch.basic_publish(body, routing_key="pqr", properties=prio(p))
    ch2 = await client.channel()
    await ch2.queue_declare("pqr", passive=True)
    m = await ch.basic_get("pqr")
    assert m.body == b"h1"
    ch.basic_nack(m.delivery_tag, requeue=True)
    got = [x.body for x in await drain_all(ch, "pqr")]
    # h1 returns AHEAD of h2 (same priority, earlier offset), above low
    assert got == [b"h1", b"h2", b"low"]
    assert got and got[0] == b"h1"


async def test_durable_priority_queue_recovery(tmp_path):
    """Restart ordering + exactness: consumed-and-acked entries stay gone
    (per-row settles — the watermark cannot prune here), survivors recover
    into priority order."""
    db = str(tmp_path / "prio.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db))
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("pqd2", durable=True,
                           arguments={"x-max-priority": 9})
    sends = [(b"p0a", 0), (b"p9a", 9), (b"p5a", 5), (b"p9b", 9),
             (b"p0b", 0), (b"p5b", 5)]
    for body, p in sends:
        ch.basic_publish(body, routing_key="pqd2", properties=prio(p))
    await ch.wait_unconfirmed_below(1)
    # consume the two highest (p9a, p9b) and ack them
    for expect in (b"p9a", b"p9b"):
        m = await ch.basic_get("pqd2")
        assert m.body == expect
        ch.basic_ack(m.delivery_tag)
    await asyncio.sleep(0.1)  # let the row deletes flush
    await c.close()
    await srv.stop()

    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("pqd2", durable=True, passive=True,
                                     arguments={"x-max-priority": 9})
        assert ok.message_count == 4
        got = [m.body for m in await drain_all(ch2, "pqd2")]
        assert got == [b"p5a", b"p5b", b"p0a", b"p0b"]
        await c2.close()
    finally:
        await srv2.stop()


async def test_unacked_priority_messages_recover(tmp_path):
    """Delivered-but-unacked entries come back after a restart, re-sorted
    into the priority order with the untouched backlog."""
    db = str(tmp_path / "priou.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db))
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("pqu", durable=True,
                           arguments={"x-max-priority": 9})
    for body, p in ((b"u9", 9), (b"u5", 5), (b"u0", 0)):
        ch.basic_publish(body, routing_key="pqu", properties=prio(p))
    await ch.wait_unconfirmed_below(1)
    m = await ch.basic_get("pqu")  # u9 delivered, NOT acked
    assert m.body == b"u9"
    await asyncio.sleep(0.1)
    await srv.stop()  # hard stop: unack outstanding

    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        got = [x.body for x in await drain_all(ch2, "pqu")]
        assert got == [b"u9", b"u5", b"u0"]
        await c2.close()
    finally:
        await srv2.stop()


async def test_priority_queue_validation(client):
    for args in ({"x-max-priority": 0}, {"x-max-priority": 256},
                 {"x-max-priority": "high"},
                 {"x-max-priority": 5, "x-queue-mode": "lazy"}):
        ch = await client.channel()
        with pytest.raises(ChannelClosedError) as exc_info:
            await ch.queue_declare("pq_bad", arguments=args)
        assert exc_info.value.reply_code == 406, args


async def test_priority_with_maxlen_and_dlx(client):
    """Cap + DLX still work on a priority queue: drop-head evicts the
    current front (highest priority first, documented) into the DLX."""
    ch = await client.channel()
    await ch.exchange_declare("pq_dlx", "fanout")
    await ch.queue_declare("pq_dead")
    await ch.queue_bind("pq_dead", "pq_dlx", "")
    await ch.queue_declare("pq_cap", arguments={
        "x-max-priority": 9, "x-max-length": 2,
        "x-dead-letter-exchange": "pq_dlx"})
    ch.basic_publish(b"m1", routing_key="pq_cap", properties=prio(9))
    ch.basic_publish(b"m2", routing_key="pq_cap", properties=prio(1))
    ch.basic_publish(b"m3", routing_key="pq_cap", properties=prio(5))
    ch2 = await client.channel()
    await ch2.queue_declare("pq_cap", passive=True)
    ok = await ch2.queue_declare("pq_cap", passive=True)
    assert ok.message_count == 2
    dead = None
    for _ in range(50):
        dead = await ch.basic_get("pq_dead", no_ack=True)
        if dead is not None:
            break
        await asyncio.sleep(0.02)
    assert dead is not None
    assert dead.properties.headers["x-death"][0]["reason"] == "maxlen"


async def test_ttl_expiry_on_priority_queue(client):
    ch = await client.channel()
    await ch.queue_declare("pq_ttl", arguments={
        "x-max-priority": 5, "x-message-ttl": 60})
    ch.basic_publish(b"gone", routing_key="pq_ttl", properties=prio(5))
    await asyncio.sleep(0.3)
    ok = await ch.queue_declare("pq_ttl", passive=True)
    assert ok.message_count == 0


async def test_priority_insert_above_tail_still_passivates(tmp_path):
    """A capped priority queue must keep passivating: a push that inserts
    ABOVE the tail (higher priority) is not mistaken for an overflow victim
    and still pages out beyond the resident watermark."""
    from chanamq_tpu.broker.broker import Broker
    from chanamq_tpu.broker.server import BrokerServer as _BS

    broker = Broker(store=SqliteStore(str(tmp_path / "pp.db")),
                    queue_max_resident=4)
    srv = _BS(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare("pp_q", durable=True, arguments={
            "x-max-priority": 9, "x-max-length": 100})
        body = b"z" * 512
        # low-priority backlog past the watermark, then high-priority
        # inserts that land mid-queue (above the low tail)
        for i in range(20):
            ch.basic_publish(body, routing_key="pp_q", properties=prio(0))
        for i in range(20):
            ch.basic_publish(body, routing_key="pp_q", properties=prio(9))
        await ch.wait_unconfirmed_below(1)
        queue = broker.vhosts["/"].queues["pp_q"]
        assert len(queue.messages) == 40
        resident = sum(1 for qm in queue.messages
                       if qm.message.body is not None)
        assert resident <= 6, resident  # watermark held, both priorities
        # drains fully with hydration, highest priority first
        got = [m.body for m in await drain_all(ch, "pp_q")]
        assert len(got) == 40 and all(b == body for b in got)
        await c.close()
    finally:
        await srv.stop()


async def test_purge_clears_buffered_row_deletes(tmp_path):
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(str(tmp_path / "pg.db")))
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("pg_q", durable=True,
                               arguments={"x-max-priority": 5})
        for i in range(10):
            ch.basic_publish(b"x", routing_key="pg_q", properties=prio(1))
        await asyncio.sleep(0.05)
        assert await ch.queue_purge("pg_q") == 10
        queue = srv.broker.vhosts["/"].queues["pg_q"]
        assert queue._row_del_buf == []
        await c.close()
    finally:
        await srv.stop()


async def test_recovery_loads_bodies_for_priority_head(tmp_path):
    """After a restart over a deep priority backlog where the high
    priorities were published LAST (highest offsets), the sorted head must
    come back with bodies resident — dispatch serves it without a store
    stall."""
    from chanamq_tpu.broker.broker import Broker
    from chanamq_tpu.broker.server import BrokerServer as _BS

    db = str(tmp_path / "ph.db")
    broker = Broker(store=SqliteStore(db), queue_max_resident=8)
    srv = _BS(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("ph_q", durable=True,
                           arguments={"x-max-priority": 9})
    for i in range(30):
        ch.basic_publish(b"low-%02d" % i, routing_key="ph_q",
                         properties=prio(0))
    for i in range(5):
        ch.basic_publish(b"high-%d" % i, routing_key="ph_q",
                         properties=prio(9))
    await ch.wait_unconfirmed_below(1)
    await c.close()
    await srv.stop()

    broker2 = Broker(store=SqliteStore(db), queue_max_resident=8)
    srv2 = _BS(broker=broker2, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv2.start()
    try:
        queue = broker2.vhosts["/"].queues["ph_q"]
        # the sorted head (the 5 highs + first lows) is resident
        head = list(queue.messages)[:8]
        assert all(qm.message.body is not None for qm in head), \
            [qm.message.body for qm in head]
        assert [qm.message.body for qm in head[:5]] == \
            [b"high-%d" % i for i in range(5)]
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        got = [m.body for m in await drain_all(ch2, "ph_q")]
        assert got[:5] == [b"high-%d" % i for i in range(5)]
        assert got[5:] == [b"low-%02d" % i for i in range(30)]
        await c2.close()
    finally:
        await srv2.stop()
