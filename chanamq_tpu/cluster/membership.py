"""Heartbeat membership with gossip piggyback.

The analogue of the reference's Akka cluster membership + phi-accrual failure
detection (chana-mq-base reference.conf:26-48): every node heartbeats every
alive peer on an interval; a peer silent past the failure timeout is marked
DOWN and leaves the ownership ring; heartbeats piggyback the sender's member
list (with incarnation counters) so views converge without a coordinator.
A downed node that comes back re-joins with a higher incarnation.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .rpc import RpcClient, RpcError, RpcServer, UdsTransport

log = logging.getLogger("chanamq.membership")

ALIVE = "alive"
DOWN = "down"

# lifecycle states (gossiped independently of liveness): a node is born
# JOINING, turns ACTIVE once it has exchanged a heartbeat with the cluster,
# enters DRAINING when an operator starts an evacuation, and ends LEFT when
# every held queue has moved off. DRAINING/LEFT nodes stay out of the
# placement ring so no new holdership lands on them.
JOINING = "joining"
ACTIVE = "active"
DRAINING = "draining"
LEFT = "left"


@dataclass
class Member:
    name: str  # "host:port" of the node's RPC endpoint
    incarnation: int = 0
    status: str = ALIVE
    last_seen: float = field(default_factory=time.monotonic)
    # lifecycle travels on its own monotonic version so it converges even
    # when the incarnation counter (liveness suspicion) never moves
    lifecycle: str = ACTIVE
    lifecycle_version: int = 0

    @property
    def host(self) -> str:
        return self.name.rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        return int(self.name.rsplit(":", 1)[1])


MembershipListener = Callable[[str, Member], None]  # (event, member)


class Membership:
    """Tracks the member set for one node."""

    def __init__(
        self,
        self_name: str,
        seeds: list[str],
        rpc_server: RpcServer,
        *,
        heartbeat_interval_s: float = 1.0,
        failure_timeout_s: float = 5.0,
        uds_map: Optional[dict[str, str]] = None,
    ) -> None:
        self.self_name = self_name
        self.seeds = [s for s in seeds if s != self_name]
        # member name -> Unix-socket path for sibling shards on this
        # machine: heartbeats and control RPC to them skip the TCP stack
        self.uds_map = dict(uds_map or {})
        self.heartbeat_interval_s = heartbeat_interval_s
        self.failure_timeout_s = failure_timeout_s
        self.incarnation = int(time.time() * 1000)
        lifecycle = JOINING if self.seeds else ACTIVE
        self.members: dict[str, Member] = {
            self_name: Member(self_name, self.incarnation,
                              lifecycle=lifecycle)
        }
        self.listeners: list[MembershipListener] = []
        self._clients: dict[str, RpcClient] = {}
        self._task: Optional[asyncio.Task] = None
        rpc_server.register("cluster.ping", self._on_ping)

    # -- view --------------------------------------------------------------

    def alive_members(self) -> list[str]:
        return sorted(
            name for name, m in self.members.items() if m.status == ALIVE
        )

    def is_alive(self, name: str) -> bool:
        member = self.members.get(name)
        return member is not None and member.status == ALIVE

    def lifecycle_of(self, name: str) -> str:
        member = self.members.get(name)
        return member.lifecycle if member is not None else ACTIVE

    def placement_members(self) -> list[str]:
        """Alive members eligible for NEW holdership: draining and left
        nodes keep serving what they still hold but take nothing new."""
        return [
            name for name in self.alive_members()
            if self.members[name].lifecycle not in (DRAINING, LEFT)
        ]

    def set_lifecycle(self, state: str) -> None:
        """Advance this node's own lifecycle state (version bump makes the
        transition win every gossip merge)."""
        me = self.members[self.self_name]
        if me.lifecycle == state:
            return
        me.lifecycle = state
        me.lifecycle_version += 1
        self._emit("lifecycle", me)

    def leader(self) -> str:
        """Deterministic leader: lowest alive name (the reference's
        cluster-singleton placement on the oldest node, approximated)."""
        alive = self.alive_members()
        return alive[0] if alive else self.self_name

    def client(self, name: str) -> RpcClient:
        client = self._clients.get(name)
        if client is None or client.closed:
            uds_path = self.uds_map.get(name)
            if uds_path is not None:
                client = RpcClient(UdsTransport(uds_path, peer=name))
            else:
                member = self.members.get(name)
                host, port = (member.host, member.port) if member else (
                    name.rsplit(":", 1)[0], int(name.rsplit(":", 1)[1]))
                client = RpcClient(host, port)
            self._clients[name] = client
        return client

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        for seed in self.seeds:
            self.members.setdefault(seed, Member(seed, 0))
        self._task = asyncio.get_event_loop().create_task(self._heartbeat_loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None
        for client in self._clients.values():
            await client.close()
        self._clients.clear()

    # -- gossip ------------------------------------------------------------

    def _view(self) -> dict:
        return {
            "from": self.self_name,
            "members": {
                name: {"incarnation": m.incarnation, "status": m.status,
                       "lc": m.lifecycle, "lv": m.lifecycle_version}
                for name, m in self.members.items()
            },
        }

    def _merge_lifecycle(self, member: Member, info: dict) -> None:
        lv = int(info.get("lv", 0))
        if lv > member.lifecycle_version:
            member.lifecycle_version = lv
            state = str(info.get("lc", ACTIVE))
            if state != member.lifecycle:
                member.lifecycle = state
                self._emit("lifecycle", member)

    def _merge(self, view: dict) -> None:
        for name, info in (view.get("members") or {}).items():
            incarnation = int(info.get("incarnation", 0))
            status = str(info.get("status", ALIVE))
            if name == self.self_name:
                # a peer gossiping a higher-versioned lifecycle for US is
                # stale third-party state (e.g. a drain from a previous
                # identity): refute it with a yet-higher version
                me = self.members[name]
                lv = int(info.get("lv", 0))
                if lv > me.lifecycle_version:
                    if str(info.get("lc", ACTIVE)) == me.lifecycle:
                        me.lifecycle_version = lv
                    else:
                        me.lifecycle_version = lv + 1
                        self._emit("lifecycle", me)
                continue
            member = self.members.get(name)
            if member is None:
                member = Member(name, incarnation, status)
                member.last_seen = time.monotonic() if status == ALIVE else 0.0
                self.members[name] = member
                self._merge_lifecycle(member, info)
                if status == ALIVE:
                    self._emit("up", member)
                continue
            self._merge_lifecycle(member, info)
            if incarnation > member.incarnation:
                member.incarnation = incarnation
                if status == ALIVE and member.status != ALIVE:
                    member.status = ALIVE
                    member.last_seen = time.monotonic()
                    self._emit("up", member)
                elif status == DOWN and member.status != DOWN:
                    member.status = DOWN
                    self._emit("down", member)

    async def _on_ping(self, payload: dict) -> dict:
        sender = str(payload.get("from", ""))
        if sender and sender != self.self_name:
            member = self.members.get(sender)
            if member is None:
                member = Member(sender)
                self.members[sender] = member
                self._emit("up", member)
            elif member.status != ALIVE:
                member.status = ALIVE
                member.incarnation = max(
                    member.incarnation,
                    int((payload.get("members") or {})
                        .get(sender, {}).get("incarnation", 0)))
                self._emit("up", member)
            member.last_seen = time.monotonic()
        self._merge(payload)
        return self._view()

    async def _ping_peer(self, name: str) -> None:
        member = self.members[name]
        try:
            reply = await self.client(name).call(
                "cluster.ping", self._view(),
                timeout_s=self.failure_timeout_s / 2)
            member.last_seen = time.monotonic()
            if member.status != ALIVE:
                member.status = ALIVE
                self._emit("up", member)
            self._merge(reply)
            me = self.members[self.self_name]
            if me.lifecycle == JOINING:
                # first confirmed contact with the cluster: we're in
                self.set_lifecycle(ACTIVE)
        except (RpcError, OSError, asyncio.TimeoutError):
            if (member.status == ALIVE
                    and time.monotonic() - member.last_seen > self.failure_timeout_s):
                member.status = DOWN
                member.incarnation += 1
                log.warning("%s: marking %s DOWN", self.self_name, name)
                self._emit("down", member)

    async def _heartbeat_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.heartbeat_interval_s)
                peers = [n for n in self.members if n != self.self_name]
                # concurrent pings: a dead peer's timeout must not delay
                # detection (or gossip) for the others
                if peers:
                    await asyncio.gather(
                        *(self._ping_peer(name) for name in peers),
                        return_exceptions=True)
        except asyncio.CancelledError:
            pass

    def _emit(self, event: str, member: Member) -> None:
        log.info("%s: member %s %s", self.self_name, member.name, event)
        for listener in self.listeners:
            try:
                listener(event, member)
            except Exception:
                log.exception("membership listener failed")
