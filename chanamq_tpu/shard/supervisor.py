"""Shard supervisor: spawn, watch, and restart per-core broker workers.

``python -m chanamq_tpu.broker.server`` with ``chana.mq.shard.count``
past 1 lands here instead of booting a broker: the supervisor writes
the merged config to ``<dir>/node-config.json``, then spawns one
worker process per shard with the per-shard pieces layered on top via
``CHANAMQ_*`` environment variables (the ordinary env-override path —
no second config mechanism):

* ``CHANAMQ_SHARD_INDEX / _COUNT / _DIR / _RESTARTS`` mark the worker;
* ``CHANAMQ_CLUSTER_PORT`` = base + index, ``CHANAMQ_CLUSTER_SEEDS`` =
  the sibling shard endpoints (+ any cross-machine seeds), heartbeat /
  failure timeouts come from the much tighter ``chana.mq.shard.*``
  knobs;
* ``CHANAMQ_ADMIN_PORT`` = admin base + index, ``CHANAMQ_STORE_PATH``
  gets a per-shard suffix so sqlite files never collide.

A worker that dies is respawned after ``chana.mq.shard.restart-backoff``
(up to ``chana.mq.shard.max-restarts`` times); the survivors' membership
marks it DOWN in the meantime, which re-hashes its queue ownership and
triggers replication promotion exactly like a remote node death.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal as signal_module
import sys
from typing import Optional

from .topology import ShardTopology

log = logging.getLogger("chanamq.shard.supervisor")


def child_env(
    config, topo: ShardTopology, index: int, restarts: int,
) -> dict[str, str]:
    """Environment for worker ``index`` (layered over the dumped file)."""
    env = dict(os.environ)
    external = config.list("chana.mq.cluster.seeds")
    env.update({
        "CHANAMQ_SHARD_INDEX": str(index),
        "CHANAMQ_SHARD_COUNT": str(topo.count),
        "CHANAMQ_SHARD_DIR": topo.dir,
        "CHANAMQ_SHARD_RESTARTS": str(restarts),
        "CHANAMQ_CLUSTER_ENABLED": "true",
        "CHANAMQ_CLUSTER_HOST": topo.host,
        "CHANAMQ_CLUSTER_PORT": str(topo.base_port + index),
        "CHANAMQ_CLUSTER_SEEDS": ",".join(topo.seeds_for(index, external)),
        "CHANAMQ_CLUSTER_HEARTBEAT_INTERVAL":
            config.str("chana.mq.shard.heartbeat-interval"),
        "CHANAMQ_CLUSTER_FAILURE_TIMEOUT":
            config.str("chana.mq.shard.failure-timeout"),
    })
    if config.bool("chana.mq.admin.enabled"):
        env["CHANAMQ_ADMIN_PORT"] = str(
            config.int("chana.mq.admin.port") + index)
    store_path = config.get("chana.mq.store.path")
    if store_path:
        env["CHANAMQ_STORE_PATH"] = f"{store_path}.shard{index}"
    return env


class ShardSupervisor:
    def __init__(self, config) -> None:
        self.config = config
        self.topo = ShardTopology.from_config(config)
        self.restart_backoff_s = config.duration_s(
            "chana.mq.shard.restart-backoff") or 0.5
        self.max_restarts = config.int("chana.mq.shard.max-restarts")
        self.reuse_port = config.bool("chana.mq.shard.reuse-port")
        self.restarts = [0] * self.topo.count
        self._procs: list[Optional[asyncio.subprocess.Process]] = (
            [None] * self.topo.count)
        self._stop = asyncio.Event()
        self._config_path = os.path.join(self.topo.dir, "node-config.json")
        self._acceptor = None

    # -- lifecycle ---------------------------------------------------------

    async def run(self) -> None:
        loop = asyncio.get_running_loop()

        def on_signal() -> None:
            if self._stop.is_set():
                os._exit(130)
            self._stop.set()

        for sig in (signal_module.SIGTERM, signal_module.SIGINT):
            try:
                loop.add_signal_handler(sig, on_signal)
            except (NotImplementedError, RuntimeError, ValueError):  # pragma: no cover
                pass

        dump = self.config.dump()
        # workers re-read this file; the resolved count/dir must land in
        # it so a worker never re-resolves 0 -> cpu_count differently
        dump["chana.mq.shard.count"] = self.topo.count
        dump["chana.mq.shard.dir"] = self.topo.dir
        with open(self._config_path, "w") as f:
            json.dump(dump, f)
        log.info("supervising %d shards (dir %s, %s)",
                 self.topo.count, self.topo.dir,
                 "SO_REUSEPORT" if self.reuse_port else "fd handoff")

        watchers = [
            asyncio.get_event_loop().create_task(self._supervise(i))
            for i in range(self.topo.count)
        ]
        try:
            if not self.reuse_port:
                # workers bind their feed sockets at boot; the acceptor
                # dials lazily per connection, so start order is soft
                from .handoff import HandoffAcceptor

                self._acceptor = HandoffAcceptor(
                    self.config.str("chana.mq.amqp.interface"),
                    self.config.int("chana.mq.amqp.port"),
                    [self.topo.handoff_path(i)
                     for i in range(self.topo.count)],
                    backlog=self.config.int("chana.mq.server.backlog") or 128)
                await self._acceptor.start()
            await self._stop.wait()
            log.info("shutdown signal; terminating %d shards",
                     self.topo.count)
        finally:
            self._stop.set()
            if self._acceptor is not None:
                await self._acceptor.stop()
            for proc in self._procs:
                if proc is not None and proc.returncode is None:
                    try:
                        proc.terminate()
                    except ProcessLookupError:
                        pass
            await asyncio.gather(*watchers, return_exceptions=True)

    # -- per-shard watcher -------------------------------------------------

    async def _spawn(self, index: int) -> asyncio.subprocess.Process:
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "chanamq_tpu.broker.server",
            "--config", self._config_path,
            env=child_env(
                self.config, self.topo, index, self.restarts[index]))
        log.info("shard %d up: pid %d (%s)", index, proc.pid,
                 self.topo.name(index))
        return proc

    async def _supervise(self, index: int) -> None:
        while not self._stop.is_set():
            try:
                proc = await self._spawn(index)
            except OSError as exc:
                log.error("shard %d spawn failed: %r", index, exc)
                return
            self._procs[index] = proc
            wait_proc = asyncio.get_event_loop().create_task(proc.wait())
            wait_stop = asyncio.get_event_loop().create_task(
                self._stop.wait())
            done, _pending = await asyncio.wait(
                {wait_proc, wait_stop},
                return_when=asyncio.FIRST_COMPLETED)
            if wait_proc not in done:
                # shutting down: the run() finally already sent SIGTERM
                wait_stop.cancel()
                await wait_proc
                return
            wait_stop.cancel()
            rc = wait_proc.result()
            self._procs[index] = None
            if self._stop.is_set():
                return
            self.restarts[index] += 1
            if self.restarts[index] > self.max_restarts:
                log.error("shard %d exited rc=%s; restart budget (%d) "
                          "exhausted — leaving it down", index, rc,
                          self.max_restarts)
                return
            log.warning("shard %d exited rc=%s; restart %d/%d in %.1fs",
                        index, rc, self.restarts[index], self.max_restarts,
                        self.restart_backoff_s)
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.restart_backoff_s)
                return  # stop arrived during the backoff
            except asyncio.TimeoutError:
                pass


async def run_supervisor(config) -> None:
    await ShardSupervisor(config).run()
