"""TensorRouter: batched publish routing over compiled binding tables.

The broker owns one TensorRouter (``chana.mq.router.enabled``). The
connection read loop, instead of routing each fused publish inline, defers
eligible messages into a per-connection buffer and flushes the WHOLE read
batch through ``Broker.flush_deferred_publishes`` -> ``route_pending``
here: one compiled-table lookup per exchange and one jitted kernel call
per exchange per flush, instead of one trie walk per message.

Consistency model (why deferral is safe):

- Deferral only happens between awaits of a single connection's read-batch
  processing, and every path that can publish, run a generic AMQP command,
  release confirms, or close the connection flushes the buffer FIRST
  (synchronously — the single-node publish path never awaits). The event
  loop is single-threaded, so no other connection's topology mutation can
  interleave with an unflushed buffer: the vhost/exchange state observed
  at ``defer_ok`` time is still live at flush time.
- ``Broker.invalidate_routes(vhost, exchange)`` drops exactly that
  exchange's compiled snapshot (or all of them for bulk mutations);
  recompilation is lazy, at the next flush that routes through it, under a
  monotonically increasing generation counter. Snapshots are immutable —
  a flush in progress keeps routing against the snapshot it resolved.
- Exchanges the compiler rejects (``Uncompilable``) and sub-``min-batch``
  kernel batches fall back to the exchange's Python matcher — the always
  available, always-correct oracle. ``chana.mq.router.verify`` cross-checks
  every kernel result against the oracle and prefers the oracle on any
  mismatch (counted in ``router_parity_mismatches``).
"""

from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, Optional

from . import compile as rcompile

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker

log = logging.getLogger("chanamq.router")

_DEFERRABLE_TYPES = ("direct", "fanout", "topic", "headers")

# resolved (vhost, name-set) -> [Queue] memo cap; cleared on invalidate
_QUEUE_CACHE_CAP = 8192


class TensorRouter:
    """Per-broker batch router over compiled binding tables."""

    def __init__(
        self,
        broker: "Broker",
        *,
        backend: str = "jax",
        min_batch: int = 16,
        max_wildcards: int = 512,
        max_queues: int = 4096,
        verify: bool = False,
    ) -> None:
        self.broker = broker
        self.backend = backend if backend in ("jax", "python") else "jax"
        self.min_batch = max(1, min_batch)
        self.max_wildcards = max_wildcards
        self.max_queues = max_queues
        self.verify = verify
        self.generation = 0
        # (vhost, exchange) -> CompiledExchange | str (uncompilable reason)
        self._compiled: dict = {}
        # (vhost, exchange) -> bool deferral decision memo
        self._defer: dict = {}
        # (vhost, frozenset-of-names) -> [Queue]
        self._queue_cache: dict = {}

    # -- invalidation ------------------------------------------------------

    def invalidate(self, vhost: Optional[str] = None,
                   exchange: Optional[str] = None) -> None:
        """Topology changed. With a (vhost, exchange) only that snapshot is
        dropped (dirty-exchange batching: untouched tables keep their
        compiled form); bulk mutations drop everything. Either way the
        deferral decisions and resolved-queue memo reset — they embed
        exchange structure and live Queue objects."""
        self._defer.clear()
        self._queue_cache.clear()
        if vhost is None or exchange is None:
            self._compiled.clear()
        else:
            self._compiled.pop((vhost, exchange), None)

    # -- deferral decision (publish hot path) ------------------------------

    def defer_ok(self, vhost_name: str, exchange_name: str) -> bool:
        """Whether a fused publish to this exchange may be deferred into
        the batch buffer. Memoized; any invalidate() clears the memo. The
        structural checks guarantee a later flush cannot raise: the
        exchange exists, is externally publishable, and carries none of
        the semantics (alternate exchange, e2e bindings) the batch path
        doesn't implement."""
        key = (vhost_name, exchange_name)
        ok = self._defer.get(key)
        if ok is None:
            ok = self._defer[key] = self._compute_defer(
                vhost_name, exchange_name)
        return ok

    def _compute_defer(self, vhost_name: str, exchange_name: str) -> bool:
        if exchange_name == "":
            return False  # default exchange: the dict hit is already optimal
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            return False
        exchange = vhost.exchanges.get(exchange_name)
        if exchange is None or exchange.internal:
            return False
        if exchange.ex_matcher is not None or exchange.alternate is not None:
            return False
        return exchange.type in _DEFERRABLE_TYPES

    # -- batch routing -----------------------------------------------------

    def _get_compiled(self, vhost, vhost_name: str, exchange_name: str):
        key = (vhost_name, exchange_name)
        comp = self._compiled.get(key)
        if comp is None:
            exchange = vhost.exchanges[exchange_name]
            self.generation += 1
            metrics = self.broker.metrics
            metrics.router_generation = self.generation
            try:
                comp = rcompile.compile_exchange(
                    exchange.type, exchange.matcher.bindings(),
                    generation=self.generation,
                    max_wildcards=self.max_wildcards,
                    max_queues=self.max_queues)
                metrics.router_compiles += 1
            except rcompile.Uncompilable as exc:
                comp = exc.reason
                log.debug("exchange %s/%s not tensorizable: %s",
                          vhost_name, exchange_name, exc.reason)
            self._compiled[key] = comp
        return None if isinstance(comp, str) else comp

    def _queues(self, vhost_name: str, vhost, names) -> list:
        """Resolve a routed name-set to live Queue objects, memoized per
        distinct set (fan-out traffic repeats a handful of sets)."""
        cache = self._queue_cache
        key = (vhost_name, names)
        queues = cache.get(key)
        if queues is None:
            vq = vhost.queues
            queues = [vq[n] for n in names if n in vq]
            if len(cache) >= _QUEUE_CACHE_CAP:
                cache.clear()
            cache[key] = queues
        return queues

    def route_pending(self, vhost_name: str, entries: list):
        """Route one deferred flush. ``entries`` rows are
        ``(exchange, routing_key, props, body, header_raw, exrk_raw,
        confirmed)``; returns ``(queues_per_entry, t0_ns, t1_ns)`` with the
        batch routing window for ROUTE span stamping."""
        t0 = time.perf_counter_ns()
        metrics = self.broker.metrics
        vhost = self.broker.vhosts[vhost_name]
        out: list = [None] * len(entries)
        # group by exchange: one compiled snapshot + one kernel call each
        groups: dict[str, list[int]] = {}
        for idx, entry in enumerate(entries):
            groups.setdefault(entry[0], []).append(idx)
        for exchange_name, idxs in groups.items():
            compiled = self._get_compiled(vhost, vhost_name, exchange_name)
            use_kernel = compiled is not None and (
                compiled.kernel_rows == 0 or len(idxs) >= self.min_batch)
            if not use_kernel:
                # Python matcher fallback: uncompilable table, or a batch
                # too small to amortize the kernel dispatch
                metrics.router_fallback_msgs += len(idxs)
                matcher = vhost.exchanges[exchange_name].matcher
                for idx in idxs:
                    entry = entries[idx]
                    names = frozenset(
                        matcher.route(entry[1], entry[2].headers))
                    out[idx] = self._queues(vhost_name, vhost, names)
                continue
            items = [(entries[i][1], entries[i][2].headers) for i in idxs]
            name_sets = rcompile.route_batch(compiled, items, self.backend)
            if self.verify:
                matcher = vhost.exchanges[exchange_name].matcher
                for pos, (key, headers) in enumerate(items):
                    oracle = matcher.route(key, headers)
                    if set(name_sets[pos]) != oracle:
                        metrics.router_parity_mismatches += 1
                        log.error(
                            "router parity mismatch on %s/%s key=%r: "
                            "kernel=%r oracle=%r", vhost_name, exchange_name,
                            key, sorted(name_sets[pos]), sorted(oracle))
                        name_sets[pos] = frozenset(oracle)
            metrics.router_batches += 1
            metrics.router_batch_msgs += len(idxs)
            metrics.router_batch_size.observe_us(len(idxs))
            for idx, names in zip(idxs, name_sets):
                out[idx] = self._queues(vhost_name, vhost, names)
        return out, t0, time.perf_counter_ns()
