"""AMQP frame model and incremental frame parser.

Capability parity with the reference's Frame model and streaming parser
(chana-mq-base .../model/Frame.scala:38-216,
 .../engine/FrameParser.scala:67-158): a frame is
type(1) channel(2) size(4) payload(size) end(0xCE); the parser is an
incremental push parser that accepts arbitrary byte chunks and yields complete
frames, enforcing the negotiated frame-max and yielding protocol errors
instead of raising mid-stream.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator

from .constants import (
    FRAME_END,
    FRAME_HEADER_SIZE,
    FrameType,
    ErrorCode,
)

_HEADER_STRUCT = struct.Struct(">BHI")


@dataclass(frozen=True, slots=True)
class Frame:
    type: int
    channel: int
    payload: bytes

    def to_bytes(self) -> bytes:
        # join, not +: payload may be a memoryview (cluster data-plane
        # bodies are zero-copy slices of the peer's read buffer)
        return b"".join((
            _HEADER_STRUCT.pack(self.type, self.channel, len(self.payload)),
            self.payload,
            b"\xce",
        ))

    @staticmethod
    def method(channel: int, payload: bytes) -> "Frame":
        return Frame(FrameType.METHOD, channel, payload)

    @staticmethod
    def header(channel: int, payload: bytes) -> "Frame":
        return Frame(FrameType.HEADER, channel, payload)

    @staticmethod
    def body(channel: int, payload: bytes) -> "Frame":
        return Frame(FrameType.BODY, channel, payload)


HEARTBEAT_FRAME = Frame(FrameType.HEARTBEAT, 0, b"")
HEARTBEAT_BYTES = HEARTBEAT_FRAME.to_bytes()


@dataclass(frozen=True, slots=True)
class FrameError:
    """A protocol-level framing error to be reported via Connection.Close."""

    code: ErrorCode
    message: str


class FrameParser:
    """Incremental frame parser.

    Feed byte chunks with :meth:`feed`; it yields `Frame` or `FrameError`
    items. After a `FrameError` the parser stops consuming (the connection is
    expected to close).
    """

    __slots__ = ("frame_max", "_buf", "_dead")

    def __init__(self, frame_max: int = 0) -> None:
        # frame_max == 0 means "not yet negotiated": accept any size.
        self.frame_max = frame_max
        self._buf = bytearray()
        self._dead = False

    def feed(self, data: bytes) -> Iterator[Frame | FrameError]:
        if self._dead:
            return
        buf = self._buf
        buf += data
        offset = 0
        n = len(buf)
        while n - offset >= FRAME_HEADER_SIZE:
            ftype, channel, size = _HEADER_STRUCT.unpack_from(buf, offset)
            # Validate the type from the header alone: a corrupt stream would
            # otherwise make us buffer up to a bogus 4-byte size field.
            if ftype not in (
                FrameType.METHOD,
                FrameType.HEADER,
                FrameType.BODY,
                FrameType.HEARTBEAT,
            ):
                self._dead = True
                yield FrameError(ErrorCode.FRAME_ERROR, f"unknown frame type {ftype}")
                return
            if self.frame_max and size + 8 > self.frame_max:
                self._dead = True
                yield FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"frame size {size} exceeds negotiated frame-max {self.frame_max}",
                )
                return
            end = offset + FRAME_HEADER_SIZE + size
            if n < end + 1:
                break
            if buf[end] != FRAME_END:
                self._dead = True
                yield FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"missing frame-end octet (got 0x{buf[end]:02x})",
                )
                return
            yield Frame(ftype, channel, bytes(buf[offset + FRAME_HEADER_SIZE : end]))
            offset = end + 1
        if offset:
            del buf[:offset]
