"""Server-side channel state: consumers, delivery tags, prefetch, confirms.

Capability parity with the reference's AMQChannel
(chana-mq-base .../model/AMQChannel.scala:16-182): per-channel mode
(normal/transaction/confirm), consumer registry with round-robin fairness,
monotonically increasing delivery tags, unacked maps, prefetch count/size
with global-vs-per-consumer accounting, confirm sequence counter — plus the
delivery rendering that the reference's FrameStage did inline
(FrameStage.scala:411-443).
"""

from __future__ import annotations

import enum
import struct
import time
from typing import TYPE_CHECKING, Any, Optional

from .. import events, trace
from ..amqp.command import AMQCommand
from ..amqp.constants import FRAME_OVERHEAD
from ..amqp.methods import Basic
from .entities import Delivery, Queue, QueuedMessage

if TYPE_CHECKING:  # pragma: no cover
    from .connection import AMQPConnection

_FRAME_HDR = struct.Struct(">BHI").pack


class ChannelMode(enum.Enum):
    NORMAL = "normal"
    CONFIRM = "confirm"
    TX = "tx"


class Consumer:
    """One basic.consume subscription."""

    __slots__ = (
        "tag", "channel", "queue", "no_ack", "exclusive", "arguments",
        "priority", "unacked_count", "unacked_size", "buffered_bytes",
        "slow", "_deliver_prefix",
    )

    def __init__(
        self,
        tag: str,
        channel: "ServerChannel",
        queue: Queue,
        no_ack: bool,
        exclusive: bool,
        arguments: Optional[dict[str, Any]] = None,
    ) -> None:
        self.tag = tag
        self.channel = channel
        self.queue = queue
        self.no_ack = no_ack
        self.exclusive = exclusive
        self.arguments = arguments or {}
        # consumer priority (RabbitMQ x-priority consume argument, default
        # 0; higher is served first while it has prefetch budget — an
        # extension the reference lacks)
        self.priority = int(self.arguments.get("x-priority") or 0)
        self.unacked_count = 0
        self.unacked_size = 0
        # bounded delivery buffer (chana.mq.flow.consumer-buffer): body
        # bytes rendered to the connection's output buffer since it last
        # fully drained to the kernel; `slow` marks a consumer currently
        # over the bound (detected once per episode, see can_take)
        self.buffered_bytes = 0
        self.slow = False
        # precomputed basic.deliver method-payload prefix:
        # class 60, method 60, shortstr consumer-tag
        tag_b = tag.encode("utf-8")
        self._deliver_prefix = b"\x00\x3c\x00\x3c" + bytes((len(tag_b),)) + tag_b

    def deliver(self, queue: Queue, qm: QueuedMessage) -> Optional[Delivery]:
        """Dispatch hook: render to this consumer's channel. The cluster
        layer's RemoteConsumer overrides this to ship over RPC instead."""
        return self.channel.deliver(self, queue, qm)

    def detach(self) -> None:
        """Called when the queue is deleted under this consumer: deregister
        and notify the client with a server-sent Basic.Cancel if it asked
        for consumer_cancel_notify."""
        self.channel.consumers.pop(self.tag, None)
        self.channel.connection.notify_consumer_cancel(self.channel, self.tag)

    def can_take(self, next_size: int) -> bool:
        """Unified consumer-credit admission (reference:
        FrameStage.scala:387-392 + QueueEntity.scala:342-359): every
        delivery passes the same ordered budget checks — channel flow,
        connection write saturation, the per-consumer bounded delivery
        buffer (slow-consumer detection), then the basic.qos prefetch
        count/size budgets (per-consumer and channel-global, with
        RabbitMQ's let-one-oversized-through-when-empty size semantics).
        no_ack consumers skip only the prefetch budgets — the buffer bound
        still applies (they are exactly the consumers that can otherwise
        buffer without limit)."""
        ch = self.channel
        if not ch.flow_active or ch.closed:
            return False
        if ch.connection.write_saturated:
            return False
        limit = ch.connection.broker.flow_consumer_buffer
        if limit and self.buffered_bytes + next_size > limit:
            if self.buffered_bytes > 0:
                if not self.slow:
                    # one detection per episode; cleared when the
                    # connection's output buffer drains to the kernel
                    self.slow = True
                    ch.connection.broker.metrics.flow_slow_consumers += 1
                return False
        if self.no_ack:
            return True
        if ch.prefetch_count_consumer and self.unacked_count >= ch.prefetch_count_consumer:
            return False
        if ch.prefetch_size_consumer and self.unacked_size + next_size > ch.prefetch_size_consumer:
            if self.unacked_count > 0:
                return False
        if ch.prefetch_count_global and ch.total_unacked_count() >= ch.prefetch_count_global:
            return False
        if ch.prefetch_size_global and ch.total_unacked_size() + next_size > ch.prefetch_size_global:
            if ch.total_unacked_count() > 0:
                return False
        return True


class ServerChannel:
    """Per-channel broker state on one connection."""

    def __init__(self, connection: "AMQPConnection", channel_id: int) -> None:
        self.connection = connection
        self.id = channel_id
        self.mode = ChannelMode.NORMAL
        self.flow_active = True
        self.closed = False

        self.consumers: dict[str, Consumer] = {}
        self._delivery_tag = 0
        self.unacked: dict[int, Delivery] = {}  # delivery tag -> delivery

        # qos: global_=False applies to consumers started afterwards
        # (per-consumer budget); global_=True is shared across the channel.
        self.prefetch_count_consumer = 0
        self.prefetch_size_consumer = 0
        self.prefetch_count_global = 0
        self.prefetch_size_global = 0

        # confirm mode
        self.publish_seq = 0  # next publish's confirm seq (1-based when armed)

        # tx mode (reference stubs tx.* with TODO logs,
        # FrameStage.scala:1261-1272 — implemented here): ordered buffer of
        # ("publish", AMQCommand) and ("ack"|"requeue"|"drop", Delivery)
        # entries replayed at tx.commit, discarded at tx.rollback. Settle
        # entries hold deliveries REMOVED from `unacked` (so a double-settle
        # inside one tx still raises PRECONDITION_FAILED) with their QoS
        # budget still held until the commit applies them — tx_held_count/
        # size keep the channel-global prefetch math honest while the
        # deliveries are parked outside the unacked dict. tx_bytes tracks
        # buffered publish bodies for the broker memory gate.
        self.tx_ops: list = []
        self.tx_bytes = 0
        self.tx_held_count = 0
        self.tx_held_size = 0

    # -- qos accounting ----------------------------------------------------

    def total_unacked_count(self) -> int:
        return len(self.unacked) + self.tx_held_count

    def total_unacked_size(self) -> int:
        return (sum(d.queued.body_size for d in self.unacked.values())
                + self.tx_held_size)

    def set_qos(self, prefetch_size: int, prefetch_count: int, global_: bool) -> None:
        if global_:
            self.prefetch_count_global = prefetch_count
            self.prefetch_size_global = prefetch_size
        else:
            self.prefetch_count_consumer = prefetch_count
            self.prefetch_size_consumer = prefetch_size
        for consumer in self.consumers.values():
            consumer.queue.schedule_dispatch()

    # -- delivery ----------------------------------------------------------

    def next_delivery_tag(self) -> int:
        self._delivery_tag += 1
        return self._delivery_tag

    def has_delivery_older_than(self, cutoff_ms: int) -> bool:
        """Ack-timeout probe: any outstanding delivery older than the
        cutoff — including settles parked inside an uncommitted tx (they
        left `unacked` but still pin the message and its QoS budget)."""
        for delivery in self.unacked.values():
            if delivery.delivered_at_ms < cutoff_ms:
                return True
        for op in self.tx_ops:
            if op[0] != "publish" and op[1].delivered_at_ms < cutoff_ms:
                return True
        return False

    def tag_was_issued(self, tag: int) -> bool:
        """Whether this delivery tag was ever issued on the channel (ack/nack
        validation: an above-range tag is unknown even with multiple=true)."""
        return 0 < tag <= self._delivery_tag

    def deliver(
        self, consumer: Consumer, queue: Queue, qm: QueuedMessage
    ) -> Optional[Delivery]:
        """Render basic.deliver to the connection buffer. Returns the
        Delivery for acked consumers, None for no_ack (nothing outstanding).

        Hot loop: the frames are hand-assembled (the reference renders in
        FrameStage.scala:411-443) — per-consumer constant method prefix,
        cached wire-format content header (Message.header_payload), one
        buffer append for the whole delivery."""
        tag = self.next_delivery_tag()
        msg = qm.message
        body = msg.body
        tr = None
        if trace.ACTIVE is not None:
            tr = msg.trace
            if tr is not None:
                t_del = time.perf_counter_ns()
        conn = self.connection
        if conn._egress is not None:
            # native batch egress: buffer the record, render the whole
            # dispatch pass in one chana_encode_deliveries call at the
            # flush point (connection.flush_egress)
            exrk = msg.exrk_raw
            if exrk is None:
                ex = msg.exchange.encode("utf-8")
                rk = msg.routing_key.encode("utf-8")
                exrk = msg.exrk_raw = (
                    bytes((len(ex),)) + ex + bytes((len(rk),)) + rk)
            conn.egress_deliver(
                self.id, consumer._deliver_prefix, tag, qm.redelivered,
                exrk, msg.header_payload(), body)
        else:
            conn.send_bytes(
                self._render_deliver(consumer, tag, qm.redelivered, msg, body))
        conn.delivered_msgs += 1
        if self.connection.broker.flow_consumer_buffer:
            consumer.buffered_bytes += len(body)
        metrics = self.connection.broker.metrics
        metrics.delivered(len(body))
        us = (time.perf_counter_ns() - msg.published_ns) / 1000.0
        metrics.publish_to_deliver_us.observe_us(us)
        tenant = self.connection.tenant
        if tenant is not None and tenant.latency_hist is not None:
            # per-tenant publish->deliver histogram: allocated only when a
            # delivery-latency SLO targets the tenant (tenancy/registry.py)
            tenant.latency_hist.observe_us(us)
        if tr is not None:
            tr.span(trace.DELIVER, t_del, time.perf_counter_ns(),
                    self.connection.broker.trace_node)
        fh = events.FIREHOSE
        if fh is not None and fh.tap_bindings:
            fh.tap_deliver(queue.name, msg.exchange, msg.routing_key, body,
                           queue.vhost)
        if consumer.no_ack:
            if tr is not None:
                # no-ack settles at delivery (AMQP 0-9-1 semantics)
                trace.ACTIVE.on_settle(tr, self.connection.broker.trace_node)
            return None
        delivery = Delivery(qm, queue, self, consumer.tag, tag, no_ack=False)
        self.unacked[tag] = delivery
        consumer.unacked_count += 1
        consumer.unacked_size += len(body)
        return delivery

    def _render_deliver(
        self, consumer: Consumer, tag: int, redelivered: bool, msg, body: bytes
    ) -> bytes:
        # length-prefixed exchange+routing-key: captured verbatim from the
        # publish frame when possible, else built once and cached
        exrk = msg.exrk_raw
        if exrk is None:
            ex = msg.exchange.encode("utf-8")
            rk = msg.routing_key.encode("utf-8")
            exrk = msg.exrk_raw = (
                bytes((len(ex),)) + ex + bytes((len(rk),)) + rk)
        method_payload = b"".join((
            consumer._deliver_prefix,
            tag.to_bytes(8, "big"),
            b"\x01" if redelivered else b"\x00",
            exrk,
        ))
        header_payload = msg.header_payload()
        cid = self.id
        parts = [
            _FRAME_HDR(1, cid, len(method_payload)), method_payload, b"\xce",
            _FRAME_HDR(2, cid, len(header_payload)), header_payload, b"\xce",
        ]
        if body:
            frame_max = self.connection.frame_max
            max_payload = (frame_max - FRAME_OVERHEAD) if frame_max else len(body)
            if len(body) <= max_payload:
                parts += (_FRAME_HDR(3, cid, len(body)), body, b"\xce")
            else:
                for off in range(0, len(body), max_payload):
                    chunk = body[off:off + max_payload]
                    parts += (_FRAME_HDR(3, cid, len(chunk)), chunk, b"\xce")
        return b"".join(parts)

    def redeliver(self, delivery: Delivery) -> None:
        """basic.recover(requeue=false): resend an unacked delivery on the
        same channel with the same tag, redelivered=true
        (reference: FrameStage.scala:711-776)."""
        msg = delivery.queued.message
        delivery.queued.redelivered = True
        self.connection.send_command(
            AMQCommand(
                self.id,
                Basic.Deliver(
                    consumer_tag=delivery.consumer_tag,
                    delivery_tag=delivery.delivery_tag,
                    redelivered=True,
                    exchange=msg.exchange,
                    routing_key=msg.routing_key,
                ),
                msg.properties,
                msg.body,
                header_raw=msg.header_raw,
            )
        )
        self.connection.broker.metrics.delivered(len(msg.body))

    def _release_budget(self, delivery: Delivery) -> None:
        consumer = self.consumers.get(delivery.consumer_tag)
        if consumer is not None:
            consumer.unacked_count = max(0, consumer.unacked_count - 1)
            consumer.unacked_size = max(
                0, consumer.unacked_size - delivery.queued.body_size
            )

    # -- ack paths ---------------------------------------------------------

    def resolve_tags(self, delivery_tag: int, multiple: bool) -> list[Delivery]:
        """Tags covered by an ack/nack (reference: AMQChannel.scala:161-174
        getMultipleTagsTill). delivery_tag=0 with multiple means 'all'."""
        if multiple:
            if delivery_tag == 0:
                tags = sorted(self.unacked)
            else:
                tags = sorted(t for t in self.unacked if t <= delivery_tag)
        else:
            tags = [delivery_tag] if delivery_tag in self.unacked else []
        return [self.unacked[t] for t in tags]

    def ack(self, delivery: Delivery) -> None:
        self.unacked.pop(delivery.delivery_tag, None)
        self._release_budget(delivery)
        self.connection.acked_msgs += 1
        delivery.queue.ack(delivery)
        delivery.queue.schedule_dispatch()

    def requeue(self, delivery: Delivery) -> None:
        self.unacked.pop(delivery.delivery_tag, None)
        self._release_budget(delivery)
        delivery.queue.requeue(delivery)

    # -- tx buffering ------------------------------------------------------

    def tx_stash_settle(self, kind: str, delivery: Delivery) -> None:
        """Park a validated ack/nack/reject resolution until tx.commit: the
        delivery leaves `unacked` (a second settle of the same tag inside
        the tx raises like a double-ack would) but its QoS budget stays
        held via tx_held_count/size until the commit applies it."""
        self.unacked.pop(delivery.delivery_tag, None)
        self.tx_ops.append((kind, delivery))
        self.tx_held_count += 1
        self.tx_held_size += delivery.queued.body_size

    def tx_release_held(self, delivery: Delivery) -> None:
        """Commit is applying this parked settle: drop it from the held-
        budget counters (ack/requeue/drop then release the rest)."""
        self.tx_held_count -= 1
        self.tx_held_size -= delivery.queued.body_size

    def tx_restore_settles(self, ops: list) -> None:
        """Return parked settles to the unacked set (rollback / implicit
        rollback / partial-commit failure): the acks are discarded and the
        deliveries are outstanding again, NOT redelivered (per 0-9-1, a
        client wanting redelivery issues basic.recover)."""
        for op in ops:
            if op[0] != "publish":
                delivery = op[1]
                self.tx_release_held(delivery)
                self.unacked[delivery.delivery_tag] = delivery

    def tx_rollback(self) -> None:
        """Discard the buffered transaction: publishes vanish (with their
        memory-gauge accounting), parked settles return to unacked. Shared
        by tx.rollback and the implicit rollback on channel close."""
        ops, self.tx_ops = self.tx_ops, []
        if self.tx_bytes:
            self.connection.broker.account_memory(-self.tx_bytes)
            self.tx_bytes = 0
        self.tx_restore_settles(ops)

    def drop(self, delivery: Delivery) -> None:
        self.unacked.pop(delivery.delivery_tag, None)
        self._release_budget(delivery)
        delivery.queue.drop(delivery)
        delivery.queue.schedule_dispatch()

    # -- teardown ----------------------------------------------------------

    def release_all(self) -> None:
        """On channel close: requeue every unacked delivery and detach all
        consumers (reference: FrameStage.scala:144-153 semantics). An open
        transaction implicitly rolls back: buffered publishes are dropped
        (with their memory accounting) and tx-held deliveries requeue like
        any other unacked delivery."""
        self.closed = True
        self.tx_rollback()
        # highest tag first: each requeue then lands at the queue head via
        # the O(1) appendleft fast path instead of a linear insert scan
        for tag in sorted(self.unacked, reverse=True):
            delivery = self.unacked.pop(tag)
            self._release_budget(delivery)
            delivery.queue.requeue(delivery)
        for consumer in list(self.consumers.values()):
            self.consumers.pop(consumer.tag, None)
            auto_deleted = consumer.queue.remove_consumer(consumer)
            if auto_deleted:
                self.connection.broker.schedule_queue_delete(
                    self.connection.vhost_name, consumer.queue.name
                )
