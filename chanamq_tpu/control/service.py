"""ControlService: the predictive control plane's runtime half.

Sampling and actuation happen on the event loop; the decision evaluation
runs on a single-worker executor (the Arax split — accelerator/decision
work never blocks the serving path). Each tick:

  1. gather one ``ControlInputs`` snapshot on the loop (flow ladder
     state, gate-growth trend, forecaster output when fresh + trusted,
     per-queue telemetry, peer loads over the cluster control plane),
  2. evaluate off-loop (deterministic; see engine.py),
  3. apply each decision through existing actuators — the accountant's
     stage floor + per-connection publish credit for admission, cluster
     holdership handoff for rebalance, the cluster consume-credit window
     for prefetch — unless ``dry_run`` is set, in which case decisions
     are logged and counted but provably mutate nothing.

Every decision lands in a bounded log with its input snapshot; the log
serializes canonically (sorted keys, fixed float rounding) so two runs
over the same telemetry series compare byte-for-byte. ``/admin/control``
serves ``snapshot()`` and flips ``dry_run`` at runtime (the rollout
path: observe decisions in dry-run, then enable).
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from .. import events, trace
from ..flow import STAGE_THROTTLE
from .engine import ControlConfig, ControlEngine, ControlInputs, QueueInput

log = logging.getLogger(__name__)

# telemetry QUEUE_FIELDS column order (telemetry/service.py)
_Q_PUBLISH, _Q_DELIVER, _Q_ACK, _Q_DEPTH, _Q_UNACKED, _Q_CONSUMERS, \
    _Q_READY_BYTES = range(7)


class ControlService:
    def __init__(
        self,
        broker,
        *,
        interval_s: float = 1.0,
        dry_run: bool = True,
        admission: bool = True,
        rebalance: bool = True,
        prefetch: bool = True,
        horizon_s: float = 5.0,
        arm_ticks: int = 2,
        cooldown_s: float = 10.0,
        rebalance_cooldown_s: float = 30.0,
        credit_factor: float = 0.5,
        credit_min: int = 4096,
        rebalance_ratio: float = 1.5,
        rebalance_min_rate: float = 1024.0,
        prefetch_min: int = 8,
        prefetch_max: int = 256,
        log_size: int = 256,
        forecast_max_age_s: float = 10.0,
        forecast_error_gate: float = 0.5,
        join_window_s: float = 30.0,
    ) -> None:
        self.broker = broker
        self.interval_s = max(0.05, float(interval_s))
        self.dry_run = bool(dry_run)
        self.admission_enabled = bool(admission)
        self.rebalance_enabled = bool(rebalance)
        self.prefetch_enabled = bool(prefetch)
        self.forecast_max_age_s = float(forecast_max_age_s)
        self.forecast_error_gate = float(forecast_error_gate)
        ticks = lambda s: max(1, int(round(float(s) / self.interval_s)))
        self.cfg = ControlConfig(
            horizon_ticks=ticks(horizon_s),
            arm_ticks=max(1, int(arm_ticks)),
            cooldown_ticks=ticks(cooldown_s),
            credit_factor=float(credit_factor),
            credit_min=int(credit_min),
            rebalance_ratio=float(rebalance_ratio),
            rebalance_min_rate=float(rebalance_min_rate),
            rebalance_cooldown_ticks=ticks(rebalance_cooldown_s),
            prefetch_min=int(prefetch_min),
            prefetch_max=int(prefetch_max),
            prefetch_cooldown_ticks=ticks(cooldown_s),
        )
        self.engine = ControlEngine(self.cfg)
        self.tick = 0
        self.log: deque = deque(maxlen=max(16, int(log_size)))
        # inflow EWMA (bytes/s) — the load figure peers compare for
        # rebalancing, served over the `control.load` cluster RPC
        self.load_rate = 0.0
        self._last_gate_total: Optional[int] = None
        self._last_published_bytes: Optional[int] = None
        # original publish credit, saved at pre-arm so relax restores it
        self._orig_credit: Optional[int] = None
        # join-triggered rebalance: a member that came up recently is fed
        # to the engine as an explicit target for a bounded tick window
        self._join_window_ticks = ticks(join_window_s)
        self._join_target: Optional[str] = None
        self._join_deadline_tick = 0
        self._member_listener = None
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="control")
        broker.control = self

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        cluster = self.broker.cluster
        if cluster is not None and cluster.membership is not None:

            def _on_member(event: str, member) -> None:
                if event == "up" and member.name != cluster.name:
                    self.note_member_join(member.name)

            self._member_listener = _on_member
            cluster.membership.listeners.append(_on_member)
        self._task = asyncio.get_event_loop().create_task(self._run())
        log.info("control plane started (interval=%.2fs dry_run=%s)",
                 self.interval_s, self.dry_run)

    def note_member_join(self, name: str) -> None:
        """A member joined: make it a rebalance target for a bounded
        window so backlog drains onto it without waiting for this node's
        load to diverge. Joins observed before the first tick are boot
        convergence, not elasticity — ignored."""
        if not self.rebalance_enabled or self.tick < 1:
            return
        self._join_target = name
        self._join_deadline_tick = self.tick + self._join_window_ticks

    async def stop(self) -> None:
        self._stopping = True
        cluster = self.broker.cluster
        if self._member_listener is not None and cluster is not None \
                and cluster.membership is not None:
            try:
                cluster.membership.listeners.remove(self._member_listener)
            except ValueError:
                pass
            self._member_listener = None
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        self._executor.shutdown(wait=False)
        if self.broker.control is self:
            self.broker.control = None

    async def _run(self) -> None:
        while not self._stopping:
            await asyncio.sleep(self.interval_s)
            try:
                await self.step(self.interval_s)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.broker.metrics.control_errors += 1
                log.exception("control tick failed")

    # -- one control tick (public: soaks/tests drive it manually) ----------

    async def step(self, dt_s: float) -> list:
        broker = self.broker
        flow = broker.flow
        if flow is None:
            return []  # no ladder configured: nothing to project against
        broker.metrics.control_ticks += 1
        self.tick += 1
        inputs = self._gather(dt_s)
        if self.rebalance_enabled:
            inputs.peer_loads = await self._peer_loads()
        loop = asyncio.get_event_loop()
        decisions, suppressed = await loop.run_in_executor(
            self._executor, self.engine.evaluate, inputs)
        broker.metrics.control_suppressed += suppressed
        for decision in decisions:
            broker.metrics.control_decisions += 1
            applied = False
            if self.dry_run:
                broker.metrics.control_dry_run += 1
            else:
                try:
                    applied = await self._apply(decision)
                except Exception:
                    broker.metrics.control_errors += 1
                    log.exception("control decision %s failed to apply",
                                  decision["id"])
            if applied:
                broker.metrics.control_applied += 1
            entry = dict(decision)
            entry["applied"] = applied
            entry["dry_run"] = self.dry_run
            self.log.append(entry)
            bus = events.ACTIVE
            if bus is not None:
                bus.emit(f"control.decision.{decision['kind']}", entry)
            if trace.ACTIVE is not None:
                trace.ACTIVE.note_chaos_fire(
                    f"control:{decision['kind']}:{decision['id']}")
            log.info("control decision %s %s %s (applied=%s dry_run=%s)",
                     decision["id"], decision["kind"], decision["action"],
                     applied, self.dry_run)
        return decisions

    # -- input gathering (event loop side) ---------------------------------

    def _gather(self, dt_s: float) -> ControlInputs:
        broker = self.broker
        flow = broker.flow
        gate_total = flow.total - flow.components.get("held", 0)
        # observed resident growth: the reactive trend the engine falls
        # back on when no trusted forecast is available
        if self._last_gate_total is None or dt_s <= 0:
            net_rate = 0.0
        else:
            net_rate = (gate_total - self._last_gate_total) / dt_s
        self._last_gate_total = gate_total
        published = broker.metrics.published_bytes
        if self._last_published_bytes is not None and dt_s > 0:
            inst = max(0.0, (published - self._last_published_bytes) / dt_s)
            self.load_rate = 0.7 * self.load_rate + 0.3 * inst
        self._last_published_bytes = published
        forecast_net = self._forecast_net_rate()
        queues = self._queue_inputs() if (
            self.rebalance_enabled or self.prefetch_enabled) else ()
        cluster = broker.cluster
        consume_credit = None
        if self.prefetch_enabled and cluster is not None:
            consume_credit = cluster.consume_credit
        join_target = None
        if self._join_target is not None:
            membership = cluster.membership if cluster is not None else None
            expired = self.tick > self._join_deadline_tick
            gone = (membership is None
                    or self._join_target not in
                    membership.placement_members())
            if expired or gone:
                self._join_target = None
            else:
                join_target = self._join_target
        inputs = ControlInputs(
            tick=self.tick,
            interval_s=self.interval_s,
            stage=flow.stage,
            floor=flow.floor,
            gate_total=gate_total,
            enter_throttle=(flow.enter[STAGE_THROTTLE]
                            if self.admission_enabled else 0),
            exit_throttle=flow.exit[STAGE_THROTTLE],
            net_rate=net_rate,
            publish_credit=broker.flow_publish_credit,
            forecast_net_rate=forecast_net,
            queues=queues,
            node=broker.trace_node,
            self_load=self.load_rate,
            consume_credit=consume_credit,
            join_target=join_target,
        )
        return inputs

    def _forecast_net_rate(self) -> Optional[float]:
        """Forecast net inflow (bytes/s) iff the model output is fresh and
        its tracked accuracy passes the gate; None falls the engine back
        to the observed trend."""
        forecaster = self.broker.forecaster
        if forecaster is None or not getattr(forecaster, "forecast", None):
            return None
        updated = getattr(forecaster, "updated_at", None)
        if updated is None or \
                time.time() - updated > self.forecast_max_age_s:
            return None
        if not self._forecast_trusted(forecaster):
            return None
        fc = forecaster.forecast
        inflow = fc.get("publish_bytes_rate")
        outflow = fc.get("deliver_bytes_rate")
        if inflow is None or outflow is None:
            return None
        return float(inflow) - float(outflow)

    def _forecast_trusted(self, forecaster) -> bool:
        accuracy = getattr(forecaster, "accuracy", None)
        acc = accuracy() if callable(accuracy) else accuracy
        if not acc or not acc.get("scored"):
            return False
        mae = acc.get("mae") or {}
        err = mae.get("publish_bytes_rate")
        if err is None:
            return False
        scale = max(abs(self.load_rate), 1024.0)
        return err <= self.forecast_error_gate * scale

    def _queue_inputs(self) -> tuple:
        broker = self.broker
        telemetry = broker.telemetry
        if telemetry is None:
            return ()
        keys, latest = telemetry.queues.latest_matrix()
        if not keys:
            return ()
        slot_depths = self._forecast_slot_depths()
        out = []
        for i, key in enumerate(keys):
            vhost, name = key
            row = latest[i]
            out.append(QueueInput(
                vhost=vhost, name=name,
                depth=float(row[_Q_DEPTH]),
                publish_rate=float(row[_Q_PUBLISH]),
                deliver_rate=float(row[_Q_DELIVER]),
                ack_rate=float(row[_Q_ACK]),
                ready_bytes=float(row[_Q_READY_BYTES]),
                consumers=float(row[_Q_CONSUMERS]),
                movable=self._movable(vhost, name),
                forecast_depth=slot_depths.get(key),
            ))
        return tuple(out)

    def _forecast_slot_depths(self) -> dict:
        forecaster = self.broker.forecaster
        if forecaster is None or not getattr(forecaster, "forecast", None):
            return {}
        slots = getattr(forecaster, "slot_queues", None)
        if slots is None:
            return {}
        depths = {}
        for i, key in enumerate(slots()):
            if key is None:
                continue
            value = forecaster.forecast.get(f"top{i}_depth")
            if value is not None:
                depths[tuple(key)] = float(value)
        return depths

    def _movable(self, vhost_name: str, name: str) -> bool:
        """Safe-to-hand-off check: the queue's durable content must be
        recoverable by the target from the shared store and every
        attached consumer re-registrable from its origin node."""
        broker = self.broker
        cluster = broker.cluster
        if not self.rebalance_enabled or cluster is None:
            return False
        if (vhost_name, name) not in cluster.queue_metas:
            return False
        if not cluster.owns_queue(vhost_name, name):
            return False
        vhost = broker.vhosts.get(vhost_name)
        queue = vhost.queues.get(name) if vhost is not None else None
        if queue is None or queue.deleted or queue.is_stream:
            return False
        if queue.exclusive_owner is not None or queue.outstanding:
            return False
        from ..cluster.node import RemoteConsumer
        if any(not isinstance(c, RemoteConsumer) for c in queue.consumers):
            return False
        if queue.messages:
            if not queue.durable:
                return False
            if any(not qm.message.persisted for qm in queue.messages):
                return False
        return True

    async def _peer_loads(self) -> dict:
        cluster = self.broker.cluster
        if cluster is None or cluster.membership is None:
            return {}
        loads = {}
        for peer in cluster.membership.alive_members():
            if peer == cluster.name:
                continue
            try:
                reply = await cluster._call(peer, "control.load", {},
                                            timeout_s=1.0)
                loads[peer] = float(reply.get("load", 0.0))
            except Exception:
                continue  # degraded view; rebalance just sees fewer peers
        return loads

    # -- actuation ---------------------------------------------------------

    async def _apply(self, decision: dict) -> bool:
        kind = decision["kind"]
        action = decision["action"]
        broker = self.broker
        flow = broker.flow
        if kind == "admission.prearm":
            if not self.admission_enabled or flow is None:
                return False
            if self._orig_credit is None:
                self._orig_credit = broker.flow_publish_credit
            credit = int(action.get("publish_credit", 0))
            if credit > 0:
                broker.flow_publish_credit = credit
            flow.floor = STAGE_THROTTLE
            flow.reevaluate()
            return True
        if kind == "admission.relax":
            if flow is None:
                return False
            flow.floor = 0
            if self._orig_credit is not None:
                broker.flow_publish_credit = self._orig_credit
                self._orig_credit = None
            flow.reevaluate()
            return True
        if kind == "rebalance.move":
            cluster = broker.cluster
            if cluster is None or not self.rebalance_enabled:
                return False
            moved = await cluster.handoff_queue(
                str(action["vhost"]), str(action["name"]),
                str(action["target"]), decision=decision["id"])
            if action.get("join"):
                # one seeding move per observed join
                self._join_target = None
                if moved:
                    broker.metrics.lifecycle_join_rebalances += 1
            return moved
        if kind == "prefetch.tune":
            cluster = broker.cluster
            if cluster is None or not self.prefetch_enabled:
                return False
            cluster.consume_credit = max(1, int(action["consume_credit"]))
            return True
        return False

    # -- introspection -----------------------------------------------------

    def decision_log_bytes(self) -> bytes:
        """Canonical serialization of the full retained log — the form
        the soak byte-compares across same-seed runs."""
        return "\n".join(
            json.dumps(entry, sort_keys=True, separators=(",", ":"))
            for entry in self.log
        ).encode()

    def gauges(self) -> dict:
        """Merged into broker.metrics_snapshot() (/admin/metrics)."""
        flow = self.broker.flow
        return {
            "control_floor": flow.floor if flow is not None else 0,
            "control_armed": 1 if self.engine.snapshot()["armed"] else 0,
            "control_load_rate": round(self.load_rate, 1),
            "control_log_entries": len(self.log),
        }

    def snapshot(self, tail: int = 32) -> dict:
        flow = self.broker.flow
        metrics = self.broker.metrics
        return {
            "enabled": True,
            "dry_run": self.dry_run,
            "interval_s": self.interval_s,
            "tick": self.tick,
            "features": {
                "admission": self.admission_enabled,
                "rebalance": self.rebalance_enabled,
                "prefetch": self.prefetch_enabled,
            },
            "config": {
                "horizon_ticks": self.cfg.horizon_ticks,
                "arm_ticks": self.cfg.arm_ticks,
                "cooldown_ticks": self.cfg.cooldown_ticks,
                "credit_factor": self.cfg.credit_factor,
                "credit_min": self.cfg.credit_min,
                "rebalance_ratio": self.cfg.rebalance_ratio,
                "rebalance_cooldown_ticks": self.cfg.rebalance_cooldown_ticks,
                "prefetch_min": self.cfg.prefetch_min,
                "prefetch_max": self.cfg.prefetch_max,
            },
            "engine": self.engine.snapshot(),
            "flow": {
                "stage": flow.stage if flow is not None else 0,
                "floor": flow.floor if flow is not None else 0,
            },
            "load_rate": round(self.load_rate, 1),
            "counters": {
                "ticks": metrics.control_ticks,
                "decisions": metrics.control_decisions,
                "applied": metrics.control_applied,
                "suppressed": metrics.control_suppressed,
                "dry_run": metrics.control_dry_run,
                "errors": metrics.control_errors,
            },
            "log": list(self.log)[-max(0, tail):],
        }
