"""Per-core shard subsystem tests (chanamq_tpu/shard/): topology layout,
supervisor env forwarding and restart budget, RPC + data plane over Unix
sockets (frame kinds 4/5/6), trace trailers across the intra-node hop,
chaos data.* seams on UDS, fd handoff, the shard Prometheus label,
shard-liveness readiness, and the UDS chaos soak invariants."""

import asyncio
import os
import sys
import tempfile

import pytest

from chanamq_tpu import chaos, trace
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.chaos.plan import FaultPlan, FaultRule
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster.node import ClusterNode
from chanamq_tpu.cluster.rpc import RpcClient, RpcServer, UdsTransport
from chanamq_tpu.config import Config
from chanamq_tpu.shard import ShardTopology, resolve_count
from chanamq_tpu.shard.handoff import HandoffAcceptor, HandoffReceiver
from chanamq_tpu.shard.supervisor import ShardSupervisor, child_env
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.trace import INTRA_SHARD_HOP, STAGES, TraceRuntime

pytestmark = pytest.mark.asyncio


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    trace.clear()
    chaos.clear()


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------


def _config(values=None):
    return Config(values or {}, env={})


async def test_resolve_count_auto_and_explicit():
    assert resolve_count(_config({"chana.mq.shard.count": 3})) == 3
    auto = resolve_count(_config({"chana.mq.shard.count": 0}))
    assert auto == (os.cpu_count() or 1)
    assert resolve_count(_config()) == 1  # default: sharding off


async def test_topology_layout(tmp_path):
    topo = ShardTopology(count=3, host="127.0.0.1", base_port=7000,
                         dir=str(tmp_path))
    assert topo.names() == ["127.0.0.1:7000", "127.0.0.1:7001",
                            "127.0.0.1:7002"]
    assert topo.uds_path(1) == os.path.join(str(tmp_path), "shard-1.sock")
    assert topo.handoff_path(2) == os.path.join(
        str(tmp_path), "handoff-2.sock")
    # self excluded; every sibling mapped to its socket
    assert topo.uds_map_for(1) == {
        "127.0.0.1:7000": topo.uds_path(0),
        "127.0.0.1:7002": topo.uds_path(2),
    }
    assert topo.seeds_for(0, external=["10.0.0.9:7000"]) == [
        "127.0.0.1:7001", "127.0.0.1:7002", "10.0.0.9:7000"]


async def test_topology_from_env_recovers_base_port(tmp_path):
    # the supervisor overrode this worker's cluster.port to base + index;
    # the worker must recover the base by subtraction
    config = _config({"chana.mq.cluster.host": "127.0.0.1",
                      "chana.mq.cluster.port": 7002})
    topo = ShardTopology.from_env(
        config, 2,
        environ={"CHANAMQ_SHARD_COUNT": "3",
                 "CHANAMQ_SHARD_DIR": str(tmp_path)})
    assert topo.base_port == 7000 and topo.count == 3
    assert topo.name(2) == "127.0.0.1:7002"
    assert topo.uds_map_for(2) == {
        "127.0.0.1:7000": topo.uds_path(0),
        "127.0.0.1:7001": topo.uds_path(1),
    }


async def test_child_env_layers_per_shard_values(tmp_path):
    config = _config({
        "chana.mq.cluster.host": "127.0.0.1",
        "chana.mq.cluster.port": 7100,
        "chana.mq.cluster.seeds": ["10.0.0.9:7100"],
        "chana.mq.admin.enabled": True,
        "chana.mq.admin.port": 15700,
        "chana.mq.store.path": str(tmp_path / "node.db"),
        "chana.mq.shard.heartbeat-interval": "200ms",
        "chana.mq.shard.failure-timeout": "1.5s",
    })
    topo = ShardTopology(count=2, host="127.0.0.1", base_port=7100,
                         dir=str(tmp_path))
    env = child_env(config, topo, 1, restarts=4)
    assert env["CHANAMQ_SHARD_INDEX"] == "1"
    assert env["CHANAMQ_SHARD_COUNT"] == "2"
    assert env["CHANAMQ_SHARD_DIR"] == str(tmp_path)
    assert env["CHANAMQ_SHARD_RESTARTS"] == "4"
    assert env["CHANAMQ_CLUSTER_ENABLED"] == "true"
    assert env["CHANAMQ_CLUSTER_PORT"] == "7101"
    # siblings first, then the cross-machine seed from the config
    assert env["CHANAMQ_CLUSTER_SEEDS"] == "127.0.0.1:7100,10.0.0.9:7100"
    assert env["CHANAMQ_CLUSTER_HEARTBEAT_INTERVAL"] == "200ms"
    assert env["CHANAMQ_CLUSTER_FAILURE_TIMEOUT"] == "1.5s"
    assert env["CHANAMQ_ADMIN_PORT"] == "15701"
    assert env["CHANAMQ_STORE_PATH"] == str(tmp_path / "node.db") + ".shard1"


async def test_supervisor_restart_budget(monkeypatch, tmp_path):
    """A worker that keeps dying is respawned max-restarts times, then
    left down — the watcher must not spin."""
    config = _config({
        "chana.mq.shard.count": 2,
        "chana.mq.shard.dir": str(tmp_path),
        "chana.mq.shard.restart-backoff": "10ms",
        "chana.mq.shard.max-restarts": 2,
    })
    sup = ShardSupervisor(config)

    async def fake_spawn(index):
        return await asyncio.create_subprocess_exec(
            sys.executable, "-c", "pass",
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL)

    monkeypatch.setattr(sup, "_spawn", fake_spawn)
    await asyncio.wait_for(sup._supervise(0), 30)
    assert sup.restarts[0] == 3  # budget (2) exhausted on the 3rd exit


# ---------------------------------------------------------------------------
# Unix-socket control + data plane
# ---------------------------------------------------------------------------


async def test_rpc_over_uds_and_unlink(tmp_path):
    path = os.path.join(str(tmp_path), "s.sock")
    server = RpcServer("127.0.0.1", 0, uds_path=path)
    async def echo(payload):
        return {"got": payload["x"]}

    server.register("echo", echo)
    await server.start()
    client = RpcClient(UdsTransport(path, peer="127.0.0.1:7000"))
    try:
        assert os.path.exists(path)
        result = await client.call("echo", {"x": 41})
        assert result == {"got": 41}
        # the transport's chaos identity is the member name, not the path
        assert client.transport.peer == "127.0.0.1:7000"
        assert client.transport.kind == "uds"
    finally:
        await client.close()
        await server.stop()
    assert not os.path.exists(path)  # stale socket unlinked on stop


async def _start_uds_pair(sock_dir):
    """Two in-process nodes whose control + data planes ride Unix sockets
    (the sibling-shard wiring, minus the supervisor)."""
    a_path = os.path.join(sock_dir, "a.sock")
    b_path = os.path.join(sock_dir, "b.sock")

    async def one(seeds, uds_path):
        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                           store=MemoryStore())
        await srv.start()
        cl = ClusterNode(srv.broker, "127.0.0.1", 0, seeds,
                         heartbeat_interval_s=0.1, failure_timeout_s=0.8,
                         uds_path=uds_path)
        await cl.start()
        return srv, cl

    a_srv, a_cl = await one([], a_path)
    b_srv, b_cl = await one([a_cl.name], b_path)
    # ephemeral cluster ports: names are only known post-start, so the
    # sibling map is patched in afterwards (the supervisor precomputes it)
    a_cl.uds_map[b_cl.name] = b_path
    b_cl.uds_map[a_cl.name] = a_path
    for _ in range(100):
        if (len(a_cl.membership.alive_members()) == 2
                and len(b_cl.membership.alive_members()) == 2):
            break
        await asyncio.sleep(0.05)
    assert len(a_cl.membership.alive_members()) == 2
    return (a_srv, a_cl), (b_srv, b_cl)


async def _stop_pair(a, b):
    for srv, cl in (b, a):
        await cl.stop()
        await srv.stop()


def _owned_by(cluster, owner_name, prefix):
    return next(f"{prefix}{i}" for i in range(200)
                if cluster.queue_owner("/", f"{prefix}{i}") == owner_name)


async def test_uds_dataplane_push_deliver_settle():
    """Publish via the non-owner, consume remotely, manual-ack: all three
    binary frame kinds (push 4 / settle 6 / deliver 5) must ride the UDS
    transport, with the cross-shard push counted."""
    sock_dir = tempfile.mkdtemp(prefix="shard-test-")
    a, b = await _start_uds_pair(sock_dir)
    (a_srv, a_cl), (b_srv, b_cl) = a, b
    try:
        qn = _owned_by(a_cl, b_cl.name, "sq")
        c = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn)
        for _ in range(100):  # owner's meta broadcast is fire-and-forget
            if ("/", qn) in a_cl.queue_metas:
                break
            await asyncio.sleep(0.05)
        got = asyncio.get_event_loop().create_future()

        def on_msg(m):
            if not got.done():
                got.set_result((bytes(m.body), m.delivery_tag))

        await ch.basic_consume(qn, on_msg, no_ack=False)
        ch.basic_publish(b"over-uds", routing_key=qn)
        await ch.wait_unconfirmed_below(1, timeout=10)
        body, tag = await asyncio.wait_for(got, 10)
        assert body == b"over-uds"
        ch.basic_ack(tag)
        for _ in range(100):  # settle is batched; give the flusher a beat
            if a_srv.broker.metrics.rpc_settle_records >= 1:
                break
            await asyncio.sleep(0.05)
        await c.close()

        plane = a_cl.dataplane(b_cl.name)
        assert plane.transport.kind == "uds"
        assert plane.intra_node is True
        assert plane.stats()["transport"] == "uds"
        am, bm = a_srv.broker.metrics, b_srv.broker.metrics
        assert am.rpc_push_records >= 1  # kind 4, A -> B
        assert am.shard_cross_pushes >= 1  # counted as an intra-node hop
        assert bm.rpc_deliver_records >= 1  # kind 5, B -> A
        assert am.rpc_settle_records >= 1  # kind 6, A -> B
    finally:
        await _stop_pair(a, b)


async def test_trace_trailer_survives_intra_node_hop():
    """A sampled publish crossing shards over UDS must stitch into one
    trace spanning both workers and carry the intra-shard-hop span."""
    sock_dir = tempfile.mkdtemp(prefix="shard-test-")
    a, b = await _start_uds_pair(sock_dir)
    (a_srv, a_cl), (b_srv, b_cl) = a, b
    try:
        rt = trace.install(TraceRuntime(
            sample_rate=1.0, metrics=a_srv.broker.metrics, node=a_cl.name))
        qn = _owned_by(a_cl, b_cl.name, "tq")
        c = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn)
        for _ in range(100):
            if ("/", qn) in a_cl.queue_metas:
                break
            await asyncio.sleep(0.05)
        got = asyncio.get_event_loop().create_future()
        await ch.basic_consume(
            qn, lambda m: got.done() or got.set_result(bytes(m.body)),
            no_ack=True)
        ch.basic_publish(b"traced", routing_key=qn)
        await ch.wait_unconfirmed_below(1, timeout=10)
        assert await asyncio.wait_for(got, 10) == b"traced"
        await c.close()

        for _ in range(100):
            if rt.ring:
                break
            await asyncio.sleep(0.05)
        stitched = rt.find(rt.ring[-1].trace_id)
        d = stitched.to_dict()
        assert len(d["nodes"]) == 2, d
        span = stitched.slots[INTRA_SHARD_HOP]
        assert span is not None, (STAGES[INTRA_SHARD_HOP], d)
        assert span[2] == a_cl.name  # stamped by the pushing side
        lo, hi = stitched.bounds_ns()
        assert lo <= span[0] <= span[1] <= hi
    finally:
        await _stop_pair(a, b)


async def test_chaos_data_seams_fire_on_uds():
    """Node-scoped chaos rules must hit UDS peers: the transport carries
    the sibling's member name, so `peer=<name>` matches even though no
    TCP endpoint is involved."""
    sock_dir = tempfile.mkdtemp(prefix="shard-test-")
    a, b = await _start_uds_pair(sock_dir)
    (a_srv, a_cl), (b_srv, b_cl) = a, b
    try:
        runtime = chaos.install(FaultPlan(seed=3, rules=[
            FaultRule(name="uds-lat", kind="latency", sites=["data.*"],
                      peer=b_cl.name),
        ]), metrics=a_srv.broker.metrics)
        qn = _owned_by(a_cl, b_cl.name, "cq")
        c = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn)
        for _ in range(100):
            if ("/", qn) in a_cl.queue_metas:
                break
            await asyncio.sleep(0.05)
        ch.basic_publish(b"chaoted", routing_key=qn)
        await ch.wait_unconfirmed_below(1, timeout=10)
        await c.close()
        status = runtime.status()
        fired = {e["rule"] for e in status["fire_log_tail"]}
        assert "uds-lat" in fired, status
        assert a_srv.broker.metrics.chaos_fires >= 1
    finally:
        await _stop_pair(a, b)


# ---------------------------------------------------------------------------
# fd handoff (reuse-port fallback)
# ---------------------------------------------------------------------------


class _FakeBrokerServer:
    def __init__(self):
        self.served = 0

    async def _on_client(self, reader, writer):
        data = await reader.readexactly(5)
        writer.write(b"pong:" + data)
        await writer.drain()
        self.served += 1
        writer.close()


async def test_handoff_acceptor_to_receiver_roundtrip():
    """A client accepted by the supervisor's TCP listener is shipped over
    SCM_RIGHTS and served by the worker's event loop — bytes flow both
    ways on the original connection."""
    sock_dir = tempfile.mkdtemp(prefix="shard-test-")
    feed_path = os.path.join(sock_dir, "handoff-0.sock")
    fake = _FakeBrokerServer()
    receiver = HandoffReceiver(fake, feed_path)
    await receiver.start()
    acceptor = HandoffAcceptor("127.0.0.1", 0, [feed_path])
    await acceptor.start()
    try:
        for i in range(3):  # several clients: the feed socket is reused
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", acceptor.bound_port)
            writer.write(b"hello")
            await writer.drain()
            resp = await asyncio.wait_for(reader.readexactly(10), 5)
            assert resp == b"pong:hello"
            writer.close()
        assert acceptor.dispatched == 3
        assert acceptor.dropped == 0
        for _ in range(100):
            if receiver.adopted == 3 and fake.served == 3:
                break
            await asyncio.sleep(0.05)
        assert receiver.adopted == 3 and fake.served == 3
    finally:
        await acceptor.stop()
        await receiver.stop()
    assert not os.path.exists(feed_path)


# ---------------------------------------------------------------------------
# observability: shard label, shard readiness
# ---------------------------------------------------------------------------


async def test_prometheus_shard_label_and_counters():
    from chanamq_tpu.broker.broker import Broker
    from chanamq_tpu.rest.admin import AdminServer

    broker = Broker()
    await broker.start()
    try:
        admin = AdminServer(broker, port=0)
        # unsharded: plain series names, no label
        assert "chanamq_published_msgs 0" in admin._prometheus()
        broker.shard_info = {"index": 1, "count": 2,
                             "name": "127.0.0.1:7001"}
        broker.metrics.shard_cross_pushes = 7
        text = admin._prometheus()
        assert 'chanamq_published_msgs{shard="1"} 0' in text
        assert 'chanamq_shard_cross_pushes{shard="1"} 7' in text
        assert "# TYPE chanamq_shard_cross_pushes counter" in text
        assert "# TYPE chanamq_shard_handoffs counter" in text
        assert "# TYPE chanamq_shard_restarts counter" in text
    finally:
        await broker.stop()


async def test_readiness_flags_dead_shard_sibling():
    from chanamq_tpu.telemetry import TelemetryService
    from chanamq_tpu.telemetry.health import evaluate_health

    sock_dir = tempfile.mkdtemp(prefix="shard-test-")
    a, b = await _start_uds_pair(sock_dir)
    (a_srv, a_cl), (b_srv, b_cl) = a, b
    b_stopped = False
    try:
        a_srv.broker.shard_info = {"index": 0, "count": 2, "name": a_cl.name}
        svc = TelemetryService(a_srv.broker)
        report = evaluate_health(a_srv.broker, svc)
        assert report["checks"]["shards"]["ok"] is True
        assert report["checks"]["shards"]["dead_siblings"] == []

        await b_cl.stop()
        await b_srv.stop()
        b_stopped = True
        for _ in range(100):
            if b_cl.name not in a_cl.membership.alive_members():
                break
            await asyncio.sleep(0.05)
        report = evaluate_health(a_srv.broker, svc)
        shards = report["checks"]["shards"]
        assert shards["ok"] is False
        assert shards["dead_siblings"] == [b_cl.name]
        assert any("shard sibling" in r for r in report["reasons"])
        assert report["ready"] is False

        # the /admin/health fallback (telemetry disabled — the default)
        # must surface the same check: sibling liveness only needs
        # membership, and an LB probing a sharded worker without
        # telemetry still has to see it drain
        from chanamq_tpu.rest.admin import AdminServer, _Response

        admin = AdminServer(a_srv.broker, port=0)
        resp = await admin._health({})
        assert isinstance(resp, _Response) and "503" in resp.status
        body = resp.payload
        assert body["checks"]["shards"]["dead_siblings"] == [b_cl.name]
        assert body["ready"] is False
    finally:
        if not b_stopped:
            await b_cl.stop()
            await b_srv.stop()
        await a_cl.stop()
        await a_srv.stop()


# ---------------------------------------------------------------------------
# chaos soak over UDS
# ---------------------------------------------------------------------------


async def test_soak_uds_no_loss_and_rehash_per_survivor():
    """The seeded soak with the interconnect on Unix sockets: the default
    plan's owner crash must cost zero confirmed messages and re-hash
    ownership exactly once on each of the two survivors."""
    from chanamq_tpu.chaos.soak import run_soak

    report = await asyncio.wait_for(
        run_soak(42, messages=60, uds=True), timeout=120)
    assert report["violations"] == [], report["violations"]
    assert report["interconnect"] == "uds"
    assert report["handoffs"] == 2
    assert report["confirmed"] > 0
