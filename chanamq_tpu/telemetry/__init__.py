"""Per-entity telemetry: fixed-slot timeseries rings per queue and per
connection, an event-loop lag / sampler-saturation probe, a health and
readiness surface, and a declarative alert-rule engine evaluated
vectorized over the per-entity matrix each tick.

Layout mirrors the chaos/ and trace/ subsystems: a service object hangs
off ``broker.telemetry`` when ``chana.mq.telemetry.enabled`` is on, the
hot path pays nothing (the broker maintains plain int gauges and
counters; sampling happens on a timer off the message path), and the
admin layer serves cluster-wide views by pulling per-node payloads over
the existing control-plane RPC (``telemetry.pull``).
"""

from .store import EntityRings, QUEUE_FIELDS, CONN_FIELDS  # noqa: F401
from .alerts import AlertRule, AlertEngine, default_rules  # noqa: F401
from .health import evaluate_health  # noqa: F401
from .service import TelemetryService  # noqa: F401
