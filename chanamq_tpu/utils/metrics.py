"""Broker metrics registry: counters + latency histogram.

The reference has no metrics subsystem — throughput was measured by grepping
log lines (SURVEY.md §5 "observability", chana-mq-test/perf/sum-published.sh)
and no latency measurement existed at all. This registry supplies what
BASELINE.md needs: publish/deliver counters and publish->deliver latency
percentiles, with negligible hot-path cost.
"""

from __future__ import annotations

from bisect import bisect_left
import time
from typing import Optional


class Histogram:
    """Fixed-bucket log-scale latency histogram (microseconds)."""

    # bucket upper bounds in us: 1,2,5,10,...,1e7 (10 s), +inf
    BOUNDS = [
        1, 2, 5, 10, 20, 50, 100, 200, 500,
        1_000, 2_000, 5_000, 10_000, 20_000, 50_000,
        100_000, 200_000, 500_000, 1_000_000, 2_000_000, 5_000_000, 10_000_000,
    ]

    def __init__(self) -> None:
        self.buckets = [0] * (len(self.BOUNDS) + 1)
        self.count = 0
        self.total_us = 0

    def observe_us(self, us: float) -> None:
        # once per delivered message: bisect, not a linear bound walk (at
        # saturated latencies the walk visited most of the 22 bounds)
        self.count += 1
        self.total_us += int(us)
        self.buckets[bisect_left(self.BOUNDS, us)] += 1

    def percentile_us(self, p: float) -> Optional[float]:
        """Upper-bound estimate of the p-quantile (p in [0,1])."""
        if self.count == 0:
            return None
        target = p * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                return float(self.BOUNDS[i]) if i < len(self.BOUNDS) else float("inf")
        return float("inf")

    @property
    def mean_us(self) -> Optional[float]:
        return self.total_us / self.count if self.count else None


class Metrics:
    def __init__(self) -> None:
        self.published_msgs = 0
        self.published_bytes = 0
        self.delivered_msgs = 0
        self.delivered_bytes = 0
        self.returned_msgs = 0
        self.confirmed_msgs = 0
        self.expired_msgs = 0
        self.dead_lettered_msgs = 0
        self.connections_opened = 0
        self.connections_closed = 0
        # accepts refused at the listener cap (chana.mq.server.max-connections)
        self.connections_refused = 0
        self.publish_to_deliver_us = Histogram()
        # queue replication (replicate/): owner-side ship + follower-side
        # apply counters and the owner-observed follower ack latency
        self.repl_events_shipped = 0
        self.repl_batches_shipped = 0
        self.repl_events_applied = 0
        self.repl_resyncs = 0
        self.repl_promotions = 0
        self.repl_ack_timeouts = 0
        self.repl_ack_us = Histogram()
        # stream queues (streams/): append/seal/truncate volume plus
        # cursor activity (deliveries count records read, commits count
        # monotonic cursor advances on ack)
        self.stream_appends = 0
        self.stream_append_bytes = 0
        self.stream_segments_sealed = 0
        self.stream_segments_truncated = 0
        self.stream_records_delivered = 0
        self.stream_cursor_commits = 0
        # consumer groups on streams (streams/groups.py)
        self.stream_groups_created = 0
        self.stream_group_deliveries = 0
        # cluster interconnect data plane (cluster/dataplane.py): binary
        # frame volume, batch sizes, and what cut each batch (window timer,
        # byte cap, count cap, or a barrier demanding an early flush)
        self.rpc_data_bytes_sent = 0
        self.rpc_data_bytes_recv = 0
        self.rpc_push_records = 0
        self.rpc_push_batches = 0
        self.rpc_settle_records = 0
        self.rpc_settle_batches = 0
        self.rpc_deliver_records = 0
        self.rpc_deliver_batches = 0
        self.rpc_flush_window = 0
        self.rpc_flush_bytes = 0
        self.rpc_flush_count = 0
        self.rpc_flush_demand = 0
        # fault injection (chanamq_tpu/chaos/): all zero unless a plan fires
        self.chaos_fires = 0
        self.chaos_latency = 0
        self.chaos_errors = 0
        self.chaos_drops = 0
        self.chaos_disconnects = 0
        self.chaos_corrupt_frames = 0
        self.chaos_crashes = 0
        self.chaos_partition_drops = 0
        # message tracing (chanamq_tpu/trace/): all zero unless installed.
        # trace_stage_us is populated with one Histogram per pipeline stage
        # by TraceRuntime at install time (key: trace_<stage>_us).
        self.trace_sampled = 0
        self.trace_completed = 0
        self.trace_slow = 0
        self.trace_chaos_tagged = 0
        self.trace_ctx_sent = 0
        self.trace_ctx_recv = 0
        self.trace_evicted = 0
        self.trace_stage_us: "dict[str, Histogram]" = {}
        # OTLP interop (chanamq_tpu/otel/): forced samples minted for
        # client-supplied traceparent headers, spans exported (push and
        # pull combined), OTLP/HTTP batches posted, failed posts, traces
        # shed by the bounded exporter queue / overload ladder, and pull
        # requests served on /admin/otel/spans. All zero unless a
        # traceparent arrives or chana.mq.otel.enabled is set.
        self.otel_forced_samples = 0
        self.otel_spans_exported = 0
        self.otel_batches_sent = 0
        self.otel_export_errors = 0
        self.otel_spans_shed = 0
        self.otel_pull_served = 0
        # per-entity telemetry (chanamq_tpu/telemetry/): sampler progress,
        # ring-slot pressure, and alert-engine transitions. All zero unless
        # the telemetry service is running (chana.mq.telemetry.enabled).
        self.telemetry_ticks = 0
        self.telemetry_saturated_ticks = 0
        self.telemetry_evicted_entities = 0
        self.telemetry_dropped_entities = 0
        self.alerts_fired = 0
        self.alerts_resolved = 0
        # write-ahead log engine (chanamq_tpu/wal/): append/commit volume,
        # checkpoint + recovery accounting, stream-segment tier offload and
        # key compaction. All zero unless chana.mq.wal.enabled with a store.
        self.wal_appends = 0
        self.wal_append_bytes = 0
        self.wal_commits = 0
        self.wal_fsyncs = 0
        self.wal_commit_errors = 0
        self.wal_segments_sealed = 0
        self.wal_segments_truncated = 0
        self.wal_checkpoints = 0
        self.wal_checkpoint_errors = 0
        self.wal_recovered_records = 0
        self.wal_recover_torn = 0
        self.wal_recover_corrupt = 0
        self.wal_tier_offloads = 0
        self.wal_tier_rehydrations = 0
        self.wal_compactions = 0
        self.wal_compacted_records = 0
        self.wal_memtable_drains = 0
        self.wal_memtable_elided = 0
        self.wal_memtable_hits = 0
        self.wal_tx_batches = 0
        self.wal_tx_batch_ops = 0
        self.wal_commit_us = Histogram()
        # multi-process sharding (chanamq_tpu/shard/): cross-shard UDS
        # pushes, ownership re-hashes observed on sibling death, and the
        # restart generation the supervisor hands a respawned worker.
        self.shard_cross_pushes = 0
        self.shard_handoffs = 0
        self.shard_restarts = 0
        # overload protection (chanamq_tpu/flow/): ladder transitions,
        # stage-1 pressure paging, stage-2 throttle signals and the time
        # publishes spend parked, stage-3 cluster stalls, stage-4
        # refusals, and per-consumer delivery-buffer saturation. All
        # zero unless a flow watermark is configured.
        self.flow_escalations = 0
        self.flow_deescalations = 0
        self.flow_paged_bodies = 0
        self.flow_paged_bytes = 0
        self.flow_throttles = 0
        self.flow_resumes = 0
        self.flow_hold_releases = 0
        self.flow_hold_wait_ns = 0
        self.flow_cluster_stalls = 0
        self.flow_publishes_refused = 0
        self.flow_slow_consumers = 0
        # predictive control plane (chanamq_tpu/control/): ticks evaluated,
        # decisions emitted by the engine, decisions actually actuated,
        # triggers blocked by hysteresis/cooldown, decisions recorded in
        # dry-run without actuation, and apply/tick failures
        self.control_ticks = 0
        self.control_decisions = 0
        self.control_applied = 0
        self.control_suppressed = 0
        self.control_dry_run = 0
        self.control_errors = 0
        self.chaos_pressure = 0
        # node lifecycle (chanamq_tpu/cluster/lifecycle.py): drains run on
        # this node, queues it evacuated, activate retries + holdership
        # rollbacks during evacuation, fencing-epoch refusals (stale
        # broadcasts, ships, and writes), join-triggered rebalances this
        # node's control plane emitted, and stale holderships cleared by
        # anti-entropy / lifecycle events.
        self.lifecycle_drains_started = 0
        self.lifecycle_queues_evacuated = 0
        self.lifecycle_evacuation_retries = 0
        self.lifecycle_rollbacks = 0
        self.lifecycle_stale_epoch_refused = 0
        self.lifecycle_join_rebalances = 0
        self.lifecycle_stale_holders_cleared = 0
        # tensorized router (chanamq_tpu/router/): kernel batches routed,
        # messages in them, table compiles + the current generation (gauge),
        # messages that fell back to the Python matcher (uncompilable
        # exchange or sub-min-batch flush), and verify-mode parity
        # mismatches (always 0 unless a kernel bug slips parity testing).
        # router_batch_size is a Histogram over flush batch sizes —
        # messages per kernel call, not microseconds.
        self.router_batches = 0
        self.router_batch_msgs = 0
        self.router_compiles = 0
        self.router_generation = 0
        self.router_fallback_msgs = 0
        self.router_parity_mismatches = 0
        self.router_batch_size = Histogram()
        # native batch egress (native/chanamq_native.cpp): delivery
        # batches rendered by chana_encode_deliveries, the messages and
        # wire bytes they covered, pool-dry acquires that fell back to a
        # heap buffer, and defensive encode fallbacks to the Python
        # renderer (a size disagreement — never expected)
        self.native_egress_batches = 0
        self.native_egress_msgs = 0
        self.native_egress_bytes = 0
        self.native_egress_fallbacks = 0
        self.native_pool_exhausted = 0
        # continuous profiling (chanamq_tpu/profile/): stack-sampler
        # samples taken, event-loop callbacks caught over the slow
        # threshold, and collector pauses seen by the gc hook. All zero
        # unless chana.mq.profile.enabled. The _total suffix is baked
        # into the attribute so the Prometheus series follow the naming
        # convention for counters that grew up after PR 6.
        self.profile_samples_total = 0
        self.profile_slow_callbacks_total = 0
        self.profile_gc_pauses_total = 0
        self.profile_gc_pause_ns_total = 0
        # event bus + firehose (chanamq_tpu/events/): events that reached
        # at least one bound queue vs O(1) drops (nothing bound, or the
        # bus swallowed an emit error), and firehose taps published vs
        # shed (flow stage > 0 or no trace binding). All zero unless
        # chana.mq.events.enabled / chana.mq.firehose.enabled.
        self.events_published_total = 0
        self.events_dropped_total = 0
        self.firehose_published_total = 0
        self.firehose_dropped_total = 0
        # SLO engine (chanamq_tpu/slo/): burn-rate alert firings across
        # all specs and window pairs (per-spec counts live in the engine
        # snapshot and the chanamq_slo_violations_total labeled series)
        self.slo_violations_total = 0
        # multi-tenancy (chanamq_tpu/tenancy/): tenant gate transitions
        # (token bucket drained / memory share breached, and the matching
        # resumes), quota refusals at the declare/open mutation sites, and
        # ACL denials mapped to access-refused. All zero unless
        # chana.mq.tenant.enabled.
        self.tenancy_throttles_total = 0
        self.tenancy_resumes_total = 0
        self.tenancy_quota_refusals_total = 0
        self.tenancy_acl_denials_total = 0
        # delivery semantics (chanamq_tpu/semantics/): Tx commits/rollbacks
        # on the WAL scope, delayed-delivery timer-wheel traffic, priority
        # fan enqueues, and dead-letter outcomes (cycle suppressions are
        # fully-automatic x-death loops dropped per the RabbitMQ rule).
        self.semantics_tx_commits = 0
        self.semantics_tx_rollbacks = 0
        self.semantics_delayed_msgs = 0
        self.semantics_delay_fired = 0
        self.semantics_priority_msgs = 0
        self.dlx_published = 0
        self.dlx_cycle_drops = 0
        self.dlx_expired = 0
        self.dlx_rejected = 0
        self.dlx_maxlen = 0
        # federation (chanamq_tpu/federation/): sealed-segment shipping,
        # mirrored cursor commits, DLX forwards and staged Tx batches
        # across named links, both the shipping and the receiving side.
        self.federation_segments_shipped = 0
        self.federation_segment_bytes = 0
        self.federation_segments_applied = 0
        self.federation_duplicate_segments = 0
        self.federation_crc_failures = 0
        self.federation_ship_errors = 0
        self.federation_resyncs = 0
        self.federation_resumes = 0
        self.federation_link_failures = 0
        self.federation_cursors_shipped = 0
        self.federation_cursors_mirrored = 0
        self.federation_dlx_forwarded = 0
        self.federation_tx_batches = 0
        self.federation_tx_publishes = 0
        self.federation_tx_applied = 0
        self.federation_outbox_dropped = 0
        self.federation_outbox_dropped_publish = 0
        self.federation_outbox_dropped_tx = 0
        self.federation_duplicate_forwards = 0
        self.federation_invalid_segments = 0
        self.federation_auth_failures = 0
        # anti-entropy peers skipped because the lifecycle machine marked
        # them LEFT (satellite of the federation PR)
        self.lifecycle_left_peer_skipped = 0
        self.started_at = time.time()

    def published(self, nbytes: int) -> None:
        self.published_msgs += 1
        self.published_bytes += nbytes

    def delivered(self, nbytes: int) -> None:
        self.delivered_msgs += 1
        self.delivered_bytes += nbytes

    def histograms(self) -> "dict[str, Histogram]":
        """Every registered histogram, for cumulative Prometheus export."""
        out = {
            "publish_to_deliver_us": self.publish_to_deliver_us,
            "repl_ack_us": self.repl_ack_us,
            "wal_commit_us": self.wal_commit_us,
            "router_batch_size": self.router_batch_size,
        }
        out.update(self.trace_stage_us)
        return out

    def snapshot(self) -> dict:
        elapsed = time.time() - self.started_at
        h = self.publish_to_deliver_us
        out = {
            "uptime_s": round(elapsed, 3),
            "published_msgs": self.published_msgs,
            "published_bytes": self.published_bytes,
            "delivered_msgs": self.delivered_msgs,
            "delivered_bytes": self.delivered_bytes,
            "returned_msgs": self.returned_msgs,
            "confirmed_msgs": self.confirmed_msgs,
            "expired_msgs": self.expired_msgs,
            "dead_lettered_msgs": self.dead_lettered_msgs,
            "connections_opened": self.connections_opened,
            "connections_closed": self.connections_closed,
            "connections_refused": self.connections_refused,
            "connections_open": (
                self.connections_opened - self.connections_closed),
            "publish_to_deliver_p50_us": h.percentile_us(0.50),
            "publish_to_deliver_p99_us": h.percentile_us(0.99),
            "publish_to_deliver_mean_us": h.mean_us,
            "repl_events_shipped": self.repl_events_shipped,
            "repl_batches_shipped": self.repl_batches_shipped,
            "repl_events_applied": self.repl_events_applied,
            "repl_resyncs": self.repl_resyncs,
            "repl_promotions": self.repl_promotions,
            "repl_ack_timeouts": self.repl_ack_timeouts,
            "repl_ack_p50_us": self.repl_ack_us.percentile_us(0.50),
            "repl_ack_p99_us": self.repl_ack_us.percentile_us(0.99),
            "repl_ack_mean_us": self.repl_ack_us.mean_us,
            "stream_appends": self.stream_appends,
            "stream_append_bytes": self.stream_append_bytes,
            "stream_segments_sealed": self.stream_segments_sealed,
            "stream_segments_truncated": self.stream_segments_truncated,
            "stream_records_delivered": self.stream_records_delivered,
            "stream_cursor_commits": self.stream_cursor_commits,
            "stream_groups_created": self.stream_groups_created,
            "stream_group_deliveries": self.stream_group_deliveries,
            "rpc_data_bytes_sent": self.rpc_data_bytes_sent,
            "rpc_data_bytes_recv": self.rpc_data_bytes_recv,
            "rpc_push_records": self.rpc_push_records,
            "rpc_push_batches": self.rpc_push_batches,
            "rpc_settle_records": self.rpc_settle_records,
            "rpc_settle_batches": self.rpc_settle_batches,
            "rpc_deliver_records": self.rpc_deliver_records,
            "rpc_deliver_batches": self.rpc_deliver_batches,
            "rpc_flush_window": self.rpc_flush_window,
            "rpc_flush_bytes": self.rpc_flush_bytes,
            "rpc_flush_count": self.rpc_flush_count,
            "rpc_flush_demand": self.rpc_flush_demand,
            "chaos_fires": self.chaos_fires,
            "chaos_latency": self.chaos_latency,
            "chaos_errors": self.chaos_errors,
            "chaos_drops": self.chaos_drops,
            "chaos_disconnects": self.chaos_disconnects,
            "chaos_corrupt_frames": self.chaos_corrupt_frames,
            "chaos_crashes": self.chaos_crashes,
            "chaos_partition_drops": self.chaos_partition_drops,
            "trace_sampled": self.trace_sampled,
            "trace_completed": self.trace_completed,
            "trace_slow": self.trace_slow,
            "trace_chaos_tagged": self.trace_chaos_tagged,
            "trace_ctx_sent": self.trace_ctx_sent,
            "trace_ctx_recv": self.trace_ctx_recv,
            "trace_evicted": self.trace_evicted,
            "otel_forced_samples": self.otel_forced_samples,
            "otel_spans_exported": self.otel_spans_exported,
            "otel_batches_sent": self.otel_batches_sent,
            "otel_export_errors": self.otel_export_errors,
            "otel_spans_shed": self.otel_spans_shed,
            "otel_pull_served": self.otel_pull_served,
            "telemetry_ticks": self.telemetry_ticks,
            "telemetry_saturated_ticks": self.telemetry_saturated_ticks,
            "telemetry_evicted_entities": self.telemetry_evicted_entities,
            "telemetry_dropped_entities": self.telemetry_dropped_entities,
            "shard_cross_pushes": self.shard_cross_pushes,
            "shard_handoffs": self.shard_handoffs,
            "shard_restarts": self.shard_restarts,
            "flow_escalations": self.flow_escalations,
            "flow_deescalations": self.flow_deescalations,
            "flow_paged_bodies": self.flow_paged_bodies,
            "flow_paged_bytes": self.flow_paged_bytes,
            "flow_throttles": self.flow_throttles,
            "flow_resumes": self.flow_resumes,
            "flow_hold_releases": self.flow_hold_releases,
            "flow_hold_wait_ns": self.flow_hold_wait_ns,
            "flow_cluster_stalls": self.flow_cluster_stalls,
            "flow_publishes_refused": self.flow_publishes_refused,
            "flow_slow_consumers": self.flow_slow_consumers,
            "control_ticks": self.control_ticks,
            "control_decisions": self.control_decisions,
            "control_applied": self.control_applied,
            "control_suppressed": self.control_suppressed,
            "control_dry_run": self.control_dry_run,
            "control_errors": self.control_errors,
            "chaos_pressure": self.chaos_pressure,
            "wal_appends": self.wal_appends,
            "wal_append_bytes": self.wal_append_bytes,
            "wal_commits": self.wal_commits,
            "wal_fsyncs": self.wal_fsyncs,
            "wal_commit_errors": self.wal_commit_errors,
            "wal_segments_sealed": self.wal_segments_sealed,
            "wal_segments_truncated": self.wal_segments_truncated,
            "wal_checkpoints": self.wal_checkpoints,
            "wal_checkpoint_errors": self.wal_checkpoint_errors,
            "wal_recovered_records": self.wal_recovered_records,
            "wal_recover_torn": self.wal_recover_torn,
            "wal_recover_corrupt": self.wal_recover_corrupt,
            "wal_tier_offloads": self.wal_tier_offloads,
            "wal_tier_rehydrations": self.wal_tier_rehydrations,
            "wal_compactions": self.wal_compactions,
            "wal_compacted_records": self.wal_compacted_records,
            "wal_memtable_drains": self.wal_memtable_drains,
            "wal_memtable_elided": self.wal_memtable_elided,
            "wal_memtable_hits": self.wal_memtable_hits,
            "wal_tx_batches": self.wal_tx_batches,
            "wal_tx_batch_ops": self.wal_tx_batch_ops,
            "wal_commit_p50_us": self.wal_commit_us.percentile_us(0.50),
            "wal_commit_p99_us": self.wal_commit_us.percentile_us(0.99),
            "wal_commit_mean_us": self.wal_commit_us.mean_us,
            "alerts_fired": self.alerts_fired,
            "alerts_resolved": self.alerts_resolved,
            "lifecycle_drains_started": self.lifecycle_drains_started,
            "lifecycle_queues_evacuated": self.lifecycle_queues_evacuated,
            "lifecycle_evacuation_retries": self.lifecycle_evacuation_retries,
            "lifecycle_rollbacks": self.lifecycle_rollbacks,
            "lifecycle_stale_epoch_refused": self.lifecycle_stale_epoch_refused,
            "lifecycle_join_rebalances": self.lifecycle_join_rebalances,
            "lifecycle_stale_holders_cleared":
                self.lifecycle_stale_holders_cleared,
            "router_batches": self.router_batches,
            "router_batch_msgs": self.router_batch_msgs,
            "router_compiles": self.router_compiles,
            "router_generation": self.router_generation,
            "router_fallback_msgs": self.router_fallback_msgs,
            "router_parity_mismatches": self.router_parity_mismatches,
            "router_batch_size_p50": self.router_batch_size.percentile_us(0.50),
            "router_batch_size_p99": self.router_batch_size.percentile_us(0.99),
            "router_batch_size_mean": self.router_batch_size.mean_us,
            "native_egress_batches": self.native_egress_batches,
            "native_egress_msgs": self.native_egress_msgs,
            "native_egress_bytes": self.native_egress_bytes,
            "native_egress_fallbacks": self.native_egress_fallbacks,
            "native_pool_exhausted": self.native_pool_exhausted,
            "profile_samples_total": self.profile_samples_total,
            "profile_slow_callbacks_total": self.profile_slow_callbacks_total,
            "profile_gc_pauses_total": self.profile_gc_pauses_total,
            "profile_gc_pause_ns_total": self.profile_gc_pause_ns_total,
            "events_published_total": self.events_published_total,
            "events_dropped_total": self.events_dropped_total,
            "firehose_published_total": self.firehose_published_total,
            "firehose_dropped_total": self.firehose_dropped_total,
            "slo_violations_total": self.slo_violations_total,
            "tenancy_throttles_total": self.tenancy_throttles_total,
            "tenancy_resumes_total": self.tenancy_resumes_total,
            "tenancy_quota_refusals_total": self.tenancy_quota_refusals_total,
            "tenancy_acl_denials_total": self.tenancy_acl_denials_total,
            "semantics_tx_commits": self.semantics_tx_commits,
            "semantics_tx_rollbacks": self.semantics_tx_rollbacks,
            "semantics_delayed_msgs": self.semantics_delayed_msgs,
            "semantics_delay_fired": self.semantics_delay_fired,
            "semantics_priority_msgs": self.semantics_priority_msgs,
            "dlx_published": self.dlx_published,
            "dlx_cycle_drops": self.dlx_cycle_drops,
            "dlx_expired": self.dlx_expired,
            "dlx_rejected": self.dlx_rejected,
            "dlx_maxlen": self.dlx_maxlen,
            "federation_segments_shipped": self.federation_segments_shipped,
            "federation_segment_bytes": self.federation_segment_bytes,
            "federation_segments_applied": self.federation_segments_applied,
            "federation_duplicate_segments":
                self.federation_duplicate_segments,
            "federation_crc_failures": self.federation_crc_failures,
            "federation_ship_errors": self.federation_ship_errors,
            "federation_resyncs": self.federation_resyncs,
            "federation_resumes": self.federation_resumes,
            "federation_link_failures": self.federation_link_failures,
            "federation_cursors_shipped": self.federation_cursors_shipped,
            "federation_cursors_mirrored": self.federation_cursors_mirrored,
            "federation_dlx_forwarded": self.federation_dlx_forwarded,
            "federation_tx_batches": self.federation_tx_batches,
            "federation_tx_publishes": self.federation_tx_publishes,
            "federation_tx_applied": self.federation_tx_applied,
            "federation_outbox_dropped": self.federation_outbox_dropped,
            "federation_outbox_dropped_publish":
                self.federation_outbox_dropped_publish,
            "federation_outbox_dropped_tx":
                self.federation_outbox_dropped_tx,
            "federation_duplicate_forwards":
                self.federation_duplicate_forwards,
            "federation_invalid_segments":
                self.federation_invalid_segments,
            "federation_auth_failures": self.federation_auth_failures,
            "lifecycle_left_peer_skipped": self.lifecycle_left_peer_skipped,
        }
        for key, hist in self.trace_stage_us.items():
            base = key[:-3] if key.endswith("_us") else key
            out[f"{base}_p50_us"] = hist.percentile_us(0.50)
            out[f"{base}_p99_us"] = hist.percentile_us(0.99)
            out[f"{base}_mean_us"] = hist.mean_us
        return out
