"""Advanced delivery semantics (chanamq_tpu/semantics/): Tx atomicity on
the WAL commit boundary (one tx_batch frame, all-or-nothing under torn
writes), exchange-to-exchange closure flattening parity against the live
graph walk, delayed delivery via the broker timer wheel, per-message
priority ceiling clamping, TTL precedence, x-death monotonicity on DLX
retry cycles, and the deferred-fused-publish vs mandatory Basic.Return
ordering contract.
"""

import asyncio
import os

import pytest

from chanamq_tpu import events
from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.semantics import TimerWheel, parse_delay
from chanamq_tpu.store.api import StoredMessage
from chanamq_tpu.store.sqlite import SqliteStore
from chanamq_tpu.wal import WalStore
from chanamq_tpu.wal.segment import list_segments

pytestmark = pytest.mark.asyncio


@pytest.fixture
async def server():
    srv = BrokerServer(broker=Broker(message_sweep_interval_s=0.1),
                       host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


async def drain(ch, queue, n, timeout=3.0):
    out = []
    deadline = asyncio.get_event_loop().time() + timeout
    while len(out) < n and asyncio.get_event_loop().time() < deadline:
        msg = await ch.basic_get(queue, no_ack=True)
        if msg is None:
            await asyncio.sleep(0.02)
            continue
        out.append(msg)
    return out


class _BusStub:
    """Stands in for events.ACTIVE: records every emit for assertion."""

    def __init__(self):
        self.emits = []

    def emit(self, key, payload, vhost_name=None):
        self.emits.append((key, payload))

    def keys(self):
        return [k for k, _ in self.emits]


# ---------------------------------------------------------------------------
# Tx atomicity on the WAL commit boundary
# ---------------------------------------------------------------------------


def _wal(db_path: str) -> WalStore:
    return WalStore(SqliteStore(db_path), flush_ms=1.0,
                    checkpoint_ms=3_600_000.0)


def _msg(i: int) -> StoredMessage:
    return StoredMessage(id=i, properties_raw=b"\x01", body=b"body%d" % i,
                         exchange="ex", routing_key="rk", refer_count=1)


async def _crash(store: WalStore) -> None:
    store._commit_task.cancel()
    store._checkpoint_task.cancel()
    store._inner._closed = True
    store._executor.shutdown(wait=True)
    store._inner._executor.shutdown(wait=False)


def _wipe_index(db_path: str) -> None:
    import sqlite3
    db = sqlite3.connect(db_path)
    db.execute("DELETE FROM msgs")
    db.commit()
    db.close()


async def test_tx_batch_torn_frame_drops_whole_transaction(tmp_path):
    """SIGKILL mid-commit: a transaction is ONE tx_batch frame, so a torn
    tail drops every op in it — never a prefix. The pre-tx record written
    outside the scope survives untouched."""
    db_path = str(tmp_path / "torn.db")
    s = _wal(db_path)
    await s.open()
    lo = s.mark()
    s.insert_message_nowait(_msg(0))          # outside any tx
    s.tx_begin()
    for i in range(1, 4):
        s.insert_message_nowait(_msg(i))      # diverted into the tx scope
    lsn = s.tx_seal()
    await s.flush([(lo, lsn)])
    assert s.metrics.wal_tx_batches == 1
    assert s.metrics.wal_tx_batch_ops == 3
    await _crash(s)

    # tear the tail: the tx_batch frame was written last, so a short
    # truncation lands inside it and its CRC cannot verify
    segs = list_segments(s.dir)
    with open(segs[-1][1], "r+b") as f:
        f.truncate(f.seek(0, os.SEEK_END) - 3)
    _wipe_index(db_path)

    s2 = _wal(db_path)
    await s2.open()
    got = await s2.select_messages([0, 1, 2, 3])
    assert sorted(got) == [0]  # all-or-nothing: the whole tx vanished
    await s2.close()


async def test_tx_batch_intact_replays_every_op(tmp_path):
    """The durable case of the same boundary: an intact tx_batch frame
    replays every sub-op (publishes AND settles) on recovery."""
    db_path = str(tmp_path / "intact.db")
    s = _wal(db_path)
    await s.open()
    lo = s.mark()
    s.insert_message_nowait(_msg(0))
    s.tx_begin()
    for i in range(1, 4):
        s.insert_message_nowait(_msg(i))
    lsn = s.tx_seal()
    await s.flush([(lo, lsn)])
    await _crash(s)
    _wipe_index(db_path)

    s2 = _wal(db_path)
    await s2.open()
    got = await s2.select_messages([0, 1, 2, 3])
    assert sorted(got) == [0, 1, 2, 3]
    await s2.close()


async def test_tx_commit_is_atomic_across_restart(tmp_path):
    """End-to-end kill between Tx.Commit receipt and WAL commit: a
    restarted broker sees either every publish in the tx or none — here
    the committed tx (3 publishes + 1 ack) lands whole."""
    db_path = str(tmp_path / "tx_e2e.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("txa", durable=True)
    persistent = BasicProperties(delivery_mode=2)
    ch.basic_publish(b"seed", routing_key="txa", properties=persistent)
    msg = await ch.basic_get("txa")
    await ch.tx_select()
    for i in range(3):
        ch.basic_publish(b"tx%d" % i, routing_key="txa", properties=persistent)
    ch.basic_ack(msg.delivery_tag)
    await ch.tx_commit()
    await c.close()
    await srv.stop()

    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db_path))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("txa", durable=True, passive=True)
        assert ok.message_count == 3  # seed acked in-tx, 3 tx publishes kept
        bodies = [(await ch2.basic_get("txa", no_ack=True)).body
                  for _ in range(3)]
        assert bodies == [b"tx0", b"tx1", b"tx2"]
        await c2.close()
    finally:
        await srv2.stop()


async def test_tx_commit_and_rollback_emit_events(client):
    ch = await client.channel()
    await ch.queue_declare("txe")
    await ch.tx_select()
    stub = _BusStub()
    events.ACTIVE = stub
    try:
        ch.basic_publish(b"m", routing_key="txe")
        await ch.tx_commit()
        ch.basic_publish(b"m2", routing_key="txe")
        await ch.tx_rollback()
    finally:
        events.ACTIVE = None
    keys = stub.keys()
    assert "tx.committed" in keys and "tx.rolledback" in keys
    committed = dict(stub.emits)["tx.committed"]
    # transient store: no WAL scope, so the commit reports atomic=False
    # (the WAL-backed atomic path is covered by the restart tests above)
    assert committed["ops"] == 1 and committed["atomic"] is False


# ---------------------------------------------------------------------------
# exchange->exchange closure parity
# ---------------------------------------------------------------------------


async def test_e2e_chain_closure_matches_graph_walk():
    """3-deep bound-exchange chain: the flattened TensorRouter closure
    routes every key to exactly the set the live graph walk produces —
    verified by the router's own parity oracle (zero mismatches)."""
    broker = Broker()
    await broker.create_vhost("/")
    for name, kind in [("root", "fanout"), ("mid", "topic"),
                       ("leaf", "direct")]:
        await broker.declare_exchange("/", name, kind)
    for q in ("q_root", "q_mid", "q_leaf"):
        await broker.declare_queue("/", q)
    await broker.bind_queue("/", "q_root", "root", "")
    await broker.bind_queue("/", "q_mid", "mid", "a.*")
    await broker.bind_queue("/", "q_leaf", "leaf", "a.b")
    await broker.bind_exchange("/", "mid", "root", "")     # fanout hop
    await broker.bind_exchange("/", "leaf", "mid", "a.#")  # wildcard hop
    vhost = broker.vhost("/")
    router = broker.router
    router.min_batch = 1
    router.verify = True
    assert router.defer_ok("/", "root")  # the closure compiled
    props = BasicProperties()
    keys = ("a.b", "a.x", "b.c", "", "a.b.c", "a")
    entries = [("root", k, props, b"x", None, None, False) for k in keys]
    routes, _, _ = router.route_pending("/", entries)
    for k, qs in zip(keys, routes):
        assert {q.name for q in qs} == vhost.route("root", k, None)
    assert broker.metrics.router_parity_mismatches == 0
    assert broker.metrics.router_batches >= 1  # kernel path, not fallback

    # incremental recompile: unbinding a member invalidates the root's
    # snapshot through the closure dependency map
    await broker.unbind_exchange("/", "leaf", "mid", "a.#")
    routes, _, _ = router.route_pending(
        "/", [("root", "a.b", props, b"x", None, None, False)])
    assert ({q.name for q in routes[0]}
            == vhost.route("root", "a.b", None) == {"q_root", "q_mid"})
    assert broker.metrics.router_parity_mismatches == 0


async def test_e2e_uncompilable_closure_stays_on_walk():
    """Wildcard-over-wildcard cannot flatten: the root is not deferrable
    and per-message routing still matches the walk."""
    broker = Broker()
    await broker.create_vhost("/")
    await broker.declare_exchange("/", "src", "topic")
    await broker.declare_exchange("/", "dst", "topic")
    await broker.declare_queue("/", "q")
    await broker.bind_queue("/", "q", "dst", "a.*")
    await broker.bind_exchange("/", "dst", "src", "a.#")
    assert not broker.router.defer_ok("/", "src")
    vhost = broker.vhost("/")
    # the walk still routes correctly: both hops must match the ORIGINAL key
    assert vhost.route("src", "a.b", None) == {"q"}
    assert vhost.route("src", "a.b.c", None) == set()  # a.# yes, a.* no


# ---------------------------------------------------------------------------
# delayed delivery
# ---------------------------------------------------------------------------


def test_parse_delay_rejects_junk():
    assert parse_delay(None) is None
    assert parse_delay({}) is None
    assert parse_delay({"x-delay": 0}) is None
    assert parse_delay({"x-delay": -5}) is None
    assert parse_delay({"x-delay": True}) is None
    assert parse_delay({"x-delay": "100"}) is None
    assert parse_delay({"x-delay": 100}) == 100
    assert parse_delay({"x-delay": 1 << 40}) == (1 << 32) - 1  # clamped


def test_timer_wheel_multi_turn_entries():
    w = TimerWheel(tick_ms=10, slots=4)
    w.schedule(10, "near")    # due tick 1
    w.schedule(50, "far")     # due tick 5 -> same slot as tick 1
    assert len(w) == 2
    assert w.advance(1) == ["near"]   # the far entry stays for its turn
    assert len(w) == 1
    assert w.advance(3) == []
    assert w.advance(1) == ["far"]
    assert len(w) == 0


async def test_delayed_publish_parks_then_delivers(client):
    ch = await client.channel()
    await ch.queue_declare("dq")
    ch.basic_publish(b"later", routing_key="dq",
                     properties=BasicProperties(headers={"x-delay": 120}))
    ok = await ch.queue_declare("dq", passive=True)
    assert ok.message_count == 0  # parked, not enqueued
    got = await drain(ch, "dq", 1)
    assert [m.body for m in got] == [b"later"]
    # the header is stripped before fire so consumers never see x-delay
    assert (got[0].properties.headers or {}).get("x-delay") is None


async def test_delayed_message_outlives_queue_delete(server, client):
    """Routing happens at fire time: if the target queue is deleted while
    the message is parked, the fire routes against current topology —
    here it drops unroutably without disturbing the broker."""
    ch = await client.channel()
    await ch.queue_declare("ghost")
    ch.basic_publish(b"orphan", routing_key="ghost",
                     properties=BasicProperties(headers={"x-delay": 150}))
    await ch.queue_delete("ghost")
    broker = server.broker
    fired = broker.metrics.semantics_delay_fired
    deadline = asyncio.get_event_loop().time() + 3.0
    while (broker.metrics.semantics_delay_fired == fired
           and asyncio.get_event_loop().time() < deadline):
        await asyncio.sleep(0.02)
    assert broker.metrics.semantics_delay_fired == fired + 1
    assert len(broker.delay.wheel) == 0
    # parked-memory accounting fully released
    # broker stays healthy: a fresh queue round-trips
    await ch.queue_declare("ghost")
    ch.basic_publish(b"alive", routing_key="ghost")
    got = await drain(ch, "ghost", 1)
    assert [m.body for m in got] == [b"alive"]


async def test_delayed_publish_accounts_memory_while_parked(server, client):
    broker = server.broker
    ch = await client.channel()
    await ch.queue_declare("dmem")
    before = broker.resident_bytes
    body = b"z" * 4096
    ch.basic_publish(body, routing_key="dmem",
                     properties=BasicProperties(headers={"x-delay": 200}))
    ok = await ch.queue_declare("dmem", passive=True)
    assert ok.message_count == 0
    assert broker.resident_bytes >= before + len(body)
    got = await drain(ch, "dmem", 1)
    assert got[0].body == body


async def test_semantics_disabled_routes_x_delay_immediately():
    broker = Broker(semantics_enabled=False)
    await broker.create_vhost("/")
    await broker.declare_queue("/", "q")
    assert broker.delay is None
    routed, _ = broker.publish_sync(
        "/", "", "q", BasicProperties(headers={"x-delay": 60_000}), b"now")
    assert routed
    assert broker.vhost("/").queues["q"].message_count == 1  # no parking


# ---------------------------------------------------------------------------
# priority ceiling + TTL precedence + x-death monotonicity
# ---------------------------------------------------------------------------


async def test_priority_ceiling_clamps_not_errors(client):
    """priority > x-max-priority clamps to the ceiling (RabbitMQ rule):
    a 255-priority publish on a max-4 queue ranks equal to priority 4 and
    FIFO order breaks the tie."""
    ch = await client.channel()
    await ch.queue_declare("pq", arguments={"x-max-priority": 4})
    ch.basic_publish(b"low", routing_key="pq",
                     properties=BasicProperties(priority=1))
    ch.basic_publish(b"at-max", routing_key="pq",
                     properties=BasicProperties(priority=4))
    ch.basic_publish(b"clamped", routing_key="pq",
                     properties=BasicProperties(priority=255))
    got = await drain(ch, "pq", 3)
    # clamped (255->4) ties with at-max: FIFO within the band
    assert [m.body for m in got] == [b"at-max", b"clamped", b"low"]


async def test_per_message_ttl_beats_longer_queue_ttl(client):
    """Effective TTL is min(per-message, per-queue): a short expiration on
    a long-TTL queue expires fast; a long expiration on a short-TTL queue
    is bounded by the queue."""
    ch = await client.channel()
    await ch.exchange_declare("dlx_ttl", "fanout")
    await ch.queue_declare("dlq_ttl")
    await ch.queue_bind("dlq_ttl", "dlx_ttl", "")
    # long queue TTL, short message TTL
    await ch.queue_declare("ttl_a", arguments={
        "x-message-ttl": 60_000, "x-dead-letter-exchange": "dlx_ttl"})
    ch.basic_publish(b"msg-short", routing_key="ttl_a",
                     properties=BasicProperties(expiration="60"))
    got = await drain(ch, "dlq_ttl", 1)
    assert got[0].body == b"msg-short"
    assert got[0].properties.headers["x-death"][0]["reason"] == "expired"
    # short queue TTL, long message TTL
    await ch.queue_declare("ttl_b", arguments={
        "x-message-ttl": 60, "x-dead-letter-exchange": "dlx_ttl"})
    ch.basic_publish(b"queue-short", routing_key="ttl_b",
                     properties=BasicProperties(expiration="60000"))
    got = await drain(ch, "dlq_ttl", 1)
    assert got[0].body == b"queue-short"


async def test_x_death_count_monotonic_on_dlx_cycle(client):
    """Reject-driven DLX retry ring (work -> dlx -> work): the x-death
    count for (work, rejected) increments 1, 2, 3 — strictly monotonic,
    one increment per death, exactly-once per cycle."""
    ch = await client.channel()
    await ch.exchange_declare("retry_dlx", "fanout")
    await ch.queue_declare("work", arguments={
        "x-dead-letter-exchange": "retry_dlx"})
    await ch.queue_bind("work", "retry_dlx", "")
    ch.basic_publish(b"poison", routing_key="work")
    counts = []
    for expect in (1, 2, 3):
        msg = None
        deadline = asyncio.get_event_loop().time() + 3.0
        while msg is None and asyncio.get_event_loop().time() < deadline:
            msg = await ch.basic_get("work")
            if msg is None:
                await asyncio.sleep(0.02)
        assert msg is not None
        deaths = (msg.properties.headers or {}).get("x-death")
        if deaths is not None:
            entry = next(d for d in deaths
                         if d["queue"] == "work" and d["reason"] == "rejected")
            counts.append(entry["count"])
        ch.basic_reject(msg.delivery_tag, requeue=False)
    # after 3 rejects the message cycled 3 times; counts observed on
    # fetch are the deaths so far: [1, 2] (first fetch has no x-death yet)
    assert counts == [1, 2]
    msg = None
    deadline = asyncio.get_event_loop().time() + 3.0
    while msg is None and asyncio.get_event_loop().time() < deadline:
        msg = await ch.basic_get("work", no_ack=True)
        if msg is None:
            await asyncio.sleep(0.02)
    entry = next(d for d in msg.properties.headers["x-death"]
                 if d["queue"] == "work" and d["reason"] == "rejected")
    assert entry["count"] == 3


async def test_dead_letter_emits_event_and_metrics(server, client):
    broker = server.broker
    ch = await client.channel()
    await ch.exchange_declare("dlx_ev", "fanout")
    await ch.queue_declare("dlq_ev")
    await ch.queue_bind("dlq_ev", "dlx_ev", "")
    await ch.queue_declare("src_ev", arguments={
        "x-dead-letter-exchange": "dlx_ev"})
    ch.basic_publish(b"m", routing_key="src_ev")
    msg = await ch.basic_get("src_ev")
    stub = _BusStub()
    events.ACTIVE = stub
    before = broker.metrics.dlx_rejected
    try:
        ch.basic_reject(msg.delivery_tag, requeue=False)
        got = await drain(ch, "dlq_ev", 1)
    finally:
        events.ACTIVE = None
    assert got[0].body == b"m"
    assert broker.metrics.dlx_rejected == before + 1
    assert broker.metrics.dlx_published >= 1
    payload = dict(stub.emits)["message.dead_lettered"]
    assert payload["reason"] == "rejected" and payload["queue"] == "src_ev"


# ---------------------------------------------------------------------------
# deferred fused publish vs mandatory Basic.Return ordering
# ---------------------------------------------------------------------------


async def test_mandatory_return_does_not_overtake_deferred_batch(client):
    """Fused publishes may sit in the deferred route batch; a mandatory
    publish takes the generic path, which must flush that batch FIRST —
    so the Return renders after earlier publishes landed, and a routed
    mandatory publish keeps FIFO position behind them."""
    ch = await client.channel()
    await ch.queue_declare("ordq")
    # these are fused-path candidates (no mandatory bit)
    ch.basic_publish(b"one", routing_key="ordq")
    ch.basic_publish(b"two", routing_key="ordq")
    # mandatory + unroutable: generic path, must flush the batch first
    ch.basic_publish(b"void", routing_key="no.such.queue", mandatory=True)
    # mandatory + routed: lands strictly after one/two
    ch.basic_publish(b"three", routing_key="ordq", mandatory=True)
    deadline = asyncio.get_event_loop().time() + 3.0
    while not ch.returns and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.02)
    assert len(ch.returns) == 1
    assert ch.returns[0].reply_code == 312  # NO_ROUTE
    ok = await ch.queue_declare("ordq", passive=True)
    assert ok.message_count == 3  # the deferred pair was not lost
    got = await drain(ch, "ordq", 3)
    assert [m.body for m in got] == [b"one", b"two", b"three"]


# ---------------------------------------------------------------------------
# cycle refusal keeps admin surface consistent
# ---------------------------------------------------------------------------


async def test_cycle_refusal_emits_event(server, client):
    ch = await client.channel()
    await ch.exchange_declare("ca", "fanout")
    await ch.exchange_declare("cb", "fanout")
    await ch.exchange_bind("cb", "ca", "")
    stub = _BusStub()
    events.ACTIVE = stub
    try:
        with pytest.raises(ChannelClosedError) as exc:
            await ch.exchange_bind("ca", "cb", "")
        assert "406" in str(exc.value)
    finally:
        events.ACTIVE = None
    payload = dict(stub.emits)["exchange.cycle_refused"]
    assert payload["source"] == "cb" and payload["destination"] == "ca"
