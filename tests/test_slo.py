"""SLO engine: burn-rate math vs hand-computed oracles, window pairs,
budgets, spec parsing, the SLI sampler's counter deltas, determinism, and
the /admin/slo surface (configure + status + Prometheus series).

The engine contract under test is the AlertEngine/ControlEngine one:
``evaluate(tick, samples)`` is a pure function of the per-tick (good, bad)
streams, so every assertion here is exact — no tolerances beyond float
rounding.
"""

import asyncio
import json

import pytest

from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.slo import (
    SLISampler, SLOEngine, SLOSpec, default_slos, specs_from_json,
)
from chanamq_tpu.slo.engine import COARSE, FINE
from chanamq_tpu.telemetry import TelemetryService
from chanamq_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.asyncio


async def http_req(port: int, path: str, method: str = "GET",
                   body: dict = None) -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 20), 5)
    writer.close()
    head, _, resp = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(resp) if resp else {}


async def http_text(port: int, path: str) -> tuple[int, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 22), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), body.decode()


# ---------------------------------------------------------------------------
# burn-rate math vs hand-computed oracle
# ---------------------------------------------------------------------------


def _spec(**kw) -> SLOSpec:
    base = dict(name="t", sli="publish-success", objective=0.99,
                fast_windows=(4, 8), slow_windows=(8, 16),
                fast_burn=10.0, slow_burn=5.0, budget_window=16)
    base.update(kw)
    return SLOSpec(**base)


def test_burn_rate_static_oracle():
    # burn = (bad/total) / (1 - objective), by hand:
    # 5 bad of 100 at objective 0.99 -> 0.05 / 0.01 = 5.0
    assert SLOEngine.burn_rate(95, 5, 0.99) == pytest.approx(5.0)
    # burning exactly at budget rate: bad fraction == error budget
    assert SLOEngine.burn_rate(999, 1, 0.999) == pytest.approx(1.0)
    # no traffic is not a burn
    assert SLOEngine.burn_rate(0, 0, 0.999) == 0.0
    # total loss at 0.999: 1.0 / 0.001 = 1000
    assert SLOEngine.burn_rate(0, 7, 0.999) == pytest.approx(1000.0)


def test_window_burns_vs_oracle_across_pairs():
    """Feed a known per-tick series and check every window's burn against
    a sum computed by hand here (oracle = trailing-window sums)."""
    spec = _spec()
    engine = SLOEngine([spec])
    series = [(10, 0), (10, 0), (8, 2), (10, 0), (5, 5),
              (10, 0), (10, 0), (9, 1), (10, 0), (10, 0)]
    for tick, (good, bad) in enumerate(series, start=1):
        engine.evaluate(tick, {"publish-success": (good, bad)})

    status = engine.slo_status(spec)

    def oracle(window: int) -> float:
        tail = series[-window:]
        good = sum(g for g, _ in tail)
        bad = sum(b for _, b in tail)
        return (bad / (good + bad)) / (1 - spec.objective)

    assert status["burn"]["fast_short"]["burn_rate"] == pytest.approx(
        oracle(4), abs=1e-4)    # last 4 ticks: 1 bad / 39 -> 2.5641
    assert status["burn"]["fast_long"]["burn_rate"] == pytest.approx(
        oracle(8), abs=1e-4)    # last 8 ticks: 6 bad / 74+6
    assert status["burn"]["slow_short"]["burn_rate"] == pytest.approx(
        oracle(8), abs=1e-4)
    assert status["burn"]["slow_long"]["burn_rate"] == pytest.approx(
        oracle(10), abs=1e-4)   # 16-tick window clipped to the 10 fed
    # and the numbers are really different across windows (the test would
    # be vacuous if every window degenerated to the same total)
    assert (status["burn"]["fast_short"]["burn_rate"]
            != status["burn"]["fast_long"]["burn_rate"])


def test_multi_window_pair_fires_and_clears():
    """A pair fires only when BOTH windows burn over threshold, and
    clears when the short window recovers (long may still be hot)."""
    spec = _spec(fast_windows=(2, 6), fast_burn=10.0,
                 slow_windows=(6, 12), slow_burn=1e9)  # slow pair inert
    engine = SLOEngine([spec])
    events = []
    # ticks 1-2 clean, 3-4 total loss, 5+ clean again
    series = [(10, 0), (10, 0), (0, 10), (0, 10),
              (10, 0), (10, 0), (10, 0), (10, 0)]
    for tick, sample in enumerate(series, start=1):
        events.extend(engine.evaluate(
            tick, {"publish-success": sample}))

    burns = [e for e in events if e["event"] == "burn"]
    clears = [e for e in events if e["event"] == "clear"]
    assert len(burns) == 1 and len(clears) == 1
    # short window (2) is pure loss at tick 4 -> burn 100; long window (6)
    # at tick 3 is 10/30 err -> 33.3 > 10, so both windows agree at tick 3
    # already: short at tick 3 = 10/20 -> 50 > 10. Fire tick 3.
    assert burns[0]["since_tick"] == 3
    assert burns[0]["pair"] == "fast"
    # clears once the short window is clean: at tick 6 the last 2 ticks
    # are (10,0),(10,0) -> burn 0 <= 10 (tick 5's short still holds tick 4
    # loss: 10/20 -> 50, stays firing)
    assert clears[0]["cleared_tick"] == 6
    assert engine.fired_total == 1 and engine.cleared_total == 1
    assert engine.violations[spec.name] == 1
    assert not engine.firing


def test_budget_remaining_oracle():
    spec = _spec(objective=0.9, budget_window=10)
    engine = SLOEngine([spec])
    # 100 events, 5 bad; allowed = (1 - 0.9) * 100 = 10 -> 50% left
    for tick in range(1, 6):
        engine.evaluate(tick, {"publish-success": (19, 1)})
    assert engine.budget_remaining(spec) == pytest.approx(0.5)
    # no traffic at all = untouched budget
    fresh = SLOEngine([_spec()])
    fresh.evaluate(1, {})
    assert fresh.budget_remaining(fresh.specs[0]) == 1.0


def test_coarse_ring_beyond_fine_horizon():
    """Windows larger than the fine ring fall back to the coarse ring,
    quantized to its stride — deterministically, not approximately."""
    spec = _spec(fast_windows=(4, 8), slow_windows=(8, 16),
                 budget_window=FINE + 4 * COARSE)
    engine = SLOEngine([spec])
    ticks = FINE + 2 * COARSE
    for tick in range(1, ticks + 1):
        engine.evaluate(tick, {"publish-success": (1.0, 1.0)})
    track = engine._tracks[spec.name]
    window = FINE + COARSE  # beyond the fine horizon
    good, bad = track.window(ticks, window)
    # quantization error is bounded by one coarse stride, and good == bad
    # throughout so the split must be exact
    assert good == bad
    assert abs(good - window) <= COARSE
    # the same call is bit-stable (pure function of pushed state)
    assert track.window(ticks, window) == (good, bad)


def test_evaluate_is_deterministic_across_runs():
    """Two engines fed the same series emit identical event lists — the
    two-same-seed-soaks bar, without the soak."""
    series = [
        {"publish-success": (10, 0), "readiness": (1, 0)},
        {"publish-success": (0, 10), "readiness": (0, 1)},
        {"publish-success": (0, 10), "readiness": (0, 1)},
        {"publish-success": (10, 0), "readiness": (1, 0)},
        {"publish-success": (10, 0), "readiness": (1, 0)},
    ] * 3

    def run() -> list:
        engine = SLOEngine([
            _spec(name="pub", fast_windows=(2, 4), fast_burn=5.0,
                  slow_windows=(4, 8), slow_burn=5.0),
            _spec(name="ready", sli="readiness", fast_windows=(2, 4),
                  fast_burn=5.0, slow_windows=(4, 8), slow_burn=5.0),
        ])
        out = []
        for tick, sample in enumerate(series, start=1):
            out.extend(engine.evaluate(tick, sample))
        return out

    first, second = run(), run()
    assert first == second
    assert any(e["event"] == "burn" for e in first)


# ---------------------------------------------------------------------------
# spec parsing + defaults
# ---------------------------------------------------------------------------


def test_default_slos_scale_with_interval():
    specs = default_slos(0.5)
    by_name = {s.name: s for s in specs}
    assert set(by_name) == {"publish-availability", "delivery-success",
                            "readiness", "delivery-latency-p99"}
    # 5 m / 1 h at 0.5 s ticks
    assert by_name["readiness"].fast_windows == (600, 7200)
    assert by_name["readiness"].slow_windows == (43200, 518400)


def test_specs_from_json_seconds_and_validation():
    specs = specs_from_json([{
        "name": "pub", "sli": "publish-success", "objective": 0.95,
        "fast_windows_s": [10, 60], "slow_windows_s": [60, 300],
        "budget_window_s": 300,
    }], interval_s=2.0)
    assert specs[0].fast_windows == (5, 30)
    assert specs[0].budget_window == 150
    with pytest.raises(ValueError):
        specs_from_json([{"name": "x", "sli": "nope"}])
    with pytest.raises(ValueError):
        specs_from_json([{"name": "x", "objective": 1.5}])
    with pytest.raises(ValueError):  # short > long
        specs_from_json([{"name": "x", "fast_windows": [10, 2]}])
    with pytest.raises(ValueError):  # nameless
        specs_from_json([{}])
    with pytest.raises(ValueError):  # duplicate names refuse at the engine
        SLOEngine([_spec(), _spec()])


# ---------------------------------------------------------------------------
# SLI sampler: counter deltas, not absolutes
# ---------------------------------------------------------------------------


class _FakeBroker:
    def __init__(self):
        self.metrics = Metrics()


def test_sli_sampler_deltas():
    broker = _FakeBroker()
    sampler = SLISampler(broker, 250.0)
    m = broker.metrics
    m.published_msgs = 100
    m.delivered_msgs = 50
    # first sample establishes the baseline: deltas are zero
    s0 = sampler.sample(ready=True)
    assert s0["publish-success"] == (0.0, 0.0)
    assert s0["readiness"] == (1.0, 0.0)
    m.published_msgs += 30
    m.flow_publishes_refused += 2
    m.delivered_msgs += 10
    m.dead_lettered_msgs += 1
    s1 = sampler.sample(ready=False)
    assert s1["publish-success"] == (30.0, 2.0)
    assert s1["delivery-success"] == (10.0, 1.0)
    assert s1["readiness"] == (0.0, 1.0)
    # no latency observations yet -> no latency sample
    assert s1["delivery-latency"] == (0.0, 0.0)


def test_sli_sampler_latency_delta_buckets():
    broker = _FakeBroker()
    sampler = SLISampler(broker, latency_threshold_ms=1.0)  # 1000 us
    hist = broker.metrics.publish_to_deliver_us
    sampler.sample(ready=True)  # baseline buckets
    for _ in range(100):
        hist.observe_us(100)  # all fast
    assert sampler.sample(True)["delivery-latency"] == (1.0, 0.0)
    for _ in range(100):
        hist.observe_us(50_000)  # this tick is slow...
    assert sampler.sample(True)["delivery-latency"] == (0.0, 1.0)
    for _ in range(100):
        hist.observe_us(100)  # ...but the next recovers: deltas, not totals
    assert sampler.sample(True)["delivery-latency"] == (1.0, 0.0)


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------


async def test_admin_slo_surface_and_prometheus():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        # SLO disabled: a stable 409, not a 500
        svc = TelemetryService(server.broker, interval_s=1.0)
        server.broker.telemetry = svc
        status, body = await http_req(admin.bound_port, "/admin/slo")
        assert status == 409 and "slo disabled" in body["error"]

        # configure with an explicit spec set
        status, body = await http_req(
            admin.bound_port, "/admin/slo/configure", "POST",
            {"specs": [{"name": "ready", "sli": "readiness",
                        "objective": 0.99, "fast_windows": [2, 4],
                        "slow_windows": [4, 8], "budget_window": 16}]})
        assert status == 200 and body["slos"] == ["ready"]

        # drive deterministic ticks: 3 not-ready in a row burns
        svc.health_state = "ready"
        for _ in range(3):
            svc.slo.evaluate(svc.slo.tick + 1,
                             {"readiness": (0.0, 1.0)})
        status, body = await http_req(
            admin.bound_port, "/admin/slo?scope=local")
        assert status == 200
        ready = body["slos"][0]
        assert ready["name"] == "ready"
        assert ready["budget_remaining"] < 0  # pure loss overspends
        assert ready["burning"] == ["fast", "slow"]
        assert body["fired_total"] == 2

        # bad spec: stable 400
        status, body = await http_req(
            admin.bound_port, "/admin/slo/configure", "POST",
            {"specs": [{"name": "x", "sli": "nope"}]})
        assert status == 400

        # empty body restores the defaults
        status, body = await http_req(
            admin.bound_port, "/admin/slo/configure", "POST", {})
        assert status == 200 and len(body["slos"]) == 4

        # Prometheus series are present per SLO
        status, text = await http_text(admin.bound_port, "/metrics")
        assert status == 200
        assert "chanamq_slo_violations_total" in text
        assert 'chanamq_slo_budget_remaining{slo="readiness"' in text
        assert 'window="fast"' in text and 'window="slow"' in text

        # the readiness payload carries the SLO stamp
        status, body = await http_req(admin.bound_port, "/admin/health")
        assert body["slo"] == {"burning": [], "budget_remaining": {
            s.name: 1.0 for s in svc.slo.specs}}
    finally:
        await admin.stop()
        await server.stop()


async def test_telemetry_tick_drives_slo_and_emits(caplog):
    """sample_tick runs the SLI sampler + engine when an SLO engine is
    installed; the burn bumps slo_violations_total."""
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    try:
        broker = server.broker
        svc = TelemetryService(broker, interval_s=1.0)
        broker.telemetry = svc
        svc.set_slo(SLOEngine([
            SLOSpec("ready", "readiness", objective=0.999,
                    fast_windows=(2, 3), slow_windows=(3, 6),
                    fast_burn=10.0, slow_burn=10.0, budget_window=8),
        ]))
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("slo-q")
        ch.basic_publish(b"x", routing_key="slo-q")
        await asyncio.sleep(0.05)

        # healthy ticks: no violation
        svc.sample_tick(1.0)
        assert broker.metrics.slo_violations_total == 0
        # force not-ready ticks by draining the broker
        broker.draining = True
        for _ in range(3):
            svc.sample_tick(1.0)
        assert broker.metrics.slo_violations_total >= 1
        assert svc.slo.fired_total >= 1
        broker.draining = False
        await c.close()
    finally:
        await server.stop()
