"""AMQP frame model and incremental frame parser.

Capability parity with the reference's Frame model and streaming parser
(chana-mq-base .../model/Frame.scala:38-216,
 .../engine/FrameParser.scala:67-158): a frame is
type(1) channel(2) size(4) payload(size) end(0xCE); the parser is an
incremental push parser that accepts arbitrary byte chunks and yields complete
frames, enforcing the negotiated frame-max and yielding protocol errors
instead of raising mid-stream.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator

from .constants import (
    FRAME_END,
    FRAME_HEADER_SIZE,
    FrameType,
    ErrorCode,
)

_HEADER_STRUCT = struct.Struct(">BHI")

# Packed egress record meta, 33 bytes little-endian, shared between the
# broker's egress buffer and chana_encode_deliveries_packed (which memcpy's
# the fields, so no alignment requirement):
#   int32 channel | uint64 tag | uint8 redelivered |
#   int32 prefix_len | int32 exrk_len | int32 header_len | int64 body_len
# followed in the blob by prefix || exrk || header || body.
ENC_META = struct.Struct("<iQBiiiq")


@dataclass(frozen=True, slots=True)
class Frame:
    type: int
    channel: int
    payload: bytes

    def to_bytes(self) -> bytes:
        # join, not +: payload may be a memoryview (cluster data-plane
        # bodies are zero-copy slices of the peer's read buffer)
        return b"".join((
            _HEADER_STRUCT.pack(self.type, self.channel, len(self.payload)),
            self.payload,
            b"\xce",
        ))

    @staticmethod
    def method(channel: int, payload: bytes) -> "Frame":
        return Frame(FrameType.METHOD, channel, payload)

    @staticmethod
    def header(channel: int, payload: bytes) -> "Frame":
        return Frame(FrameType.HEADER, channel, payload)

    @staticmethod
    def body(channel: int, payload: bytes) -> "Frame":
        return Frame(FrameType.BODY, channel, payload)


HEARTBEAT_FRAME = Frame(FrameType.HEARTBEAT, 0, b"")
HEARTBEAT_BYTES = HEARTBEAT_FRAME.to_bytes()


def deliveries_wire_size(records: list, frame_max: int) -> int:
    """Exact wire size of encode_deliveries(records, frame_max)."""
    max_payload = frame_max - FRAME_HEADER_SIZE - 1 if frame_max else 0
    total = 0
    for _cid, prefix, _tag, _red, exrk, header, body in records:
        total += 16 + len(prefix) + 9 + len(exrk) + len(header)
        blen = len(body)
        if blen:
            chunks = -(-blen // max_payload) if frame_max else 1
            total += blen + 8 * chunks
    return total


def encode_deliveries(records: list, frame_max: int) -> bytes:
    """Pure-Python reference for chana_encode_deliveries: render a batch of
    ``(channel_id, prefix, tag, redelivered, exrk, header, body)`` delivery
    records (prefix = the basic.deliver method payload up to the delivery
    tag, exrk = length-prefixed exchange + routing-key, header = encoded
    content-header payload) into one contiguous wire buffer. Body frames
    split at frame_max - 8; frame_max 0 means no splitting. Used as the
    egress fallback when the native encoder is unavailable, and as the
    parity oracle in tests (byte-identical output is a test invariant)."""
    pack = _HEADER_STRUCT.pack
    parts: list = []
    for cid, prefix, tag, redelivered, exrk, header, body in records:
        method_payload = b"".join((
            prefix, tag.to_bytes(8, "big"),
            b"\x01" if redelivered else b"\x00", exrk))
        parts += (
            pack(1, cid, len(method_payload)), method_payload, b"\xce",
            pack(2, cid, len(header)), header, b"\xce",
        )
        if body:
            max_payload = (frame_max - FRAME_HEADER_SIZE - 1) if frame_max \
                else len(body)
            if len(body) <= max_payload:
                parts += (pack(3, cid, len(body)), body, b"\xce")
            else:
                for off in range(0, len(body), max_payload):
                    chunk = body[off:off + max_payload]
                    parts += (pack(3, cid, len(chunk)), chunk, b"\xce")
    return b"".join(parts)


@dataclass(frozen=True, slots=True)
class FrameError:
    """A protocol-level framing error to be reported via Connection.Close."""

    code: ErrorCode
    message: str


class FrameParser:
    """Incremental frame parser.

    Feed byte chunks with :meth:`feed`; it yields `Frame` or `FrameError`
    items. After a `FrameError` the parser stops consuming (the connection is
    expected to close).
    """

    __slots__ = ("frame_max", "_buf", "_dead")

    def __init__(self, frame_max: int = 0) -> None:
        # frame_max == 0 means "not yet negotiated": accept any size.
        self.frame_max = frame_max
        self._buf = bytearray()
        self._dead = False

    def feed(self, data: bytes) -> Iterator[Frame | FrameError]:
        if self._dead:
            return
        buf = self._buf
        buf += data
        offset = 0
        n = len(buf)
        while n - offset >= FRAME_HEADER_SIZE:
            ftype, channel, size = _HEADER_STRUCT.unpack_from(buf, offset)
            # Validate the type from the header alone: a corrupt stream would
            # otherwise make us buffer up to a bogus 4-byte size field.
            if ftype not in (
                FrameType.METHOD,
                FrameType.HEADER,
                FrameType.BODY,
                FrameType.HEARTBEAT,
            ):
                self._dead = True
                yield FrameError(ErrorCode.FRAME_ERROR, f"unknown frame type {ftype}")
                return
            if self.frame_max and size + 8 > self.frame_max:
                self._dead = True
                yield FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"frame size {size} exceeds negotiated frame-max {self.frame_max}",
                )
                return
            end = offset + FRAME_HEADER_SIZE + size
            if n < end + 1:
                break
            if buf[end] != FRAME_END:
                self._dead = True
                yield FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"missing frame-end octet (got 0x{buf[end]:02x})",
                )
                return
            yield Frame(ftype, channel, bytes(buf[offset + FRAME_HEADER_SIZE : end]))
            offset = end + 1
        if offset:
            del buf[:offset]
