"""Continuous performance observability (``chana.mq.profile.*``).

Three coupled parts, all always-cheap enough to leave on in production:

- a **per-message cost ledger**: the hot-path seams that already carry
  trace spans (ingress-parse / route / enqueue / wal-append / wal-commit /
  cluster-push / deliver / settle, PR 5) accumulate aggregate per-stage
  CPU-ns and invocation counts into fixed numpy accumulators. There is no
  sampling decision on the hot path: every seam is gated on the same
  module-level ``ACTIVE is None`` check chaos and trace use, and the
  per-message stages accumulate at batch granularity wherever a batch
  exists (router flush, dispatch pass, scan pass), so the enabled cost
  stays inside the 2% budget ``bench.py --profile-overhead`` enforces.
- a **sampling wall profiler + stall attribution**: an off-loop thread
  samples ``sys._current_frames()`` into folded-stack counts (flamegraph
  collapsed format at ``GET /admin/profile/stacks``), doubles as the
  event-loop watchdog that captures the stack and duration of any
  callback stalling the loop past ``chana.mq.profile.slow-callback-ms``,
  and a ``gc.callbacks`` hook attributes collector pauses.
- the aggregate view at ``GET /admin/profile``: µs/msg by stage and by
  subsystem plus the fraction of process CPU the ledger attributes.

Like ``trace`` and ``chaos``: disabled (the default) costs one module
attribute load + ``is None`` per seam.
"""

from __future__ import annotations

from typing import Optional

from .runtime import (  # noqa: F401 — re-exported page for the seams
    CLUSTER_PUSH, DELIVER, DISPATCH, ENQUEUE, FLOW_THROTTLE, GC,
    INGRESS_CYCLE, INGRESS_PARSE, ROUTE, SETTLE, STAGES, SUBSYSTEMS,
    TOP_LEVEL, TX_COMMIT, WAL_APPEND, WAL_COMMIT, ProfileRuntime,
)

# The gate. Hot-path seams do `prof = profile.ACTIVE` then
# `if prof is not None:` — one module attribute load when disabled.
ACTIVE: Optional[ProfileRuntime] = None


def install(runtime: ProfileRuntime) -> ProfileRuntime:
    global ACTIVE
    ACTIVE = runtime
    return runtime


def clear() -> None:
    global ACTIVE
    if ACTIVE is not None:
        ACTIVE.stop()
    ACTIVE = None


def enable_from_config(config, broker) -> ProfileRuntime:
    """Boot-time wiring (``chana.mq.profile.enabled``): build the runtime
    from the knobs, hang it off the broker for the admin surface, install
    the gate, and start the sampler/watchdog/GC hooks."""
    runtime = ProfileRuntime(
        metrics=broker.metrics,
        sample_hz=config.int("chana.mq.profile.sample-hz"),
        slow_callback_ms=config.int("chana.mq.profile.slow-callback-ms"),
        ring_size=config.int("chana.mq.profile.ring-size"),
        gc_hook=config.bool("chana.mq.profile.gc"),
        broker=broker,
    )
    broker.profile = runtime
    install(runtime)
    runtime.start()
    return runtime
