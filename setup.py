"""Build shim: compile the native hot-path library at install time.

native/chanamq_native.cpp is a plain `extern "C"` shared object consumed via
ctypes (chanamq_tpu/native_ext.py), not a CPython extension module — so it is
compiled with build_ext machinery but never imported. A missing/broken C++
toolchain must not fail the install: the broker runs on its pure-Python hot
paths (native_ext falls back silently), so build errors just skip the lib.
"""

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext


class OptionalBuildExt(build_ext):
    def build_extension(self, ext):
        try:
            super().build_extension(ext)
        except Exception as exc:  # toolchain missing: pure-Python fallback
            print(f"WARNING: skipping native extension {ext.name}: {exc}")

    def get_export_symbols(self, ext):
        # not a CPython module: there is no PyInit_* symbol to export
        return []


setup(
    ext_modules=[
        Extension(
            "chanamq_tpu._chanamq_native",
            sources=["native/chanamq_native.cpp"],
            extra_compile_args=["-O2", "-std=c++17"],
            optional=True,
        )
    ],
    cmdclass={"build_ext": OptionalBuildExt},
)
