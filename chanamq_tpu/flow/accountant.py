"""The MemoryAccountant: component cost gauges -> degradation ladder.

Design notes:

- Components are plain integers mutated by their owners. The two hot
  ones (`bodies` = resident message-body bytes, `held` = parked publish
  bytes) are pushed synchronously from Broker.account_memory /
  account_held so the ladder reacts within the publish that crosses a
  watermark — the same latency the old binary gate had. The cold ones
  (WAL memtable, data-plane buffers, connection out-buffers, stream
  sealed cache, chaos inflation) are POLLED once per broker sweep tick:
  hooking their hot-path mutations would tax every WAL append and every
  socket write for a signal that only needs sweep-tick freshness.

- The ladder has one enter threshold per stage and a matching exit
  threshold scaled by low/high, so every stage transition has the same
  hysteresis the old gate had and the broker cannot flap on a single
  oscillating publish/ack pair. Escalation is evaluated on every
  reevaluate() (a burst can jump several stages in one publish);
  de-escalation cascades the same way on a drain.

- Stage 2 (`throttle`) is wired to the broker's legacy memory gate:
  `broker.blocked` is exactly `stage >= STAGE_THROTTLE` (composed with
  the store-growth gate), so all the existing park/hold/resume and
  Connection.Blocked machinery keeps its contract unchanged.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Optional

log = logging.getLogger("chanamq.flow")

STAGE_NORMAL = 0
STAGE_PAGE = 1
STAGE_THROTTLE = 2
STAGE_CLUSTER = 3
STAGE_REFUSE = 4

STAGE_NAMES = ("normal", "page", "throttle", "cluster", "refuse")

# accounted cost sources; "bodies" and "held" are pushed synchronously,
# the rest sampled each sweep tick (see Broker._flow_tick)
COMPONENTS = (
    "bodies",           # resident message-body bytes (Broker.resident_bytes)
    "held",             # publish bodies parked at the gate (held_bytes)
    "out_buffers",      # rendered-but-unsent delivery frames per connection
    "wal_memtable",     # WAL bytes appended but not yet committed/settled
    "cluster_inflight", # data-plane push/settle bytes buffered per peer
    "stream_cache",     # sealed stream segment blobs resident in RAM
    "chaos",            # deterministic inflation from a memory-pressure rule
)


class MemoryAccountant:
    """Tracks accounted resident bytes and drives the 4-stage ladder."""

    def __init__(
        self,
        *,
        high_watermark: int,
        low_watermark: Optional[int] = None,
        page_watermark: Optional[int] = None,
        cluster_watermark: Optional[int] = None,
        hard_limit: Optional[int] = None,
        refuse_watermark: Optional[int] = None,
    ) -> None:
        hw = int(high_watermark)
        if hw <= 0:
            raise ValueError("flow high watermark must be positive")
        lw = int(low_watermark) if low_watermark is not None else int(hw * 0.8)
        if not 0 < lw < hw:
            log.warning(
                "flow low watermark %d outside (0, high=%d); "
                "clamping to 80%% of high", lw, hw)
            lw = int(hw * 0.8)
        hard = int(hard_limit) if hard_limit else 2 * hw
        hard = max(hard, hw + 1)
        refuse = int(refuse_watermark) if refuse_watermark else int(hard * 0.9)
        # enter thresholds must be strictly increasing page < hw < cluster
        # < refuse <= hard or a stage becomes unreachable / inverted
        refuse = min(max(refuse, hw + 1), hard)
        page = int(page_watermark) if page_watermark else int(hw * 0.6)
        page = min(max(page, 1), hw - 1) if hw > 1 else 1
        cluster = (int(cluster_watermark) if cluster_watermark
                   else (hw + refuse) // 2)
        cluster = min(max(cluster, hw + 1), refuse)
        self.high_watermark = hw
        self.low_watermark = lw
        self.hard_limit = hard
        # enter[s]: escalate to stage s while total > enter[s];
        # exit[s]: de-escalate below stage s while total <= exit[s].
        # exit scales each enter by low/high so stage 2 keeps the exact
        # legacy gate contract (block above high, unblock at/below low).
        self.enter = (0, page, hw, cluster, refuse)
        self.exit = tuple(e * lw // hw for e in self.enter)
        self.components: dict[str, int] = {name: 0 for name in COMPONENTS}
        self.stage = STAGE_NORMAL
        # minimum stage pinned by the predictive control plane
        # (chanamq_tpu/control/): a pre-arm decision raises the floor so
        # throttling engages BEFORE the watermark, through the exact same
        # listener/actuation chain as a reactive crossing; clearing it
        # lets the ladder settle back to the accounted total
        self.floor = STAGE_NORMAL
        self.total = 0
        self.peak_total = 0
        # fired as fn(old_stage, new_stage) on every transition
        self.listeners: list[Callable[[int, int], Any]] = []
        # cluster push handlers park on this below-stage-3 event so a
        # pressured owner delays push_many replies (the origin's stream
        # window fills and its publisher slows) instead of buffering
        self._below_cluster = asyncio.Event()
        self._below_cluster.set()

    @property
    def label(self) -> str:
        return STAGE_NAMES[self.stage]

    def add(self, component: str, delta: int) -> None:
        self.components[component] += delta
        self.reevaluate()

    def reevaluate(self) -> None:
        """Recompute the total and walk the ladder; fires listeners once
        per transition (never flaps: enter/exit gaps are the hysteresis).

        Ladder decisions deliberately EXCLUDE the ``held`` component:
        parked publishes can only drain once the gate reopens, so a gate
        that counted them could never reopen (the bytes it waits on are
        the bytes it parked). They are still reported/peaked as accounted
        cost — they are real memory — but as a bounded buffer (park cap
        per connection), not a gate input, exactly like the legacy gate."""
        total = 0
        for v in self.components.values():
            total += v
        self.total = total
        if total > self.peak_total:
            self.peak_total = total
        gate_total = total - self.components["held"]
        stage = self.stage
        while stage < STAGE_REFUSE and gate_total > self.enter[stage + 1]:
            stage += 1
        if stage == self.stage:
            while stage > STAGE_NORMAL and gate_total <= self.exit[stage]:
                stage -= 1
        if stage < self.floor:
            stage = self.floor
        if stage == self.stage:
            return
        old, self.stage = self.stage, stage
        if stage >= STAGE_CLUSTER:
            self._below_cluster.clear()
        else:
            self._below_cluster.set()
        log.warning(
            "flow stage %s -> %s (accounted=%d high=%d hard=%d)",
            STAGE_NAMES[old], STAGE_NAMES[stage], total,
            self.high_watermark, self.hard_limit)
        for listener in list(self.listeners):
            try:
                listener(old, stage)
            except Exception:
                log.exception("flow stage listener failed")

    async def cluster_stall(self, timeout: float = 0.25) -> None:
        """One bounded wait for pressure to drop below the cluster stage.
        Callers loop (or simply proceed after the timeout): a bounded
        stall per batch is pushback, an unbounded one is a deadlock."""
        if self._below_cluster.is_set():
            return
        try:
            await asyncio.wait_for(self._below_cluster.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def snapshot(self) -> dict:
        return {
            "stage": self.stage,
            "stage_label": self.label,
            "floor": self.floor,
            "total_bytes": self.total,
            "peak_bytes": self.peak_total,
            "high_watermark": self.high_watermark,
            "low_watermark": self.low_watermark,
            "hard_limit": self.hard_limit,
            "enter": list(self.enter),
            "exit": list(self.exit),
            "components": dict(self.components),
        }
