"""Multi-process sharded broker node (ISSUE 7).

One machine, N broker processes ("shards"), each a full cluster member:
the supervisor (:mod:`.supervisor`) spawns one worker per core (knob
``chana.mq.shard.count``; 0 = ``os.cpu_count()``), workers accept AMQP
clients on a shared SO_REUSEPORT listener (or via the fd-handoff
acceptor, :mod:`.handoff`, where SO_REUSEPORT is unavailable), own
queues by the same consistent hash as remote nodes (cluster/hashring),
and reach sibling shards over Unix-domain sockets with the binary data
plane (frame kinds 4/5/6) — a cross-shard hop is one zero-copy push.

The paper's location-transparent sharded entities (PAPER.md §L3) map
onto processes instead of actor shards; everything above the transport
(ownership, replication promotion, chaos seams, trace trailers,
telemetry pull) is the unchanged cluster machinery.
"""

from .topology import ShardTopology, resolve_count  # noqa: F401
