"""Cluster primitive tests: RPC, consistent-hash ring, membership."""

import asyncio

import pytest

from chanamq_tpu.cluster.hashring import HashRing
from chanamq_tpu.cluster.membership import Membership
from chanamq_tpu.cluster.rpc import RpcClient, RpcError, RpcServer, RpcTimeout

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# RPC
# ---------------------------------------------------------------------------


@pytest.fixture
async def rpc():
    server = RpcServer("127.0.0.1", 0)

    async def echo(payload):
        return {"echo": payload.get("value"), "n": payload.get("n", 0) + 1}

    async def boom(payload):
        raise RpcError("boom", "deliberate")

    async def slow(payload):
        await asyncio.sleep(5)
        return {}

    server.register("echo", echo)
    server.register("boom", boom)
    server.register("slow", slow)
    await server.start()
    client = RpcClient("127.0.0.1", server.bound_port)
    yield server, client
    await client.close()
    await server.stop()


async def test_rpc_roundtrip(rpc):
    _, client = rpc
    out = await client.call("echo", {"value": "hi", "n": 41})
    assert out == {"echo": "hi", "n": 42}


async def test_rpc_binary_payload(rpc):
    _, client = rpc
    blob = bytes(range(256)) * 10
    out = await client.call("echo", {"value": blob})
    assert out["echo"] == blob


async def test_rpc_nested_payload(rpc):
    _, client = rpc
    nested = {"value": {"a": [1, "two", {"three": 3}], "b": True, "c": None}}
    out = await client.call("echo", nested)
    assert out["echo"] == nested["value"]


async def test_rpc_error_propagates(rpc):
    _, client = rpc
    with pytest.raises(RpcError) as exc_info:
        await client.call("boom")
    assert exc_info.value.code == "boom"


async def test_rpc_unknown_method(rpc):
    _, client = rpc
    with pytest.raises(RpcError) as exc_info:
        await client.call("nope")
    assert exc_info.value.code == "no_such_method"


async def test_rpc_timeout(rpc):
    _, client = rpc
    with pytest.raises(RpcTimeout):
        await client.call("slow", timeout_s=0.2)


async def test_rpc_concurrent_correlation(rpc):
    _, client = rpc
    outs = await asyncio.gather(
        *[client.call("echo", {"n": i}) for i in range(50)])
    assert [o["n"] for o in outs] == [i + 1 for i in range(50)]


async def test_rpc_reconnects_after_server_restart():
    server = RpcServer("127.0.0.1", 0)

    async def ping(payload):
        return {"pong": True}

    server.register("ping", ping)
    await server.start()
    port = server.bound_port
    client = RpcClient("127.0.0.1", port)
    assert (await client.call("ping"))["pong"] is True
    await server.stop()
    with pytest.raises((RpcError, OSError)):
        await client.call("ping", timeout_s=1)
    server2 = RpcServer("127.0.0.1", port)
    server2.register("ping", ping)
    await server2.start()
    assert (await client.call("ping"))["pong"] is True  # lazy reconnect
    await client.close()
    await server2.stop()


# ---------------------------------------------------------------------------
# hash ring
# ---------------------------------------------------------------------------


def test_ring_deterministic_and_complete():
    ring = HashRing(["n1:1", "n2:1", "n3:1"])
    owners = {ring.owner(f"key{i}") for i in range(1000)}
    assert owners == {"n1:1", "n2:1", "n3:1"}
    assert ring.owner("stable") == ring.owner("stable")


def test_ring_minimal_movement_on_removal():
    ring = HashRing(["n1:1", "n2:1", "n3:1"])
    before = {f"key{i}": ring.owner(f"key{i}") for i in range(2000)}
    ring.remove("n2:1")
    moved = 0
    for key, old in before.items():
        new = ring.owner(key)
        if old != "n2:1":
            assert new == old  # survivors keep their keys
        else:
            moved += 1
    assert moved > 0


def test_ring_empty():
    assert HashRing([]).owner("x") is None


def test_ring_entity_key():
    ring = HashRing(["a:1", "b:1"])
    assert ring.owner_entity("q", "/", "foo") in ("a:1", "b:1")
    # distinct kinds may land differently but must be deterministic
    assert ring.owner_entity("q", "/", "foo") == ring.owner_entity("q", "/", "foo")


# ---------------------------------------------------------------------------
# membership
# ---------------------------------------------------------------------------


async def make_node(seeds):
    server = RpcServer("127.0.0.1", 0)
    await server.start()
    name = f"127.0.0.1:{server.bound_port}"
    membership = Membership(
        name, seeds, server,
        heartbeat_interval_s=0.1, failure_timeout_s=0.6)
    await membership.start()
    return server, membership


async def test_membership_three_nodes_converge_and_detect_failure():
    s1, m1 = await make_node([])
    s2, m2 = await make_node([m1.self_name])
    s3, m3 = await make_node([m1.self_name])
    try:
        for _ in range(50):
            if (len(m1.alive_members()) == 3 and len(m2.alive_members()) == 3
                    and len(m3.alive_members()) == 3):
                break
            await asyncio.sleep(0.1)
        assert len(m1.alive_members()) == 3
        assert m1.alive_members() == m2.alive_members() == m3.alive_members()
        assert m1.leader() == m2.leader() == m3.leader()

        # kill node 3
        await m3.stop()
        await s3.stop()
        for _ in range(60):
            if (m3.self_name not in m1.alive_members()
                    and m3.self_name not in m2.alive_members()):
                break
            await asyncio.sleep(0.1)
        assert m3.self_name not in m1.alive_members()
        assert m3.self_name not in m2.alive_members()
        assert len(m1.alive_members()) == 2
    finally:
        for m, s in ((m1, s1), (m2, s2)):
            await m.stop()
            await s.stop()


async def test_membership_rejoin_after_down():
    s1, m1 = await make_node([])
    s2, m2 = await make_node([m1.self_name])
    try:
        for _ in range(50):
            if len(m1.alive_members()) == 2:
                break
            await asyncio.sleep(0.1)
        # stop node2's server, wait for down, then restart on the same port
        port = m2.self_name.rsplit(":", 1)[1]
        await m2.stop()
        await s2.stop()
        for _ in range(60):
            if m2.self_name not in m1.alive_members():
                break
            await asyncio.sleep(0.1)
        assert m2.self_name not in m1.alive_members()

        s2b = RpcServer("127.0.0.1", int(port))
        await s2b.start()
        m2b = Membership(m2.self_name, [m1.self_name], s2b,
                         heartbeat_interval_s=0.1, failure_timeout_s=0.6)
        await m2b.start()
        for _ in range(60):
            if m2.self_name in m1.alive_members():
                break
            await asyncio.sleep(0.1)
        assert m2.self_name in m1.alive_members()
        await m2b.stop()
        await s2b.stop()
    finally:
        await m1.stop()
        await s1.stop()


def test_ring_balance_across_nodes():
    """Consistent-hash distribution: with the default virtual-node count,
    no node owns a pathological share of keys (the reference sharded by
    entityId.hashCode % 100; this ring must spread at least as well)."""
    ring = HashRing(["node-a", "node-b", "node-c"], virtual_nodes=64)
    counts = {"node-a": 0, "node-b": 0, "node-c": 0}
    n = 9000
    for i in range(n):
        counts[ring.owner_entity("q", "/", f"queue-{i}")] += 1
    for node, count in counts.items():
        share = count / n
        assert 0.15 < share < 0.55, (node, share, counts)


def test_ring_minimal_movement_on_join():
    """Adding a node must move only the keys the new node takes over —
    ownership of everything else is pinned (the join-churn guarantee the
    broker's queue routing relies on)."""
    before = HashRing(["node-a", "node-b"], virtual_nodes=64)
    after = HashRing(["node-a", "node-b", "node-c"], virtual_nodes=64)
    moved = stayed = 0
    for i in range(4000):
        o1 = before.owner_entity("q", "/", f"queue-{i}")
        o2 = after.owner_entity("q", "/", f"queue-{i}")
        if o1 == o2:
            stayed += 1
        else:
            moved += 1
            assert o2 == "node-c", (o1, o2)  # keys only move TO the joiner
    # roughly a third moves; anything far beyond that breaks the pin
    assert moved / 4000 < 0.5, moved
