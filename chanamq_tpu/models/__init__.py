"""Auxiliary JAX models — analytics over broker metrics.

The reference contains no ML compute path (SURVEY.md preamble: zero tensor
code in the tree), so per SURVEY.md §7.1 the only honest JAX component is
batch analytics over broker telemetry, strictly OFF the message path. The
flagship model is a small causal transformer that forecasts per-queue
traffic (enqueue/dequeue rates, depth) from a sliding window of metrics —
the kind of capacity/backlog prediction an operator would bolt onto a broker.

TPU-first by construction: bfloat16 matmuls sized for the MXU, static
shapes, lax.scan-free forward, shardable over a (dp, tp) device mesh via
NamedSharding annotations (see chanamq_tpu.parallel).
"""

from .forecaster import (
    ForecasterConfig,
    init_params,
    forward,
    loss_fn,
    make_train_step,
    synthetic_batch,
)

__all__ = [
    "ForecasterConfig",
    "init_params",
    "forward",
    "loss_fn",
    "make_train_step",
    "synthetic_batch",
]
