"""Multi-tenancy: per-tenant quotas, auth/ACLs, and tenant-scoped SLOs.

Gating discipline is identical to chaos/trace/profile/events: the
module-level ``ACTIVE`` registry is ``None`` unless tenancy is enabled,
and every enforcement seam in the broker/connection hot paths costs one
attribute load plus an identity check when off. The steady-state cost
with tenancy ON is likewise kept off the per-frame path: rate limiting
rides the existing publish-hold machinery (connections only consult the
bucket when their tenant declares a ``publish-rate``), and memory shares
ride the flow ladder's stage-floor mechanism.

Tenants are declared at boot via ``chana.mq.tenant.enabled`` +
``chana.mq.tenant.tenants`` (a JSON object of name -> spec, a dict leaf
like ``chana.mq.auth.users``), or at runtime via ``POST /admin/tenants``.
See :mod:`chanamq_tpu.tenancy.registry` for spec shape and enforcement
mechanics.
"""

from __future__ import annotations

from typing import Optional

from .registry import (  # noqa: F401
    ACL_PERMS,
    TenancyError,
    Tenant,
    TenantQuota,
    TenantRegistry,
)

ACTIVE: Optional[TenantRegistry] = None


def install(registry: Optional[TenantRegistry]) -> None:
    global ACTIVE
    ACTIVE = registry


def clear() -> None:
    install(None)


def enable_from_config(config, broker) -> Optional[TenantRegistry]:
    """Boot-time wiring: build the registry from ``chana.mq.tenant.*``,
    hang it off the broker, install the module gate. Validated fail-closed
    (like the auth knobs): a malformed tenant map, or tenants declared
    while tenancy is disabled, is a boot error — never a silently
    unenforced quota."""
    from ..config import ConfigError

    enabled = config.bool("chana.mq.tenant.enabled")
    tenants = config.get("chana.mq.tenant.tenants")
    if not enabled:
        if tenants:
            raise ConfigError(
                "chana.mq.tenant.tenants is set but chana.mq.tenant.enabled "
                "is false; enable tenancy or drop the tenant map")
        return None
    registry = TenantRegistry(broker)
    if tenants is not None:
        if not isinstance(tenants, dict):
            raise ConfigError(
                "chana.mq.tenant.tenants must map tenant names to specs")
        for name in sorted(tenants):
            try:
                registry.define(name, tenants[name])
            except TenancyError as exc:
                raise ConfigError(
                    f"chana.mq.tenant.tenants[{name!r}]: {exc}") from exc
    broker.tenancy = registry
    install(registry)
    return registry
