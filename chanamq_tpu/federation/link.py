"""FederationLink: the shipping side of one named federation link.

One background task per link runs a connect → resume → pump loop:

- **connect**: dial the remote federation listener (chaos seam
  ``fed.connect``), handshake with ``fed.hello``, then ``fed.resume``
  every mirrored queue to learn the mirror's next expected offset — the
  remote is the source of truth, so a reconnect after a severed link
  resumes exactly where the last applied segment left off;
- **pump**: ship every sealed segment the remote hasn't seen (chaos seam
  ``fed.ship``, CRC32 stamped on the wire, blobs read through
  ``store.select_stream_segment`` so tiered-off cold segments rehydrate
  via the PR 8 path), flush coalesced cursor commits, and drain the
  outbox of staged DLX forwards and Tx batches.

Sends pipeline through a :class:`DataStream` whose ``inflight``
semaphore is the per-link in-flight window; per queue, ships stay
sequential (the remote requires contiguous bases) while distinct queues
and outbox entries interleave freely inside the window. Any transport
or remote error marks the link down, backs off, and reconnects — state
staged locally (dirty cursors, outbox) survives the outage and drains
after heal.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
import zlib
from collections import deque
from typing import TYPE_CHECKING, Optional

from .. import chaos
from ..cluster.dataplane import DataStream, _put_ss
from ..cluster.rpc import RpcClient, RpcError

if TYPE_CHECKING:  # pragma: no cover
    from .service import FederationService

log = logging.getLogger("chanamq.federation")

# binary method ids on the federation RpcServer (a dedicated listener:
# these share no namespace with the intra-cluster data plane's ids)
FED_SHIP = 1     # sealed segment ship
FED_TX = 2       # staged Tx publish batch (all-or-nothing far side)
FED_PUBLISH = 3  # single forwarded publish (DLX routing)

# staged-work bound per link: a long outage drops staged forwards
# rather than growing without bound (counted per kind, and documented as
# at-most-once for DLX/Tx forwarding across extended outages). Single
# DLX forwards shed before whole committed Tx batches — see _stage.
_OUTBOX_MAX = 10_000


def _chaos_fed_error(fault) -> RpcError:
    return RpcError(getattr(fault, "code", "chaos") or "chaos",
                    f"chaos[{fault.rule}]: {fault.message}")


class FederationLink:
    """Local half of one named link to a remote cluster."""

    def __init__(self, service: "FederationService", spec: dict) -> None:
        self.service = service
        self.name = str(spec["name"])
        self.host = str(spec["host"])
        self.port = int(spec["port"])
        self.vhost = str(spec.get("vhost", "/"))
        self.queues: list[str] = [str(q) for q in spec.get("queues", [])]
        self.exchanges: set[str] = {
            str(e) for e in spec.get("exchanges", [])}
        self.window = max(1, int(spec.get("window", service.window)))
        self.retry_s = float(spec.get("retry_s", service.retry_s))
        #: shared secret presented on every federation call (control and
        #: data plane); must match the remote listener's ``auth_token``
        self.token = str(spec.get("token", service.auth_token))
        #: per-boot shipper incarnation: the receiver keys its Tx/publish
        #: dedup high-water marks by (link, epoch), so a restarted
        #: shipper whose in-memory sequences reset to 0 starts a fresh
        #: dedup scope instead of having every batch swallowed as a
        #: duplicate of the previous incarnation's sequence space
        self.epoch = uuid.uuid4().hex[:16]
        self.rpc = RpcClient(self.host, self.port, timeout_s=10.0)
        self.data = DataStream(
            self.host, self.port, inflight=self.window, timeout_s=30.0,
            metrics=service.metrics)
        self.state = "down"
        self.remote_node = ""
        self.last_error: Optional[str] = None
        #: mirror's next expected offset per queue (from fed.resume /
        #: ship replies); shipping starts here after every (re)connect
        self.remote_next: dict[str, int] = {}
        #: coalesced cursor commits awaiting mirror flush
        self.dirty_cursors: dict[str, dict[str, int]] = {}
        #: staged DLX forwards and Tx batches, drained in order
        self.outbox: deque = deque()
        self._tx_seq = 0
        self._pub_seq = 0
        self._was_up = False
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        await self.rpc.close()
        await self.data.close()

    def wake(self) -> None:
        self._wake.set()

    # -- staging (called from broker hooks; must not await) ----------------

    def note_cursor(self, queue: str, name: str, offset: int) -> None:
        cursors = self.dirty_cursors.setdefault(queue, {})
        if offset > cursors.get(name, -1):
            cursors[name] = offset
        self._wake.set()

    def queue_publish(self, exchange: str, routing_key: str,
                      header_raw: bytes, body: bytes) -> None:
        self._pub_seq += 1
        self._stage(
            ("publish", self._pub_seq, exchange, routing_key,
             header_raw, body))

    def queue_tx(self, ops: list) -> None:
        self._tx_seq += 1
        self._stage(("tx", self._tx_seq, ops))
        self.service.metrics.federation_tx_batches += 1
        self.service.metrics.federation_tx_publishes += len(ops)

    def _stage(self, item: tuple) -> None:
        if len(self.outbox) >= _OUTBOX_MAX:
            # shed a single DLX forward before a whole committed Tx
            # batch: the oldest publish goes first, a tx entry only when
            # the outbox holds nothing else (counted per kind)
            metrics = self.service.metrics
            for idx, staged in enumerate(self.outbox):
                if staged[0] == "publish":
                    del self.outbox[idx]
                    metrics.federation_outbox_dropped_publish += 1
                    break
            else:
                self.outbox.popleft()
                metrics.federation_outbox_dropped_tx += 1
            metrics.federation_outbox_dropped += 1
        self.outbox.append(item)
        self._wake.set()

    # -- observability -----------------------------------------------------

    def queue_lag(self, qname: str) -> int:
        """Records appended locally but not yet applied on the mirror
        (includes the unsealed active segment: honest lag, not just
        shippable lag)."""
        try:
            queue = self.service.broker.get_queue(self.vhost, qname)
        except Exception:
            return 0
        if not getattr(queue, "is_stream", False):
            return 0
        return max(0, queue.next_offset - self.remote_next.get(qname, 0))

    def total_lag(self) -> int:
        return max((self.queue_lag(q) for q in self.queues), default=0)

    def info(self) -> dict:
        backoff = self.rpc.backoff_state()
        return {
            "name": self.name,
            "host": self.host, "port": self.port, "vhost": self.vhost,
            "state": self.state,
            "remote_node": self.remote_node,
            "window": self.window,
            "queues": {
                q: {"remote_next": self.remote_next.get(q, 0),
                    "lag": self.queue_lag(q)}
                for q in self.queues},
            "lag": self.total_lag(),
            "exchanges": sorted(self.exchanges),
            "cursors_pending": sum(
                len(c) for c in self.dirty_cursors.values()),
            "outbox": len(self.outbox),
            "last_error": self.last_error,
            "backoff": backoff,
        }

    # -- the link loop -----------------------------------------------------

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self._connect()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self._note_down(exc)
                await asyncio.sleep(self.retry_s)
                continue
            try:
                while not self._stopped:
                    await self._pump()
                    try:
                        await asyncio.wait_for(
                            self._wake.wait(), self.service.idle_s)
                    except asyncio.TimeoutError:
                        pass
                    self._wake.clear()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self._note_down(exc)
                await asyncio.sleep(self.retry_s)

    async def _connect(self) -> None:
        if chaos.ACTIVE is not None:
            fault = await chaos.ACTIVE.fire(
                "fed.connect", peer=self.name, on_error=_chaos_fed_error)
            if fault is not None:
                raise RpcError(fault.code or "chaos",
                               f"chaos[{fault.rule}]: {fault.message}")
        hello = await self.rpc.call(
            "fed.hello", {"link": self.name, "node": self.service.node_name,
                          "epoch": self.epoch, "token": self.token})
        self.remote_node = str(hello.get("node", ""))
        for qname in self.queues:
            resume = await self.rpc.call("fed.resume", {
                "link": self.name, "vhost": self.vhost, "queue": qname,
                "token": self.token})
            self.remote_next[qname] = int(resume.get("next", 0))
        resumed = self._was_up
        self._was_up = True
        self.state = "up"
        self.last_error = None
        payload = {"link": self.name, "remote": self.remote_node,
                   "queues": {q: self.remote_next.get(q, 0)
                              for q in self.queues}}
        self.service.record("link.up", payload)
        if resumed:
            self.service.metrics.federation_resumes += 1
            self.service.record("link.resumed", payload)

    def _note_down(self, exc: Exception) -> None:
        self.last_error = repr(exc)
        if self.state != "down":
            self.state = "down"
            self.service.metrics.federation_link_failures += 1
            self.service.record("link.down", {
                "link": self.name, "error": type(exc).__name__})

    async def _pump(self) -> None:
        # distinct queues ship concurrently inside the DataStream window;
        # within a queue, ships stay sequential (contiguous bases)
        if len(self.queues) > 1:
            results = await asyncio.gather(
                *(self._ship_queue(q) for q in self.queues),
                return_exceptions=True)
            for res in results:
                if isinstance(res, BaseException):
                    raise res
        elif self.queues:
            await self._ship_queue(self.queues[0])
        await self._flush_cursors()
        await self._flush_outbox()

    async def _ship_queue(self, qname: str) -> None:
        broker = self.service.broker
        try:
            queue = broker.get_queue(self.vhost, qname)
        except Exception:
            return  # not declared locally yet: nothing to ship
        if not getattr(queue, "is_stream", False):
            return
        metrics = self.service.metrics
        while True:
            next_needed = self.remote_next.get(qname, 0)
            seg = None
            for candidate in queue._segments:
                if candidate.last_offset < next_needed:
                    continue
                seg = candidate
                break
            if seg is None:
                return
            if seg.base_offset > next_needed:
                # local retention truncated past the mirror's position:
                # nothing can fill the hole — hold until the remote
                # operator resets the mirror (counted, not silent)
                metrics.federation_ship_errors += 1
                log.warning(
                    "link %s: queue %s local head %d past mirror next %d",
                    self.name, qname, seg.base_offset, next_needed)
                return
            try:
                applied_next = await self._ship_segment(queue, seg)
            except RpcError as exc:
                gap = _parse_gap(exc)
                if gap is None:
                    raise
                # receiver knows better (e.g. a duplicate race after a
                # lost ack): adopt its position and retry from there
                metrics.federation_resyncs += 1
                self.remote_next[qname] = gap
                continue
            self.remote_next[qname] = applied_next
            metrics.federation_segments_shipped += 1

    async def _ship_segment(self, queue, seg) -> int:
        """Ship one sealed segment; returns the mirror's next offset."""
        # deferred: importing streams at module level before the broker
        # package finishes initializing would close an import cycle
        from ..streams.segment import pack_records

        if chaos.ACTIVE is not None:
            fault = await chaos.ACTIVE.fire(
                "fed.ship", peer=self.name, on_error=_chaos_fed_error)
            if fault is not None:
                raise RpcError(fault.code or "chaos",
                               f"chaos[{fault.rule}]: {fault.message}")
        if seg.records is not None:
            blob = pack_records([r for r in seg.records if r is not None])
        else:
            # evicted/cold segment: the store read rehydrates a tiered-off
            # blob through the PR 8 offload path transparently
            blob = await self.service.broker.store.select_stream_segment(
                queue.vhost, queue.name, seg.base_offset)
            if blob is None:
                raise RpcError("missing",
                               f"segment {seg.base_offset} unreadable")
        head = bytearray()
        _put_ss(head, self.token)
        _put_ss(head, queue.vhost)
        _put_ss(head, queue.name)
        head += seg.base_offset.to_bytes(8, "big")
        head += seg.last_offset.to_bytes(8, "big")
        head += seg.first_ts_ms.to_bytes(8, "big")
        head += seg.last_ts_ms.to_bytes(8, "big")
        head += (zlib.crc32(blob) & 0xFFFFFFFF).to_bytes(4, "big")
        head += len(blob).to_bytes(4, "big")
        reply = await self.data.request(FED_SHIP, [bytes(head), blob])
        self.service.metrics.federation_segment_bytes += len(blob)
        return int.from_bytes(bytes(reply[:8]), "big")

    async def _flush_cursors(self) -> None:
        while self.dirty_cursors:
            qname = next(iter(self.dirty_cursors))
            cursors = self.dirty_cursors.pop(qname)
            try:
                await self.rpc.call("fed.cursor", {
                    "link": self.name, "vhost": self.vhost, "queue": qname,
                    "cursors": cursors, "token": self.token})
            except BaseException:
                # stays dirty; re-merge (a commit may have landed since)
                merged = self.dirty_cursors.setdefault(qname, {})
                for name, offset in cursors.items():
                    if offset > merged.get(name, -1):
                        merged[name] = offset
                raise
            self.service.metrics.federation_cursors_shipped += len(cursors)

    async def _flush_outbox(self) -> None:
        while self.outbox:
            item = self.outbox[0]
            if item[0] == "publish":
                _, seq, exchange, rkey, header, body = item
                buf = bytearray()
                _put_ss(buf, self.token)
                _put_ss(buf, self.name)
                _put_ss(buf, self.epoch)
                buf += seq.to_bytes(8, "big")
                _put_ss(buf, self.vhost)
                _put_ss(buf, exchange)
                _put_ss(buf, rkey)
                buf += len(header).to_bytes(4, "big")
                buf += header
                buf += len(body).to_bytes(4, "big")
                buf += body
                await self.data.request(FED_PUBLISH, [bytes(buf)])
            else:
                _, seq, ops = item
                buf = bytearray()
                _put_ss(buf, self.token)
                _put_ss(buf, self.name)
                _put_ss(buf, self.epoch)
                buf += seq.to_bytes(8, "big")
                _put_ss(buf, self.vhost)
                buf += len(ops).to_bytes(4, "big")
                for exchange, rkey, header, body in ops:
                    _put_ss(buf, exchange)
                    _put_ss(buf, rkey)
                    buf += len(header).to_bytes(4, "big")
                    buf += header
                    buf += len(body).to_bytes(4, "big")
                    buf += body
                await self.data.request(FED_TX, [bytes(buf)])
            self.outbox.popleft()


def _parse_gap(exc: RpcError) -> Optional[int]:
    """The receiver's resync hint: a remote ``RpcError("gap", "<next>")``
    arrives through the binary error reply as message ``"gap: <next>"``."""
    message = getattr(exc, "message", "") or ""
    if message.startswith("gap:"):
        try:
            return int(message[4:])
        except ValueError:
            return None
    return None
