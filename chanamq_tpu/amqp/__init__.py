"""L0: AMQP 0-9-1 wire codec and protocol model.

Rebuilds the capability of the reference's chana-mq-base protocol library
(reference: chana-mq-base/src/main/scala/chana/mq/amqp/{model,method,engine})
as a standalone Python codec: frames, field-table values, content-header
properties, the full method-class registry, and the command assembler.
"""

from .constants import FrameType, ErrorCode, PROTOCOL_HEADER
from .frame import Frame, FrameParser, FrameError, HEARTBEAT_FRAME
from .properties import BasicProperties
from .command import AMQCommand, CommandAssembler

__all__ = [
    "FrameType",
    "ErrorCode",
    "PROTOCOL_HEADER",
    "Frame",
    "FrameParser",
    "FrameError",
    "HEARTBEAT_FRAME",
    "BasicProperties",
    "AMQCommand",
    "CommandAssembler",
]
