"""Delayed delivery: x-delay via a broker timer wheel.

RabbitMQ ships this as the delayed-message-exchange plugin; here it is a
publish-path feature (EXCEEDS the reference, which has no timers beyond
per-entity TTL sweeps, MessageEntity.scala:168-198). A publish whose
headers carry ``x-delay: <ms>`` parks in a hashed timer wheel instead of
routing; when the delay elapses it re-enters the NORMAL publish path
with the header stripped. Because routing happens at fire time, a
delayed message naturally survives topology churn in between — the queue
it would have landed in may be deleted and recreated, or its bindings
rewired, and the fire simply routes against whatever exists then
(unroutable fires drop, plugin parity: mandatory is not honored for
delayed publishes).

Parked bodies are resident broker memory, so they are accounted through
the PR 9 MemoryAccountant like queued bodies — a flood of long-delay
publishes walks the flow ladder instead of growing silently.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Optional

from .. import events

log = logging.getLogger("chanamq.semantics")

# one wheel turn at the default tick covers 512 * 50ms = 25.6s; longer
# delays just ride multiple turns (entries carry their absolute tick)
DEFAULT_TICK_MS = 50
DEFAULT_SLOTS = 512

# clamp ceiling, mirroring the delayed-message-exchange plugin's
# ERL_MAX_T-derived bound (~49.7 days); an absurd x-delay is a client
# bug, not a reason to pin memory for years
MAX_DELAY_MS = (1 << 32) - 1


def parse_delay(headers: Optional[dict]) -> Optional[int]:
    """Effective x-delay in ms, or None when the publish is immediate.
    Non-positive and non-integer values mean "no delay" (the plugin
    routes those immediately rather than erroring)."""
    if not headers:
        return None
    d = headers.get("x-delay")
    if isinstance(d, bool) or not isinstance(d, int) or d <= 0:
        return None
    return min(d, MAX_DELAY_MS)


class TimerWheel:
    """Hashed timer wheel: slots of pending entries, advanced tick by
    tick. schedule() is O(1); advance() touches only the slot under the
    cursor. Entries carry their absolute due tick, so a slot shared by
    multiple wheel turns fires only what is actually due."""

    __slots__ = ("tick_ms", "slots", "_wheel", "_tick", "_count")

    def __init__(self, tick_ms: int = DEFAULT_TICK_MS,
                 slots: int = DEFAULT_SLOTS) -> None:
        self.tick_ms = tick_ms
        self.slots = slots
        self._wheel: list[list] = [[] for _ in range(slots)]
        self._tick = 0
        self._count = 0

    def schedule(self, delay_ms: int, item: Any) -> None:
        ticks = max(1, -(-delay_ms // self.tick_ms))  # ceil, min one tick
        due = self._tick + ticks
        self._wheel[due % self.slots].append((due, item))
        self._count += 1

    def advance(self, ticks: int = 1) -> list:
        """Move the cursor forward, returning every entry that came due
        (in schedule order within a tick)."""
        fired: list = []
        for _ in range(ticks):
            self._tick += 1
            slot = self._wheel[self._tick % self.slots]
            if not slot:
                continue
            keep = []
            for due, item in slot:
                if due <= self._tick:
                    fired.append(item)
                else:
                    keep.append((due, item))  # a later wheel turn's entry
            slot[:] = keep
        self._count -= len(fired)
        return fired

    def __len__(self) -> int:
        return self._count


class DelayService:
    """Owns the wheel and the single asyncio driver task.

    The driver runs only while entries are parked (spawned on first park,
    exits when the wheel drains), so an idle broker pays nothing. Fires
    re-publish synchronously on single-node brokers — the same eager
    path Tx commits rely on — and via a spawned task when clustered.
    """

    def __init__(self, broker, tick_ms: int = DEFAULT_TICK_MS,
                 slots: int = DEFAULT_SLOTS) -> None:
        self.broker = broker
        self.wheel = TimerWheel(tick_ms=tick_ms, slots=slots)
        self._task = None

    def park(self, vhost: str, exchange: str, routing_key: str,
             properties, body: bytes, delay_ms: int) -> None:
        """Stage one delayed publish. The x-delay header is stripped NOW
        so the fire-time publish cannot re-park (and downstream consumers
        see the same headers the plugin would deliver)."""
        headers = dict(properties.headers)
        headers.pop("x-delay", None)
        props = properties.copy()
        props.headers = headers or None
        self.wheel.schedule(delay_ms, (vhost, exchange, routing_key, props, body))
        broker = self.broker
        broker.account_memory(len(body))
        broker.metrics.semantics_delayed_msgs += 1
        bus = events.ACTIVE
        if bus is not None:
            bus.emit("message.delayed", {
                "vhost": vhost, "exchange": exchange,
                "routing_key": routing_key, "delay_ms": delay_ms,
                "bytes": len(body),
            }, vhost_name=vhost)
        if self._task is None or self._task.done():
            self._task = asyncio.get_event_loop().create_task(self._run())
            broker._bg_tasks.add(self._task)
            self._task.add_done_callback(broker._bg_tasks.discard)

    async def _run(self) -> None:
        tick_s = self.wheel.tick_ms / 1000.0
        loop = asyncio.get_event_loop()
        last = loop.time()
        while len(self.wheel):
            await asyncio.sleep(tick_s)
            now = loop.time()
            elapsed_ticks = max(1, int((now - last) / tick_s))
            last += elapsed_ticks * tick_s
            for item in self.wheel.advance(elapsed_ticks):
                self._fire(item)

    def _fire(self, item: tuple) -> None:
        vhost, exchange, routing_key, props, body = item
        broker = self.broker
        broker.account_memory(-len(body))
        broker.metrics.semantics_delay_fired += 1
        if broker.cluster is None:
            try:
                broker.publish_sync(vhost, exchange, routing_key, props, body)
            except Exception as exc:  # topology vanished: drop, don't die
                log.warning("delayed publish to '%s' dropped: %s", exchange, exc)
        else:
            broker.spawn(self._publish_clustered(item))

    async def _publish_clustered(self, item: tuple) -> None:
        vhost, exchange, routing_key, props, body = item
        try:
            await self.broker.publish(vhost, exchange, routing_key, props, body)
        except Exception as exc:
            log.warning("delayed publish to '%s' dropped: %s", exchange, exc)

    def snapshot(self) -> dict:
        m = self.broker.metrics
        return {
            "parked": len(self.wheel),
            "tick_ms": self.wheel.tick_ms,
            "delayed_total": m.semantics_delayed_msgs,
            "fired_total": m.semantics_delay_fired,
        }
