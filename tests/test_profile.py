"""Continuous profiling: cost ledger, sampler/watchdog/GC hooks, admin
surface, and the bench-trajectory regression verdict.

Covers the PR-14 observability subsystem end to end: the fixed-stage
accumulators against a hand-driven oracle, the ``ACTIVE is None``
disabled path, folded-stack sampling of a synthetic busy loop, the
event-loop stall watchdog (capture + ring + counter + structured log
line), GC pause attribution, the /admin/profile route conventions
alongside the PR-6 telemetry ones, Prometheus export, and the pure
``regress_evaluate`` verdict on doctored trajectory records.
"""

import asyncio
import gc
import json
import logging
import threading
import time

import pytest

import bench
from chanamq_tpu import profile
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.profile.runtime import ProfileRuntime
from chanamq_tpu.profile.sampler import fold_stack
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.utils.logjson import JsonLogFormatter
from chanamq_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.asyncio


async def http_req(port: int, path: str, method: str = "GET") -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 20), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(body) if body else {}


async def http_req_text(port: int, path: str) -> tuple[int, str, str]:
    """GET returning (status, content-type, body-text) for text routes."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 20), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    ctype = ""
    for line in lines[1:]:
        if line.lower().startswith("content-type:"):
            ctype = line.split(":", 1)[1].strip()
    return int(lines[0].split()[1]), ctype, body.decode()


# ---------------------------------------------------------------------------
# ledger accumulators vs oracle
# ---------------------------------------------------------------------------


def test_ledger_matches_oracle():
    rt = ProfileRuntime(gc_hook=False)
    # drive the accumulators the way the seams do and keep a dict oracle
    oracle_ns = {}
    oracle_calls = {}
    plan = [
        (profile.ROUTE, 1500, 3),
        (profile.ENQUEUE, 2500, 3),
        (profile.ROUTE, 700, 1),
        (profile.WAL_APPEND, 9000, 1),
        (profile.DISPATCH, 50_000, 1),
        (profile.DELIVER, 50_000, 4),  # shares the dispatch window
    ]
    for stage, dt, calls in plan:
        rt.note(stage, dt, calls)
        oracle_ns[stage] = oracle_ns.get(stage, 0) + dt
        oracle_calls[stage] = oracle_calls.get(stage, 0) + calls
    for stage, want in oracle_ns.items():
        assert int(rt.stage_ns[stage]) == want
        assert int(rt.stage_calls[stage]) == oracle_calls[stage]
    snap = rt.snapshot()
    route = snap["stages"]["route"]
    assert route["ns"] == 2200 and route["calls"] == 4
    assert route["us_per_call"] == round(2200 / 4 / 1000.0, 3)
    # busy = top-level windows only; fine stages must not inflate it
    assert snap["busy_ns"] == 50_000
    # subsystem rollup sums the fine stages only, never top-level or GC
    assert snap["subsystems"]["router"]["ns"] == 2200
    assert snap["subsystems"]["wal"]["ns"] == 9000
    # enqueue + deliver only: the 50 µs dispatch window itself stays out
    assert snap["subsystems"]["broker"]["ns"] == 2500 + 50_000


def test_ledger_hand_timed_window():
    """A real timed busy window lands in the right stage within a loose
    tolerance (the accumulator is exact; the tolerance covers the timer
    reads around the busy loop)."""
    rt = ProfileRuntime(gc_hook=False)
    t0 = time.perf_counter_ns()
    deadline = t0 + 20_000_000  # 20 ms
    x = 0
    while time.perf_counter_ns() < deadline:
        x += 1
    dt = time.perf_counter_ns() - t0
    rt.note(profile.SETTLE, dt)
    got = int(rt.stage_ns[profile.SETTLE])
    assert got == dt
    assert 15_000_000 < got < 500_000_000
    detail = rt.stage_detail("settle")
    assert detail["calls"] == 1 and detail["ns"] == dt
    assert rt.stage_detail("not-a-stage") is None


def test_disabled_path_and_clear():
    # the module gate defaults to off: seams see None and skip everything
    assert profile.ACTIVE is None
    rt = profile.install(ProfileRuntime(gc_hook=False))
    assert profile.ACTIVE is rt
    prof = profile.ACTIVE
    if prof is not None:  # the exact seam shape used on hot paths
        prof.stage_ns[profile.ROUTE] += 10
        prof.stage_calls[profile.ROUTE] += 1
    assert int(rt.stage_ns[profile.ROUTE]) == 10
    profile.clear()
    assert profile.ACTIVE is None
    # cleared: the seam gate short-circuits, nothing accumulates anywhere
    prof = profile.ACTIVE
    assert prof is None


def test_stage_table_shape():
    # append-only contract: indices are load-bearing for Prometheus series
    assert profile.STAGES.index("route") == profile.ROUTE
    assert profile.STAGES.index("ingress-cycle") == profile.INGRESS_CYCLE
    assert len(profile.STAGES) == len(profile.SUBSYSTEMS)
    assert profile.TOP_LEVEL <= set(range(len(profile.STAGES)))
    assert profile.GC not in profile.TOP_LEVEL


# ---------------------------------------------------------------------------
# sampler: folded stacks + watchdog + GC
# ---------------------------------------------------------------------------


def _busy_ms(ms: float) -> None:
    deadline = time.perf_counter() + ms / 1000.0
    while time.perf_counter() < deadline:
        pass


def test_fold_stack_format():
    import sys

    frame = sys._getframe()
    folded = fold_stack(frame)
    parts = folded.split(";")
    assert parts, folded
    # leaf is this function, rendered as `name (file:line)`
    assert parts[-1].startswith("test_fold_stack_format (")
    assert "test_profile.py:" in parts[-1]


def test_sampler_folds_busy_thread_stacks():
    rt = ProfileRuntime(sample_hz=200, slow_callback_ms=0, gc_hook=False)
    rt.start()  # no running loop: ledger + sampler only
    # repoint the sampler at a synthetic "loop" thread we keep busy
    # (start() stamps the caller's thread id, so repoint afterwards)
    ready = threading.Event()
    stop = threading.Event()

    def pinned_loop():
        ready.set()
        while not stop.is_set():
            _busy_ms(1)

    t = threading.Thread(target=pinned_loop, daemon=True)
    t.start()
    ready.wait(5)
    rt.loop_thread_id = t.ident
    try:
        deadline = time.time() + 5
        while time.time() < deadline and rt.sampler.samples < 10:
            time.sleep(0.02)
        assert rt.sampler.samples >= 10
        collapsed = rt.collapsed()
        assert collapsed
        stack, _, count = collapsed.splitlines()[0].rpartition(" ")
        assert int(count) >= 1 and ";" in stack
        assert any("pinned_loop" in ln or "_busy_ms" in ln
                   for ln in collapsed.splitlines())
        snap = rt.snapshot()
        assert snap["sampler"]["samples"] == rt.sampler.samples
        assert snap["sampler"]["distinct_stacks"] >= 1
    finally:
        stop.set()
        rt.stop()
        t.join(5)


async def test_watchdog_captures_slow_callback(caplog):
    rt = ProfileRuntime(sample_hz=0, slow_callback_ms=40, ring_size=8,
                        gc_hook=False)
    rt.start()
    try:
        await asyncio.sleep(0.05)  # let the heartbeat establish a beat
        with caplog.at_level(logging.WARNING, logger="chanamq.profile"):
            _busy_ms(300)  # pin the loop well past threshold + 2 ticks
            # yield so the heartbeat resumes and the episode closes
            deadline = time.time() + 5
            while time.time() < deadline and rt.sampler.slow_count == 0:
                await asyncio.sleep(0.02)
        assert rt.sampler.slow_count >= 1
        entry = rt.sampler.ring[-1]
        assert entry["duration_ms"] >= 40
        assert entry["stack"]  # the offending callback got a name
        snap = rt.snapshot()
        assert snap["slow_callbacks"]["count"] == rt.sampler.slow_count
        assert snap["slow_callbacks"]["recent"]
        # the structured log line carried the folded stack via extra=data
        recs = [r for r in caplog.records if r.name == "chanamq.profile"]
        assert recs and getattr(recs[-1], "data")["stack"] == entry["stack"]
    finally:
        rt.stop()


def test_watchdog_bumps_metric_counter():
    m = Metrics()
    rt = ProfileRuntime(metrics=m, sample_hz=0, slow_callback_ms=40,
                        gc_hook=False)
    rt.sampler = None
    from chanamq_tpu.profile.sampler import Sampler

    s = Sampler(rt)
    rt.sampler = s
    s._stall_beat = 1
    s._stall_max_ns = 50_000_000
    s._stall_stack = "a;b;c"
    s._finish_stall()
    assert s.slow_count == 1
    assert m.profile_slow_callbacks_total == 1
    assert m.snapshot()["profile_slow_callbacks_total"] == 1


def test_gc_pause_capture():
    m = Metrics()
    rt = ProfileRuntime(metrics=m, gc_hook=True)
    rt.start()
    try:
        before = rt.gc_pauses
        gc.collect()
        assert rt.gc_pauses > before
        assert rt.gc_pause_ns > 0
        assert int(rt.stage_calls[profile.GC]) == rt.gc_pauses
        assert int(rt.stage_ns[profile.GC]) == rt.gc_pause_ns
        assert rt.gc_max_pause_ns <= rt.gc_pause_ns
        assert m.profile_gc_pauses_total == rt.gc_pauses
        snap = rt.snapshot()
        assert snap["gc"]["pauses"] == rt.gc_pauses
    finally:
        rt.stop()
    # stop() unhooks: further collections no longer accumulate
    after = rt.gc_pauses
    gc.collect()
    assert rt.gc_pauses == after


def test_logjson_merges_data_dict():
    fmt = JsonLogFormatter()
    rec = logging.LogRecord("chanamq.profile", logging.WARNING, __file__, 1,
                            "slow event-loop callback: %.1f ms", (51.2,), None)
    rec.data = {"node": "n1:5672", "duration_ms": 51.2, "stack": "a;b 1"}
    out = json.loads(fmt.format(rec))
    assert out["node"] == "n1:5672"
    assert out["duration_ms"] == 51.2
    assert out["stack"] == "a;b 1"
    assert out["msg"].startswith("slow event-loop callback")


# ---------------------------------------------------------------------------
# admin surface (PR-6 conventions)
# ---------------------------------------------------------------------------


@pytest.fixture
async def profile_stack():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    rt = ProfileRuntime(metrics=server.broker.metrics, sample_hz=100,
                        slow_callback_ms=0, broker=server.broker)
    server.broker.profile = rt
    profile.install(rt)
    rt.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    yield server, admin, rt
    profile.clear()
    server.broker.profile = None
    await admin.stop()
    await server.stop()


async def test_admin_profile_get_and_405(profile_stack):
    server, admin, rt = profile_stack
    # traffic so the ledger has something: publish through a real client
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    ch = await c.channel()
    await ch.queue_declare("pq")
    for i in range(30):
        ch.basic_publish(b"x" * 64, routing_key="pq")
    await asyncio.sleep(0.2)
    await c.close()

    status, snap = await http_req(admin.bound_port, "/admin/profile")
    assert status == 200
    assert set(snap["stages"]) == set(profile.STAGES)
    assert snap["stages"]["route"]["calls"] >= 30
    assert snap["stages"]["enqueue"]["calls"] >= 30
    assert snap["busy_ns"] > 0 and snap["loop_cpu_ns"] > 0
    assert snap["node"] == server.broker.trace_node

    status, body = await http_req(admin.bound_port, "/admin/profile", "POST")
    assert status == 405 and body == {"error": "use GET"}

    status, det = await http_req(admin.bound_port, "/admin/profile/stage/route")
    assert status == 200 and det["stage"] == "route" and det["calls"] >= 30
    status, body = await http_req(
        admin.bound_port, "/admin/profile/stage/nope")
    assert status == 404 and "unknown stage" in body["error"]


async def test_admin_profile_stacks_text(profile_stack):
    server, admin, rt = profile_stack
    deadline = time.time() + 5
    while time.time() < deadline and rt.sampler.samples < 5:
        await asyncio.sleep(0.02)
    status, ctype, text = await http_req_text(
        admin.bound_port, "/admin/profile/stacks")
    assert status == 200
    assert ctype.startswith("text/plain")
    assert text.strip()
    stack, _, count = text.splitlines()[0].rpartition(" ")
    assert int(count) >= 1 and ";" in stack


async def test_admin_profile_disabled_409():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        for path in ("/admin/profile", "/admin/profile/stacks",
                     "/admin/profile/stage/route"):
            status, body = await http_req(admin.bound_port, path)
            assert status == 409, path
            assert "disabled" in body["error"], path
    finally:
        await admin.stop()
        await server.stop()


async def test_admin_profile_stacks_409_without_sampler():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    rt = ProfileRuntime(sample_hz=0, slow_callback_ms=0, gc_hook=False,
                        broker=server.broker)
    server.broker.profile = rt
    rt.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        status, body = await http_req(admin.bound_port,
                                      "/admin/profile/stacks")
        assert status == 409 and "sample-hz" in body["error"]
        # the snapshot itself still serves fine without the sampler
        status, snap = await http_req(admin.bound_port, "/admin/profile")
        assert status == 200 and snap["sampler"]["hz"] == 0
    finally:
        rt.stop()
        server.broker.profile = None
        await admin.stop()
        await server.stop()


async def test_prometheus_profile_series(profile_stack):
    server, admin, rt = profile_stack
    rt.note(profile.ROUTE, 12345, 7)
    status, ctype, text = await http_req_text(admin.bound_port, "/metrics")
    assert status == 200
    assert 'chanamq_profile_stage_ns_total{stage="route"}' in text
    assert 'chanamq_profile_stage_calls_total{stage="route"}' in text
    for name in profile.STAGES:
        assert f'stage="{name}"' in text, name
    assert "chanamq_profile_samples_total" in text
    assert "chanamq_profile_gc_pauses_total" in text


# ---------------------------------------------------------------------------
# regression verdict on doctored trajectory records
# ---------------------------------------------------------------------------


def _rec(wall, cpu, scenario="s"):
    return {"scenario": scenario, "us_per_msg": wall, "cpu_us_per_msg": cpu}


def test_regress_both_over_fails():
    v = bench.regress_evaluate(_rec(130.0, 23.0), _rec(100.0, 20.0))
    assert v["wall_over"] and v["cpu_over"] and v["regressed"]


def test_regress_single_band_noise_passes():
    # wall spiked (steal burst) but CPU held: not a regression
    v = bench.regress_evaluate(_rec(130.0, 20.5), _rec(100.0, 20.0))
    assert v["wall_over"] and not v["cpu_over"] and not v["regressed"]
    # CPU crept but wall held: not a regression either
    v = bench.regress_evaluate(_rec(105.0, 25.0), _rec(100.0, 20.0))
    assert v["cpu_over"] and not v["wall_over"] and not v["regressed"]


def test_regress_wall_only_fallback():
    # old baseline without the CPU ledger: wall alone decides
    v = bench.regress_evaluate(_rec(130.0, 23.0),
                               {"scenario": "s", "us_per_msg": 100.0})
    assert v["regressed"]
    v = bench.regress_evaluate(_rec(115.0, 23.0),
                               {"scenario": "s", "us_per_msg": 100.0})
    assert not v["regressed"]


def test_regress_boundary_is_strict():
    # exactly at the band edge is NOT over — strictly greater regresses
    v = bench.regress_evaluate(_rec(120.0, 22.0), _rec(100.0, 20.0))
    assert not v["wall_over"] and not v["cpu_over"] and not v["regressed"]


def test_trajectory_baseline_env_matching(tmp_path):
    env = bench._env_fingerprint()
    path = tmp_path / "traj.jsonl"
    other = dict(env, cores=(env["cores"] or 0) + 64)
    lines = [
        {"scenario": "s", "us_per_msg": 10.0, "env": env, "ts": 1},
        {"scenario": "s", "us_per_msg": 99.0, "env": other, "ts": 2},
        {"scenario": "t", "us_per_msg": 55.0, "env": env, "ts": 3},
        {"scenario": "s", "us_per_msg": 12.0, "env": env, "ts": 4},
    ]
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
        f.write("not json\n")  # corrupt tail lines are skipped, not fatal
    base = bench.trajectory_baseline("s", str(path))
    # latest matching-env line for the scenario wins
    assert base["ts"] == 4 and base["us_per_msg"] == 12.0
    assert bench.trajectory_baseline("missing", str(path)) is None
    assert bench.trajectory_baseline("s", str(tmp_path / "ghost")) is None
