"""Routing matcher unit tests (the reference's TrieMatcher.main self-test
coverage, QueueMatcher.scala:75-139, extended with '#' and headers)."""

from chanamq_tpu.broker.matchers import (
    DirectMatcher,
    FanoutMatcher,
    HeadersMatcher,
    TopicMatcher,
    matcher_for,
)


def test_direct_exact_match():
    m = DirectMatcher()
    assert m.bind("k1", "q1")
    assert not m.bind("k1", "q1")  # duplicate
    m.bind("k1", "q2")
    m.bind("k2", "q3")
    assert m.route("k1") == {"q1", "q2"}
    assert m.route("k2") == {"q3"}
    assert m.route("k3") == set()
    assert m.unbind("k1", "q1")
    assert not m.unbind("k1", "q1")
    assert m.route("k1") == {"q2"}


def test_fanout_ignores_key():
    m = FanoutMatcher()
    m.bind("a", "q1")
    m.bind("b", "q2")
    assert m.route("anything") == {"q1", "q2"}
    m.unbind("a", "q1")
    assert m.route("x") == {"q2"}


def test_fanout_multiple_keys_same_queue():
    m = FanoutMatcher()
    m.bind("a", "q1")
    m.bind("b", "q1")
    m.unbind("a", "q1")
    assert m.route("x") == {"q1"}  # still bound via key b
    m.unbind("b", "q1")
    assert m.route("x") == set()


def test_topic_star_single_word():
    m = TopicMatcher()
    m.bind("stock.*.nyse", "q1")
    assert m.route("stock.ibm.nyse") == {"q1"}
    assert m.route("stock.goog.nyse") == {"q1"}
    assert m.route("stock.nyse") == set()
    assert m.route("stock.ibm.x.nyse") == set()


def test_topic_exact_and_star_coexist():
    m = TopicMatcher()
    m.bind("a.b.c", "exact")
    m.bind("a.*.c", "star")
    m.bind("*.b.c", "star2")
    assert m.route("a.b.c") == {"exact", "star", "star2"}
    assert m.route("a.x.c") == {"star"}
    assert m.route("z.b.c") == {"star2"}


def test_topic_hash_zero_or_more():
    m = TopicMatcher()
    m.bind("stock.#", "all_stock")
    m.bind("#", "everything")
    m.bind("#.nyse", "nyse_suffix")
    assert m.route("stock") == {"all_stock", "everything"}
    assert m.route("stock.ibm") == {"all_stock", "everything"}
    assert m.route("stock.ibm.nyse") == {"all_stock", "everything", "nyse_suffix"}
    assert m.route("nyse") == {"everything", "nyse_suffix"}
    assert m.route("bond") == {"everything"}


def test_topic_hash_middle():
    m = TopicMatcher()
    m.bind("a.#.z", "q")
    assert m.route("a.z") == {"q"}
    assert m.route("a.b.z") == {"q"}
    assert m.route("a.b.c.z") == {"q"}
    assert m.route("a.b") == set()


def test_topic_unbind_prunes():
    m = TopicMatcher()
    m.bind("a.b.c", "q1")
    m.bind("a.b", "q2")
    assert m.unbind("a.b.c", "q1")
    assert m.route("a.b.c") == set()
    assert m.route("a.b") == {"q2"}
    assert not m.unbind("a.b.c", "q1")
    # internal trie pruned back to just a.b
    assert m.bindings() == [("a.b", "q2", None)]


def test_topic_unbind_queue_bulk():
    m = TopicMatcher()
    m.bind("a.*", "q1")
    m.bind("b.*", "q1")
    m.bind("a.*", "q2")
    assert m.unbind_queue("q1") == 2
    assert m.route("a.x") == {"q2"}
    assert m.route("b.x") == set()


def test_headers_all_match():
    m = HeadersMatcher()
    m.bind("", "q1", {"x-match": "all", "type": "report", "fmt": "pdf"})
    assert m.route("", {"type": "report", "fmt": "pdf"}) == {"q1"}
    assert m.route("", {"type": "report", "fmt": "pdf", "extra": 1}) == {"q1"}
    assert m.route("", {"type": "report"}) == set()
    assert m.route("", {"type": "memo", "fmt": "pdf"}) == set()


def test_headers_any_match():
    m = HeadersMatcher()
    m.bind("", "q1", {"x-match": "any", "a": 1, "b": 2})
    assert m.route("", {"a": 1}) == {"q1"}
    assert m.route("", {"b": 2, "c": 3}) == {"q1"}
    assert m.route("", {"a": 9}) == set()
    assert m.route("", {}) == set()


def test_headers_empty_bindings_and_unbind():
    m = HeadersMatcher()
    m.bind("", "qall", {"x-match": "all"})       # empty all: matches anything
    m.bind("", "qany", {"x-match": "any"})       # empty any: never matches
    m.bind("", "q1", {"x-match": "all", "k": "v"})
    assert m.route("", {}) == {"qall"}
    assert m.route("", {"k": "v"}) == {"qall", "q1"}
    assert m.unbind("", "q1", {"x-match": "all", "k": "v"})
    assert m.route("", {"k": "v"}) == {"qall"}
    assert m.unbind_queue("qall") == 1
    assert m.route("", {"k": "v"}) == set()


def test_headers_unhashable_values_still_route():
    """Field-table arrays are unhashable: those bindings take the verified
    fallback bucket and must still match/unmatch correctly."""
    m = HeadersMatcher()
    m.bind("", "q1", {"x-match": "all", "tags": [1, 2]})
    m.bind("", "q2", {"x-match": "any", "tags": [1, 2], "k": "v"})
    assert m.route("", {"tags": [1, 2]}) == {"q1", "q2"}
    assert m.route("", {"tags": [9]}) == set()
    assert m.route("", {"k": "v"}) == {"q2"}
    # unhashable MESSAGE header against hashable bindings: no crash, no match
    m2 = HeadersMatcher()
    m2.bind("", "q3", {"x-match": "any", "k": "v"})
    assert m2.route("", {"k": [1]}) == set()


def test_headers_index_scales_route_not_bindings():
    """Route cost rides the index: with 2000 bindings on distinct values a
    route touches only its own candidates (observable: correctness over a
    large binding set; the per-route scan of every binding is gone)."""
    m = HeadersMatcher()
    for i in range(2000):
        m.bind("", f"q{i}", {"x-match": "all", "shard": i})
    assert m.route("", {"shard": 1234}) == {"q1234"}
    assert m.route("", {"shard": -1}) == set()


def test_matcher_factory():
    from chanamq_tpu import native_ext

    assert isinstance(matcher_for("direct"), DirectMatcher)
    assert isinstance(matcher_for("fanout"), FanoutMatcher)
    topic = matcher_for("topic")
    if native_ext.available():
        assert isinstance(topic, native_ext.NativeTopicMatcher)
    else:
        assert isinstance(topic, TopicMatcher)
    assert isinstance(matcher_for("headers"), HeadersMatcher)


def test_topic_matchers_agree_randomized():
    """Seeded property test: the Python TopicMatcher, the native C++ trie,
    and a brute-force reference evaluator must agree on every (pattern
    set, routing key) pair across random topologies — including `*`/`#`
    in every position, empty words, and bind/unbind churn."""
    import random

    from chanamq_tpu import native_ext
    from chanamq_tpu.broker.matchers import TopicMatcher

    def naive_match(pattern: str, key: str) -> bool:
        # textbook recursive AMQP topic match over '.'-split words
        def rec(p, k):
            if not p:
                return not k
            if p[0] == "#":
                return any(rec(p[1:], k[i:]) for i in range(len(k) + 1))
            if not k:
                return False
            if p[0] == "*" or p[0] == k[0]:
                return rec(p[1:], k[1:])
            return False
        return rec(pattern.split("."), key.split("."))

    rng = random.Random(0x70C1C)
    words = ["a", "b", "cc", "*", "#"]
    key_words = ["a", "b", "cc", "d"]
    matchers = [TopicMatcher()]
    if native_ext.available():
        matchers.append(native_ext.NativeTopicMatcher())
    bound: set[tuple[str, str]] = set()
    for trial in range(400):
        op = rng.random()
        if op < 0.5 or not bound:
            pattern = ".".join(rng.choice(words)
                               for _ in range(rng.randrange(1, 5)))
            queue = f"q{rng.randrange(6)}"
            for m in matchers:
                m.bind(pattern, queue)
            bound.add((pattern, queue))
        elif op < 0.65:
            pattern, queue = rng.choice(sorted(bound))
            for m in matchers:
                m.unbind(pattern, queue)
            bound.discard((pattern, queue))
        key = ".".join(rng.choice(key_words)
                       for _ in range(rng.randrange(1, 5)))
        expected = {q for (p, q) in bound if naive_match(p, key)}
        for m in matchers:
            got = m.route(key)
            assert got == expected, (
                f"{type(m).__name__} diverged on key={key!r}: "
                f"{got} != {expected}; bound={sorted(bound)}")


def test_headers_matcher_agrees_with_naive_model():
    """Seeded property test: the inverted-index HeadersMatcher must agree
    with a brute-force evaluator across random binding sets (x-match all
    and any, overlapping keys, absent headers, bind/unbind churn)."""
    import random

    from chanamq_tpu.broker.matchers import HeadersMatcher

    def naive_route(bindings, headers):
        out = set()
        headers = headers or {}
        for args, queue in bindings:
            pairs = {k: v for k, v in args.items() if not k.startswith("x-")}
            if not pairs:
                continue
            if args.get("x-match") == "any":
                ok = any(headers.get(k) == v for k, v in pairs.items())
            else:  # all (default)
                ok = all(headers.get(k) == v for k, v in pairs.items())
            if ok:
                out.add(queue)
        return out

    rng = random.Random(0x4EAD)
    keys = ["fmt", "region", "tier"]
    vals = ["a", "b", 1, 2]
    matcher = HeadersMatcher()
    bound: list[tuple[dict, str]] = []
    for trial in range(300):
        if rng.random() < 0.5 or not bound:
            args = {k: rng.choice(vals)
                    for k in rng.sample(keys, rng.randrange(1, 3))}
            if rng.random() < 0.5:
                args["x-match"] = rng.choice(["all", "any"])
            queue = f"q{rng.randrange(5)}"
            # HeadersMatcher dedupes on (args, queue); mirror that
            if not any(a == args and q == queue for a, q in bound):
                matcher.bind("", queue, args)
                bound.append((dict(args), queue))
        elif rng.random() < 0.3:
            args, queue = bound.pop(rng.randrange(len(bound)))
            matcher.unbind("", queue, args)
        headers = {k: rng.choice(vals)
                   for k in rng.sample(keys, rng.randrange(0, 4))}
        if rng.random() < 0.1:
            headers = None
        expected = naive_route(bound, headers)
        got = matcher.route("ignored", headers)
        assert got == expected, (trial, headers, sorted(
            (a, q) for a, q in bound), got, expected)
