"""Aux JAX analytics model tests: forward, train step, and mesh sharding on
the virtual 8-device CPU mesh (conftest sets the XLA flags)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


@pytest.fixture(scope="module", autouse=True)
def force_cpu():
    # the axon sitecustomize pins the TPU platform; tests use the CPU mesh
    jax.config.update("jax_platforms", "cpu")


def small_cfg():
    from chanamq_tpu.models import ForecasterConfig

    return ForecasterConfig(seq_len=8, d_model=32, n_heads=4, d_ff=64, n_layers=2)


def test_forward_shape_and_dtype():
    from chanamq_tpu.models import forward, init_params, synthetic_batch

    cfg = small_cfg()
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, cfg)
    x, y = synthetic_batch(rng, cfg, batch=4)
    out = jax.jit(lambda p, x: forward(p, x, cfg))(params, x)
    assert out.shape == (4, cfg.n_features)
    assert out.dtype == np.float32
    assert np.isfinite(np.asarray(out)).all()


def test_train_step_reduces_loss():
    from chanamq_tpu.models import init_params, make_train_step, synthetic_batch
    from chanamq_tpu.models.forecaster import init_momentum

    cfg = small_cfg()
    rng = jax.random.PRNGKey(1)
    params = init_params(rng, cfg)
    momentum = init_momentum(params)
    step = jax.jit(make_train_step(cfg, lr=1e-2))
    batch = synthetic_batch(rng, cfg, batch=16)
    first_loss = None
    for _ in range(30):
        params, momentum, loss = step(params, momentum, batch)
        if first_loss is None:
            first_loss = float(loss)
    assert float(loss) < first_loss * 0.5, (first_loss, float(loss))


def test_sharded_train_step_on_8_device_mesh():
    from chanamq_tpu.models import init_params, make_train_step, synthetic_batch
    from chanamq_tpu.models.forecaster import init_momentum
    from chanamq_tpu.parallel import make_mesh, make_sharded_train_step
    from chanamq_tpu.parallel.mesh import place

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = small_cfg()
    mesh = make_mesh(8)
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"dp": 2, "tp": 4}
    rng = jax.random.PRNGKey(2)
    params = init_params(rng, cfg)
    momentum = init_momentum(params)
    batch = synthetic_batch(rng, cfg, batch=8)
    step = make_sharded_train_step(mesh, cfg, make_train_step(cfg))
    params, batch = place(mesh, params, batch)
    momentum, _ = place(mesh, momentum, batch)
    new_params, new_momentum, loss = step(params, momentum, batch)
    assert np.isfinite(float(loss))
    # params keep their shardings across steps (donation round-trips)
    qkv = new_params["layer0/attn/qkv"]
    assert not qkv.sharding.is_fully_replicated
    # sharded result must match single-device execution
    # (GSPMD-inserted collectives preserve the math)


def test_sharded_matches_single_device():
    from chanamq_tpu.models import forward, init_params, synthetic_batch
    from chanamq_tpu.parallel import make_mesh
    from chanamq_tpu.parallel.mesh import place

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = small_cfg()
    rng = jax.random.PRNGKey(3)
    params = init_params(rng, cfg)
    x, _ = synthetic_batch(rng, cfg, batch=8)
    single = jax.jit(lambda p, x: forward(p, x, cfg))(params, x)
    mesh = make_mesh(8)
    p_sharded, (x_sharded, _) = place(mesh, params, (x, x[:, 0]))
    sharded = jax.jit(lambda p, x: forward(p, x, cfg))(p_sharded, x_sharded)
    np.testing.assert_allclose(
        np.asarray(single), np.asarray(sharded), rtol=2e-2, atol=2e-2)
