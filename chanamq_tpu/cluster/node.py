"""Cluster node: location-transparent broker entities over the host mesh.

The rebuild of the reference's Akka-cluster distribution (SURVEY.md §5
"distributed communication backend", §3.6 failover):

- **Exchanges, bindings, vhosts are replicated** to every node (broadcast on
  mutation + snapshot pull on join), so publish routing is always local —
  where the reference paid a cluster `ask` per publish to a sharded
  ExchangeEntity (ExchangeEntity.scala:287-331), here only the per-queue
  pushes leave the node.
- **Queues are sharded** by consistent hash over alive members (the analogue
  of shard-id % 100 placement, QueueEntity.scala:43-51). Queue ops arriving
  on a non-owner node are proxied over RPC. Exclusive queues stay pinned to
  the connection's node and are never clustered.
- **Remote consumers** stream deliveries owner -> origin with a credit
  window (the QoS budget the reference computed per Pull,
  FrameStage.scala:387-392, becomes an explicit credit grant on ack).
- **Failover** (reference §3.6): node dies -> membership marks DOWN -> ring
  excludes it -> next op (or consumer re-registration) activates the queue
  on its new owner, which reloads durable state from the shared store.
  Transient queue contents die with their node, matching the reference's HA
  contract (README.md:47-49).
- **Cluster-wide worker ids** for snowflake message ids are leased from the
  current leader (lowest alive member - the reference's GlobalNodeIdService
  singleton, GlobalNodeIdService.scala:15-72).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Any, Optional

from .. import trace
from ..amqp.properties import BasicProperties
from ..flow import STAGE_CLUSTER
from ..replicate import ReplicationManager
from . import dataplane as dp
from .dataplane import PeerDataPlane
from .hashring import HashRing
from .membership import Member, Membership
from .rpc import RpcError, RpcServer, UdsTransport

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker
    from ..broker.channel import ServerChannel
    from ..broker.entities import Delivery, Queue, QueuedMessage

log = logging.getLogger("chanamq.cluster")

DEFAULT_CREDIT = 200
# remote-consume prefetch window (chana.mq.cluster.consume-credit): sized so
# deliveries stream ahead of the settle round trip instead of stalling on it
DEFAULT_CONSUME_CREDIT = 1024

# decoded-properties memo for the binary push handler: publishers stream
# identical header payloads, so the owner decodes each distinct one once
# (same idea as the origin connection's _HEADER_CACHE)
_PROPS_MEMO: dict[bytes, BasicProperties] = {}
_PROPS_MEMO_MAX = 1024


def _props_memo(props_raw) -> BasicProperties:
    key = bytes(props_raw)
    props = _PROPS_MEMO.get(key)
    if props is None:
        _, _, props = BasicProperties.decode_header(key)
        if len(_PROPS_MEMO) >= _PROPS_MEMO_MAX:
            _PROPS_MEMO.clear()
        _PROPS_MEMO[key] = props
    return props


class ClusterNode:
    """Cluster extension attached to a Broker."""

    def __init__(
        self,
        broker: "Broker",
        host: str = "127.0.0.1",
        port: int = 0,
        seeds: Optional[list[str]] = None,
        *,
        virtual_nodes: int = 64,
        heartbeat_interval_s: float = 1.0,
        failure_timeout_s: float = 5.0,
        replicate_factor: int = 1,
        replicate_sync: bool = False,
        replicate_batch_max: int = 256,
        replicate_ack_timeout_ms: int = 1000,
        streams: int = 2,
        stream_inflight: int = 32,
        flush_window_us: int = 200,
        flush_max_bytes: int = 1 << 20,
        flush_max_count: int = 512,
        consume_credit: int = DEFAULT_CONSUME_CREDIT,
        call_timeout_s: float = 10.0,
        uds_path: Optional[str] = None,
        uds_map: Optional[dict[str, str]] = None,
        drain_retry_limit: int = 5,
        drain_backoff_ms: int = 100,
        drain_backoff_cap_ms: int = 2000,
        drain_budget_s: float = 30.0,
    ) -> None:
        self.broker = broker
        self.rpc = RpcServer(host, port, uds_path=uds_path)
        self._host = host
        self._seeds = seeds or []
        # sibling shards on this machine (member name -> Unix-socket
        # path): control and data planes toward them dial UDS, not TCP
        self.uds_map = dict(uds_map or {})
        self._hb = heartbeat_interval_s
        self._ft = failure_timeout_s
        self.membership: Optional[Membership] = None
        self.ring = HashRing([], virtual_nodes)
        # replicated queue-meta registry: (vhost, name) -> meta dict
        self.queue_metas: dict[tuple[str, str], dict] = {}
        # owner-side (vhost, name) -> activated local Queue, for the binary
        # push handler's per-record resolution. Cleared alongside the
        # broker's route caches (broker.invalidate_routes) on any queue /
        # holder / membership mutation.
        self.resolve_cache: dict[tuple[str, str], Any] = {}
        # origin-side registry of remote consumers for failover re-register:
        # (vhost, queue, tag) -> info
        self._remote_consumers: dict[tuple[str, str, str], dict] = {}
        # data-plane fast path (chana.mq.cluster.streams / flush-window-us /
        # flush-max-*): binary batched pushes, settles, and deliveries.
        # Keyed (peer name, transport kind) so a UDS sibling never shares
        # striping/backoff state with a same-named TCP peer.
        self._dataplanes: dict[tuple[str, str], PeerDataPlane] = {}
        self._dp_streams = max(1, streams)
        self._dp_inflight = max(1, stream_inflight)
        self._dp_flush_window_us = flush_window_us
        self._dp_flush_max_bytes = flush_max_bytes
        self._dp_flush_max_count = flush_max_count
        self.consume_credit = max(1, consume_credit)
        # default per-call ask window for control RPCs (individual calls
        # may still override — e.g. the 5 s snapshot pull at boot)
        self.call_timeout_s = call_timeout_s
        # metadata anti-entropy: broadcasts are fire-and-forget, so a peer
        # briefly unreachable (reconnect backoff during a sharded node's
        # boot, a blip mid-partition) can miss a queue.declared for good.
        # A periodic add-only snapshot merge from one rotating peer heals
        # those gaps without ever overwriting newer local state.
        self._anti_entropy_s = max(1.0, failure_timeout_s)
        self._anti_entropy_task: Optional[asyncio.Task] = None
        self.name: str = ""
        broker.cluster = self
        # flow-ladder stage 3 (cluster): shrink peer flush windows so
        # pushback propagates across shard/cluster hops (see dataplane())
        broker.flow_stage_listeners.add(self._on_flow_stage)
        self._register_handlers()
        # queue replication (chana.mq.replicate.*): factor 1 = off; the
        # manager registers its own repl.* RPC handlers
        self.replication: Optional[ReplicationManager] = (
            ReplicationManager(
                self, factor=replicate_factor, sync=replicate_sync,
                batch_max=replicate_batch_max,
                ack_timeout_ms=replicate_ack_timeout_ms)
            if replicate_factor > 1 else None)
        # graceful drain / decommission (chana.mq.lifecycle.*)
        from .lifecycle import LifecycleCoordinator

        self.lifecycle = LifecycleCoordinator(
            self, retry_limit=drain_retry_limit,
            backoff_ms=drain_backoff_ms,
            backoff_cap_ms=drain_backoff_cap_ms,
            budget_s=drain_budget_s)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        await self.rpc.start()
        self.name = f"{self._host}:{self.rpc.bound_port}"
        # span attribution for message traces: this broker's spans carry
        # the cluster name instead of the single-node "local"
        self.broker.trace_node = self.name
        if trace.ACTIVE is not None and trace.ACTIVE.node == "local":
            trace.ACTIVE.node = self.name
        self.membership = Membership(
            self.name, self._seeds, self.rpc,
            heartbeat_interval_s=self._hb, failure_timeout_s=self._ft,
            uds_map=self.uds_map)
        self.membership.listeners.append(self._on_membership_event)
        await self.membership.start()
        self.ring.set_nodes(self._ring_members())
        # pull metadata snapshot from the first reachable seed
        for seed in self._seeds:
            try:
                snapshot = await self.membership.client(seed).call(
                    "cluster.snapshot", {}, timeout_s=5)
                await self._apply_snapshot(snapshot)
                break
            except (RpcError, OSError):
                continue
        # deactivate local queues this node does not own (boot recovery
        # loaded everything; sharded ownership says otherwise)
        self._deactivate_unowned(boot=True)
        # lease a snowflake worker id from the leader (reference:
        # ServiceBoard blocking on AskNodeId, ServiceBoard.scala:40-48 —
        # but bounded and non-blocking here)
        import uuid as uuid_module

        from .idgen import IdGenerator, MAX_WORKER_ID

        try:
            worker_id = await asyncio.wait_for(
                self.acquire_worker_id(str(uuid_module.uuid4())), timeout=10)
            self.broker.idgen = IdGenerator(worker_id & MAX_WORKER_ID)
        except (asyncio.TimeoutError, RpcError, OSError):
            log.warning("%s: worker-id lease failed; keeping local id", self.name)
        self._anti_entropy_task = asyncio.get_event_loop().create_task(
            self._anti_entropy_loop())

    async def stop(self) -> None:
        if self.lifecycle._task is not None and \
                not self.lifecycle._task.done():
            self.lifecycle._task.cancel()
            try:
                await self.lifecycle._task
            except (asyncio.CancelledError, Exception):
                pass
        if self._anti_entropy_task is not None:
            self._anti_entropy_task.cancel()
            try:
                await self._anti_entropy_task
            except (asyncio.CancelledError, Exception):
                pass
            self._anti_entropy_task = None
        self.broker.flow_stage_listeners.discard(self._on_flow_stage)
        dataplanes, self._dataplanes = self._dataplanes, {}
        for plane in dataplanes.values():
            await plane.close()
        if self.membership is not None:
            await self.membership.stop()
        await self.rpc.stop()

    # ------------------------------------------------------------------
    # ownership
    # ------------------------------------------------------------------

    def queue_owner(self, vhost: str, name: str) -> str:
        """Where ops on this queue must go. A live HOLDER (the node actually
        serving the queue, replicated through queue metas) wins over the
        hash ring: on a ring reshuffle (node join) the old owner keeps
        serving a queue with live consumers/messages — routing to the new
        ring owner would activate a second copy from the shared store and
        deliver duplicates. The ring decides only when no live holder
        exists (fresh queue, holder died, or holder released when idle)."""
        meta = self.queue_metas.get((vhost, name))
        if meta is not None:
            holder = meta.get("holder")
            if holder and (holder == self.name
                           or self.membership.is_alive(holder)):
                return holder
        owner = self.ring.owner_entity("q", vhost, name)
        return owner or self.name

    def owns_queue(self, vhost: str, name: str) -> bool:
        return self.queue_owner(vhost, name) == self.name

    def is_remote_queue(self, vhost: str, name: str) -> bool:
        """True when ops on this queue must be proxied: it is a known
        clustered (non-exclusive) queue owned elsewhere."""
        vh = self.broker.vhosts.get(vhost)
        if vh is not None:
            queue = vh.queues.get(name)
            if queue is not None:
                # local exclusive queues are always local
                return False
        meta = self.queue_metas.get((vhost, name))
        if meta is None:
            return False
        return not self.owns_queue(vhost, name)

    def _deactivate_unowned(self, boot: bool = False) -> None:
        self.broker.invalidate_routes()
        for vhost in self.broker.vhosts.values():
            for name in list(vhost.queues):
                queue = vhost.queues[name]
                if queue.exclusive_owner is not None:
                    continue
                meta = self.queue_metas.get((vhost.name, name))
                other = meta.get("holder") if meta else None
                # at boot, membership is still converging: a named foreign
                # holder must be deferred to even before it gossips alive,
                # or a joiner that pre-recovered the shared store claims a
                # queue another node is actively serving
                foreign = bool(other and other != self.name
                               and (boot or self.membership.is_alive(other)))
                if foreign:
                    if boot and not queue.consumers and not queue.outstanding:
                        # we just booted and loaded this queue from the
                        # shared store while another node is (per the
                        # snapshot) actively serving it: our copy only
                        # duplicates its durable contents (transients never
                        # recover), so drop it — a second copy would
                        # deliver duplicates. If that holder is in fact
                        # dead, its down event clears the holdership and
                        # the ring owner reactivates from the store.
                        # Release the RAM gauge but do NOT unrefer: the
                        # store rows belong to the holder.
                        for qm in queue.messages:
                            msg = qm.message
                            if msg.accounted:
                                self.broker.account_memory(
                                    -len(msg.body or b""))
                                msg.accounted = False
                        queue.deleted = True
                        queue.gauges_detach()
                        del vhost.queues[name]
                        continue
                    if queue.consumers or queue.messages or queue.outstanding:
                        # dual-holder conflict at steady state (a claim
                        # race): resolve DETERMINISTICALLY — the
                        # lexicographically smaller name wins — so the two
                        # sides can't flip holdership back and forth with
                        # racing broadcasts. The loser keeps draining its
                        # copy to its already-attached local consumers but
                        # stops being a routing target for new ops.
                        if self.name < other:
                            log.warning(
                                "%s: reclaiming %s/%s from dual holder %s",
                                self.name, vhost.name, name, other)
                            self._register_meta(queue)
                            self._set_holder(vhost.name, name, self.name)
                        else:
                            log.warning(
                                "%s: deferring %s/%s to dual holder %s",
                                self.name, vhost.name, name, other)
                        continue
                    # idle local shell under a live foreign holder
                    queue.deleted = True
                    queue.gauges_detach()
                    del vhost.queues[name]
                    continue
                # no live foreign holder. Evaluate placement BEFORE any
                # claim so an idle shell hands off with at most one
                # broadcast instead of a claim-then-release pair.
                live = bool(queue.consumers or queue.messages
                            or queue.outstanding)
                ring_owned = (
                    self.ring.owner_entity("q", vhost.name, name) == self.name)
                if not ring_owned and not live:
                    # idle shell owned elsewhere by the ring: hand off
                    queue.deleted = True
                    queue.gauges_detach()
                    del vhost.queues[name]
                    if self.replication is not None:
                        # close (not delete) the outgoing log: the next
                        # owner opens its own from seq 0 and followers
                        # resync against it on the owner-change
                        self.replication.detach(vhost.name, name)
                    if other is not None:
                        self._set_holder(vhost.name, name, None)
                    continue
                # we keep serving (ring owner, or sticky live copy — a ring
                # reshuffle on join moves nothing mid-flight); broadcast the
                # claim only when the replicated view doesn't already say so
                self._register_meta(queue)
                if other != self.name:
                    self._set_holder(vhost.name, name, self.name)

    def _register_meta(self, queue: "Queue") -> None:
        # registering a live local queue claims holdership: ops for it must
        # come to this node while it serves consumers/messages
        self.broker.invalidate_routes()
        prev = self.queue_metas.get((queue.vhost, queue.name))
        self.queue_metas[(queue.vhost, queue.name)] = {
            "durable": queue.durable,
            "auto_delete": queue.auto_delete,
            "ttl_ms": queue.ttl_ms,
            "arguments": dict(queue.arguments or {}),
            "holder": self.name,
            # the fencing epoch survives re-registration: it only moves
            # forward, through _set_holder
            "epoch": int(prev.get("epoch") or 0) if prev is not None else 0,
        }

    def queue_epoch(self, vhost: str, name: str) -> int:
        meta = self.queue_metas.get((vhost, name))
        return int(meta.get("epoch") or 0) if meta is not None else 0

    def seat_epoch(self, vhost: str, name: str) -> int:
        """Seat a freshly declared queue at fencing epoch 1. Epoch 0 marks
        pre-fencing legacy traffic that the refusal checks deliberately
        wave through, so a declared queue must start above it for its very
        first ships to be fenceable. Re-declares keep the current epoch."""
        meta = self.queue_metas.get((vhost, name))
        if meta is None:
            return 0
        if not int(meta.get("epoch") or 0):
            meta["epoch"] = 1
        return int(meta["epoch"])

    def _set_holder(self, vhost: str, name: str, holder: Optional[str],
                    decision: Optional[str] = None) -> int:
        """Record + replicate who serves a queue (None = released: the
        hash ring decides again). Every holder change bumps the queue's
        monotonic FENCING EPOCH and stamps it on the broadcast: receivers
        (and replication ships) refuse anything carrying a lower epoch, so
        a partitioned ex-holder cannot reassert a queue that moved on
        without it. A control-plane rebalance stamps its decision id on
        the broadcast so every node's log links the move back to the
        decision (and its recorded inputs)."""
        self.broker.invalidate_routes()
        meta = self.queue_metas.get((vhost, name))
        epoch = (int(meta.get("epoch") or 0) if meta is not None else 0) + 1
        if meta is not None:
            meta["holder"] = holder
            meta["epoch"] = epoch
        payload = {
            "kind": "queue.holder", "vhost": vhost, "name": name,
            "holder": holder, "epoch": epoch,
        }
        if decision is not None:
            payload["decision"] = decision
        self.broadcast_bg("meta.apply", payload)
        return epoch

    def claim_queue(self, queue: "Queue") -> None:
        """Called by the broker when a queue materializes locally
        (declare/activate): this node becomes the holder cluster-wide."""
        if queue.exclusive_owner is not None:
            return
        self._register_meta(queue)
        self._set_holder(queue.vhost, queue.name, self.name)
        if self.replication is not None:
            self.replication.attach(queue)

    async def handoff_queue(self, vhost_name: str, name: str, target: str,
                            *, decision: Optional[str] = None) -> bool:
        """Proactively move holdership of a local queue to ``target`` (a
        control-plane rebalance decision). Reuses the exact machinery of
        the boot-time dual-copy drop (_deactivate_unowned): release the
        local copy's RAM accounting WITHOUT unreferring (the store rows
        now belong to the new holder), replicate the holder change, then
        activate on the target so it rematerializes durable content from
        the shared store. Callers must pre-check movability (no local
        consumers, no outstanding, durable-persisted content only) — this
        re-verifies and refuses rather than losing data."""
        broker = self.broker
        vhost = broker.vhosts.get(vhost_name)
        queue = vhost.queues.get(name) if vhost is not None else None
        if queue is None or queue.deleted or queue.is_stream:
            return False
        if queue.exclusive_owner is not None or queue.outstanding:
            return False
        if (vhost_name, name) not in self.queue_metas:
            return False
        if target == self.name or self.membership is None \
                or not self.membership.is_alive(target):
            return False
        if any(not isinstance(c, RemoteConsumer) for c in queue.consumers):
            return False  # local AMQP consumers cannot follow the queue
        if queue.messages and (
                not queue.durable
                or any(not qm.message.persisted for qm in queue.messages)):
            return False  # transient content would not survive the move
        if self.replication is not None and queue.durable \
                and not queue.is_stream:
            # private-store deployments: the target must hold a complete,
            # head-synced replica copy BEFORE holdership moves — it
            # materializes that copy when it activates. (Shared-store
            # deployments pass through here too; the copy just duplicates
            # rows the target could already see.)
            if not await self.replication.prepare_handoff(
                    vhost_name, name, target):
                return False
        # detach remote-consumer stubs; their origins re-register on the
        # new holder when the queue.holder broadcast lands
        for consumer in list(queue.consumers):
            queue.consumers.remove(consumer)
            if queue._counted:
                broker.queue_consumers -= 1
        for qm in queue.messages:
            msg = qm.message
            if msg.accounted:
                broker.account_memory(-len(msg.body or b""))
                msg.accounted = False
        queue.deleted = True
        queue.gauges_detach()
        del vhost.queues[name]
        if self.replication is not None:
            self.replication.detach(vhost_name, name)
        self._set_holder(vhost_name, name, target, decision=decision)
        # this node may itself consume from the moved queue
        if any(key[0] == vhost_name and key[1] == name
               for key in self._remote_consumers):
            asyncio.get_event_loop().create_task(self._reconcile_consumers())
        activated = False
        delay = 0.05
        for attempt in range(3):
            try:
                await self._call(target, "queue.activate",
                                 {"vhost": vhost_name, "name": name,
                                  "handoff": True})
                activated = True
                break
            except (RpcError, OSError) as exc:
                log.warning("%s: handoff activate of %s/%s on %s failed "
                            "(attempt %d: %s)", self.name, vhost_name, name,
                            target, attempt + 1, exc)
                self.broker.metrics.lifecycle_evacuation_retries += 1
                if self.membership is None \
                        or not self.membership.is_alive(target):
                    break  # target died: no point retrying it
                await asyncio.sleep(delay)
                delay *= 2
        if not activated:
            # roll holdership back: the store rows were never unreferred,
            # so re-activating locally rematerializes the full backlog and
            # re-claims with a FRESH epoch (so the aborted target claim
            # can't win a late race)
            self.broker.metrics.lifecycle_rollbacks += 1
            log.warning("%s: rolling %s/%s holdership back from %s",
                        self.name, vhost_name, name, target)
            await self.broker.activate_queue(vhost_name, name)
            return False
        log.info("%s: handed off %s/%s -> %s%s", self.name, vhost_name,
                 name, target,
                 f" (decision {decision})" if decision else "")
        return True

    # ------------------------------------------------------------------
    # membership reactions
    # ------------------------------------------------------------------

    @property
    def draining(self) -> bool:
        """True once this node entered DRAINING (or finished, LEFT): it
        keeps serving what it still holds but claims nothing new."""
        if self.membership is None:
            return False
        from .membership import DRAINING, LEFT

        me = self.membership.members.get(self.name)
        return me is not None and me.lifecycle in (DRAINING, LEFT)

    def _ring_members(self) -> list[str]:
        """Placement-eligible members for the ownership ring: draining and
        left nodes are excluded so no new holdership hashes onto them. If
        that empties the ring (every node draining), fall back to the full
        alive set — refusing all placement is worse than placing badly."""
        assert self.membership is not None
        placement = self.membership.placement_members()
        return placement or self.membership.alive_members()

    def _on_membership_event(self, event: str, member: Member) -> None:
        assert self.membership is not None
        self.broker.invalidate_routes()
        self.ring.set_nodes(self._ring_members())
        if event == "lifecycle":
            from .membership import LEFT

            if member.lifecycle == LEFT and member.name != self.name:
                # the member finished draining: any holdership still
                # pointing at it is a straggler the evacuation broadcasts
                # missed — clear it so the ring decides again
                for meta in self.queue_metas.values():
                    if meta.get("holder") == member.name:
                        meta["holder"] = None
                        self.broker.metrics.lifecycle_stale_holders_cleared \
                            += 1
            self._deactivate_unowned()
            asyncio.get_event_loop().create_task(self._reconcile_consumers())
            return
        if event == "down":
            # tear down the dead peer's data streams: buffered batches fail
            # fast instead of dialing a corpse until their timeouts
            for key in [k for k in self._dataplanes if k[0] == member.name]:
                plane = self._dataplanes.pop(key, None)
                if plane is not None:
                    asyncio.get_event_loop().create_task(plane.close())
            # one ownership re-hash per observed peer death — the soak's
            # "exactly-one re-hash" invariant counts these
            self.broker.metrics.shard_handoffs += 1
        if event == "down":
            # a dead node can't serve anything: clear its holderships so
            # queue_owner falls back to the ring (node names embed ephemeral
            # ports, so a stale holder entry would otherwise pin forever)
            for meta in self.queue_metas.values():
                if meta.get("holder") == member.name:
                    meta["holder"] = None
        if self.replication is not None:
            # BEFORE the reconcile task below is created: promotion intents
            # must be registered so activate_queue can await them instead of
            # cold-activating an empty shell over a warm replica
            if event == "down":
                self.replication.on_node_down(member.name)
            else:
                self.replication.on_membership()
        self._deactivate_unowned()
        # re-register remote consumers whose queues changed owner; also
        # requeue outstanding deliveries from consumers whose origin died
        if event == "down":
            self._drop_origin_consumers(member.name)
        asyncio.get_event_loop().create_task(self._reconcile_consumers())

    def _drop_origin_consumers(self, origin: str) -> None:
        for vhost in self.broker.vhosts.values():
            for queue in vhost.queues.values():
                for consumer in list(queue.consumers):
                    if isinstance(consumer, RemoteConsumer) and consumer.origin == origin:
                        consumer.requeue_outstanding()
                        queue.consumers.remove(consumer)
                        if queue._counted:
                            self.broker.queue_consumers -= 1

    _reconcile_retry_pending = False

    async def _reconcile_consumers(self) -> None:
        any_failed = False
        for (vhost, queue, tag), info in list(self._remote_consumers.items()):
            owner = self.queue_owner(vhost, queue)
            if owner == info.get("owner") and info.get("alive", True):
                continue
            try:
                if owner == self.name:
                    # queue came home: activate it locally; the origin-side
                    # stub keeps working because deliveries now come from
                    # the local dispatch through the same stub channel
                    local_queue = await self.broker.activate_queue(vhost, queue)
                    if local_queue is not None:
                        stub = info["stub"]
                        if stub not in local_queue.consumers:
                            local_queue.add_consumer(stub)
                    info["owner"] = owner
                    continue
                await self._call(owner, "queue.activate",
                                 {"vhost": vhost, "name": queue})
                await self._call(owner, "queue.consume", {
                    "vhost": vhost, "queue": queue, "tag": tag,
                    "no_ack": info["no_ack"], "origin": self.name,
                    "credit": info["credit"],
                    "priority": info.get("priority", 0),
                })
                info["owner"] = owner
                info["alive"] = True
                log.info("%s: re-registered consumer %s on %s", self.name, tag, owner)
            except (RpcError, OSError) as exc:
                log.warning("%s: consumer re-register failed (%s); retrying", self.name, exc)
                info["alive"] = False
                any_failed = True
        # exactly one pending retry regardless of how many consumers failed
        if any_failed and not self._reconcile_retry_pending:
            self._reconcile_retry_pending = True
            loop = asyncio.get_event_loop()

            def _retry() -> None:
                self._reconcile_retry_pending = False
                loop.create_task(self._reconcile_consumers())

            loop.call_later(1.0, _retry)

    # ------------------------------------------------------------------
    # RPC plumbing
    # ------------------------------------------------------------------

    async def _call(
        self, node: str, method: str, payload: dict,
        timeout_s: Optional[float] = None,
    ) -> dict:
        assert self.membership is not None
        # buffered/in-flight settles precede any control RPC: a cancel /
        # delete / purge issued after an ack in the same read batch must
        # find the ack applied on the owner (the data and control planes
        # are separate connections, so this fence is the only ordering)
        await self._drain_settles()
        return await self.membership.client(node).call(
            method, payload, timeout_s=timeout_s or self.call_timeout_s)

    def dataplane(self, node: str) -> PeerDataPlane:
        """The binary fast path toward a peer (lazily dialed, N streams).
        Sibling shards (uds_map) get a Unix-socket transport; remote nodes
        get TCP — the two never share a plane."""
        uds_path = self.uds_map.get(node)
        kind = "uds" if uds_path is not None else "tcp"
        plane = self._dataplanes.get((node, kind))
        if plane is None or plane.closed:
            if uds_path is not None:
                target: Any = UdsTransport(uds_path, peer=node)
                port = 0
            else:
                member = (self.membership.members.get(node)
                          if self.membership is not None else None)
                target, port = (member.host, member.port) \
                    if member is not None \
                    else (node.rsplit(":", 1)[0], int(node.rsplit(":", 1)[1]))
            plane = PeerDataPlane(
                target, port,
                streams=self._dp_streams,
                inflight_per_stream=self._dp_inflight,
                flush_window_us=self._dp_flush_window_us,
                flush_max_bytes=self._dp_flush_max_bytes,
                flush_max_count=self._dp_flush_max_count,
                metrics=self.broker.metrics,
                node_tag=self.name)
            flow = self.broker.flow
            plane.pressure = (flow is not None
                              and flow.stage >= STAGE_CLUSTER)
            self._dataplanes[(node, kind)] = plane
        return plane

    def _on_flow_stage(self, old: int, new: int) -> None:
        """Broker flow-ladder transition: at/above the cluster stage every
        peer data plane switches to pressure mode (flush caps shrink, so
        this node buffers less toward peers and the per-stream in-flight
        windows throttle the origin side sooner)."""
        pressured = new >= STAGE_CLUSTER
        for plane in self._dataplanes.values():
            plane.pressure = pressured

    def dataplane_buffered_bytes(self) -> int:
        """Bytes accumulated toward peers but not yet flushed — the flow
        accountant's ``cluster_inflight`` component, polled per sweep."""
        total = 0
        for plane in self._dataplanes.values():
            total += plane.buffered_bytes()
        return total

    async def _event(self, node: str, method: str, payload: dict) -> None:
        """Fire-and-forget event toward a peer. Loss is part of the design
        contract (deliveries: unacked copies requeue via failure detection;
        no_ack is at-most-once; credit: replenished on the next settle) —
        but log it for the operator chasing a partition."""
        assert self.membership is not None
        try:
            await self.membership.client(node).send_event(method, payload)
        except (RpcError, OSError) as exc:
            log.debug("event %s to %s dropped: %r", method, node, exc)

    async def broadcast(self, method: str, payload: dict) -> None:
        assert self.membership is not None
        for node in self.membership.alive_members():
            if node != self.name:
                await self._event(node, method, payload)

    def broadcast_bg(self, method: str, payload: dict) -> None:
        asyncio.get_event_loop().create_task(self.broadcast(method, payload))

    def _register_handlers(self) -> None:
        rpc = self.rpc
        rpc.register("cluster.snapshot", self._h_snapshot)
        rpc.register("cluster.node-id", self._h_node_id)
        rpc.register("meta.apply", self._h_meta_apply)
        rpc.register("queue.declare", self._h_queue_declare)
        rpc.register("queue.activate", self._h_queue_activate)
        rpc.register("queue.delete", self._h_queue_delete)
        rpc.register("queue.purge", self._h_queue_purge)
        rpc.register("queue.stats", self._h_queue_stats)
        rpc.register("queue.push", self._h_queue_push)
        rpc.register("queue.push_many", self._h_queue_push_many)
        rpc.register("queue.get", self._h_queue_get)
        rpc.register("queue.consume", self._h_queue_consume)
        rpc.register("queue.cancel", self._h_queue_cancel)
        rpc.register("queue.settle", self._h_queue_settle)
        rpc.register("consumer.deliver", self._h_consumer_deliver)
        rpc.register("consumer.deliver_many", self._h_consumer_deliver_many)
        rpc.register("consumer.credit", self._h_consumer_credit)
        rpc.register("consumer.cancelled", self._h_consumer_cancelled)
        rpc.register("telemetry.pull", self._h_telemetry_pull)
        rpc.register("slo.pull", self._h_slo_pull)
        rpc.register("control.load", self._h_control_load)
        # data plane: binary zero-copy bodies, no field-table codec
        rpc.register_binary(dp.METHOD_PUSH_MANY, self._hb_push_many)
        rpc.register_binary(dp.METHOD_SETTLE_MANY, self._hb_settle_many)
        rpc.register_binary(dp.METHOD_DELIVER_MANY, self._hb_deliver_many)

    # ------------------------------------------------------------------
    # metadata replication
    # ------------------------------------------------------------------

    def _snapshot(self) -> dict:
        exchanges = []
        for vhost in self.broker.vhosts.values():
            for exchange in vhost.exchanges.values():
                if not exchange.name and vhost.name:
                    continue
                exchanges.append({
                    "vhost": vhost.name, "name": exchange.name,
                    "type": exchange.type, "durable": exchange.durable,
                    "auto_delete": exchange.auto_delete,
                    "internal": exchange.internal,
                    "arguments": dict(exchange.arguments or {}),
                    "binds": [
                        {"key": key, "queue": queue, "args": args or {}}
                        for key, queue, args in exchange.matcher.bindings()
                    ],
                    "ex_binds": [
                        {"key": key, "destination": dest, "args": args or {}}
                        for key, dest, args in (
                            exchange.ex_matcher.bindings()
                            if exchange.ex_matcher is not None else [])
                    ],
                })
        return {
            "vhosts": {v.name: v.active for v in self.broker.vhosts.values()},
            "exchanges": exchanges,
            "queues": {
                f"{vh}\x00{name}": meta
                for (vh, name), meta in self.queue_metas.items()
            },
        }

    async def _h_snapshot(self, payload: dict) -> dict:
        return self._snapshot()

    async def _apply_snapshot(self, snapshot: dict) -> None:
        self.broker.invalidate_routes()
        for vhost_name, active in (snapshot.get("vhosts") or {}).items():
            if vhost_name not in self.broker.vhosts:
                await self.broker.create_vhost(vhost_name)
            self.broker.vhosts[vhost_name].active = bool(active)
        for ex in snapshot.get("exchanges") or []:
            await self._h_meta_apply({"kind": "exchange.declared", **ex})
        for key, meta in (snapshot.get("queues") or {}).items():
            vhost, _, name = key.partition("\x00")
            self.queue_metas[(vhost, name)] = dict(meta)

    async def _anti_entropy_loop(self) -> None:
        """Heal lost meta broadcasts: every failure-timeout, pull one
        rotating alive peer's snapshot and merge entries this node is
        missing. Steady state is a no-op (no route-cache invalidation)."""
        peer_idx = 0
        while True:
            await asyncio.sleep(self._anti_entropy_s)
            if self.membership is None:
                continue
            peers = self._anti_entropy_peers()
            if not peers:
                continue
            peer = peers[peer_idx % len(peers)]
            peer_idx += 1
            try:
                snapshot = await self.membership.client(peer).call(
                    "cluster.snapshot", {}, timeout_s=5)
                await self._merge_snapshot(snapshot, peer)
            except (RpcError, OSError) as exc:
                log.debug("anti-entropy pull from %s failed: %r", peer, exc)

    def _anti_entropy_peers(self) -> list[str]:
        """Alive peers worth pulling a snapshot from. Liveness and
        lifecycle converge independently, so a departed member can gossip
        as alive for a while after LEFT lands — pulling its snapshot
        would resurrect metas it is busy forgetting."""
        from .membership import LEFT

        peers = []
        for n in self.membership.alive_members():
            if n == self.name:
                continue
            if self.membership.lifecycle_of(n) == LEFT:
                self.broker.metrics.lifecycle_left_peer_skipped += 1
                continue
            peers.append(n)
        return peers

    async def _merge_snapshot(self, snapshot: dict, peer: str) -> None:
        """Add-only snapshot merge: fill in queue metas, exchanges and
        bindings this node has never heard of. Existing local entries are
        never overwritten — local state may be newer (fresher holders,
        post-promotion metas) than the peer's."""
        from .membership import DOWN, LEFT

        merged = 0
        for key, meta in (snapshot.get("queues") or {}).items():
            vhost, _, name = key.partition("\x00")
            local = self.queue_metas.get((vhost, name))
            if local is None:
                self.queue_metas[(vhost, name)] = dict(meta)
                merged += 1
                continue
            # holder reconciliation (NOT add-only): adopt the peer's
            # holdership when it carries a strictly newer fencing epoch —
            # a drain that completed while this node was partitioned left
            # it with a stale holder that plain gap-fill would resurrect
            incoming = int(meta.get("epoch") or 0)
            current = int(local.get("epoch") or 0)
            if incoming > current:
                local["epoch"] = incoming
                if local.get("holder") != meta.get("holder"):
                    local["holder"] = meta.get("holder")
                    merged += 1
        # clear holderships pointing at members this node knows are gone
        # (left the cluster, or dead): nobody can serve them, and keeping
        # them pins proxied ops onto a corpse until the next down event
        for (vhost, name), local in self.queue_metas.items():
            holder = local.get("holder")
            if not holder or holder == self.name or self.membership is None:
                continue
            member = self.membership.members.get(holder)
            if member is not None and (member.status == DOWN
                                       or member.lifecycle == LEFT):
                local["holder"] = None
                self.broker.metrics.lifecycle_stale_holders_cleared += 1
                merged += 1
        for ex in snapshot.get("exchanges") or []:
            vhost_name = str(ex.get("vhost", ""))
            vhost = self.broker.vhosts.get(vhost_name)
            exchange = (vhost.exchanges.get(str(ex.get("name")))
                        if vhost is not None else None)
            missing = exchange is None
            if not missing:
                have = {(k, q)
                        for k, q, _a in exchange.matcher.bindings()}
                missing = any(
                    (str(b["key"]), str(b["queue"])) not in have
                    for b in ex.get("binds") or [])
            if not missing and ex.get("ex_binds"):
                have_ex = {(k, d) for k, d, _a in (
                    exchange.ex_matcher.bindings()
                    if exchange.ex_matcher is not None else [])}
                missing = any(
                    (str(b["key"]), str(b["destination"])) not in have_ex
                    for b in ex["ex_binds"])
            if missing:
                await self._h_meta_apply({"kind": "exchange.declared", **ex})
                merged += 1
        if merged:
            self.broker.invalidate_routes()
            log.info("%s: anti-entropy merged %d missing meta entr%s "
                     "from %s", self.name, merged,
                     "y" if merged == 1 else "ies", peer)

    async def _h_meta_apply(self, payload: dict) -> dict:
        """Apply one replicated metadata mutation (broadcast receiver).
        Every kind mutates routing inputs (queue metas, holders, bindings,
        exchanges), so cached publish routes drop first."""
        self.broker.invalidate_routes()
        kind = str(payload.get("kind"))
        vhost_name = str(payload.get("vhost", ""))
        if kind == "vhost.created":
            if vhost_name not in self.broker.vhosts:
                from ..broker.entities import VHost

                self.broker.vhosts[vhost_name] = VHost(vhost_name)
            return {}
        if kind == "vhost.deleted":
            self.broker.vhosts.pop(vhost_name, None)
            return {}
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            from ..broker.entities import VHost

            vhost = VHost(vhost_name)
            self.broker.vhosts[vhost_name] = vhost
        if kind == "exchange.declared":
            from ..broker.entities import Exchange

            name = str(payload["name"])
            if name not in vhost.exchanges:
                vhost.exchanges[name] = Exchange(
                    vhost_name, name, str(payload["type"]),
                    durable=bool(payload.get("durable")),
                    auto_delete=bool(payload.get("auto_delete")),
                    internal=bool(payload.get("internal")),
                    arguments=dict(payload.get("arguments") or {}),
                )
            exchange = vhost.exchanges[name]
            for bind in payload.get("binds") or []:
                exchange.matcher.bind(
                    str(bind["key"]), str(bind["queue"]), bind.get("args"))
            for bind in payload.get("ex_binds") or []:
                exchange.ensure_ex_matcher().bind(
                    str(bind["key"]), str(bind["destination"]), bind.get("args"))
            return {}
        if kind == "exchange.deleted":
            vhost.exchanges.pop(str(payload["name"]), None)
            vhost.drop_exchange_refs(str(payload["name"]))
            return {}
        if kind == "exbind.added":
            exchange = vhost.exchanges.get(str(payload["source"]))
            if exchange is not None:
                exchange.ensure_ex_matcher().bind(
                    str(payload["key"]), str(payload["destination"]),
                    payload.get("args") or None)
            return {}
        if kind == "exbind.removed":
            exchange = vhost.exchanges.get(str(payload["source"]))
            if exchange is not None and exchange.ex_matcher is not None:
                exchange.ex_matcher.unbind(
                    str(payload["key"]), str(payload["destination"]),
                    payload.get("args") or None)
            return {}
        if kind == "bind.added":
            exchange = vhost.exchanges.get(str(payload["exchange"]))
            if exchange is not None:
                exchange.matcher.bind(
                    str(payload["key"]), str(payload["queue"]),
                    payload.get("args") or None)
            return {}
        if kind == "bind.removed":
            exchange = vhost.exchanges.get(str(payload["exchange"]))
            if exchange is not None:
                exchange.matcher.unbind(
                    str(payload["key"]), str(payload["queue"]),
                    payload.get("args") or None)
            return {}
        if kind == "queue.declared":
            name = str(payload["name"])
            prev = self.queue_metas.get((vhost_name, name))
            # re-declares must not rewind the fencing epoch
            epoch = max(int(payload.get("epoch") or 0),
                        int(prev.get("epoch") or 0) if prev is not None else 0)
            self.queue_metas[(vhost_name, name)] = {
                "durable": bool(payload.get("durable")),
                "auto_delete": bool(payload.get("auto_delete")),
                "ttl_ms": payload.get("ttl_ms"),
                "arguments": payload.get("arguments") or {},
                "holder": payload.get("holder"),
                "epoch": epoch,
            }
            return {}
        if kind == "queue.holder":
            name = str(payload["name"])
            meta = self.queue_metas.get((vhost_name, name))
            if meta is not None:
                incoming = int(payload.get("epoch") or 0)
                current = int(meta.get("epoch") or 0)
                if incoming and incoming < current:
                    # fenced: a stale (pre-move) holder broadcast arriving
                    # late — e.g. from a partitioned ex-owner healing —
                    # must not overwrite the newer holdership
                    self.broker.metrics.lifecycle_stale_epoch_refused += 1
                    log.warning(
                        "%s: refused stale holder broadcast for %s/%s "
                        "(epoch %d < %d)", self.name, vhost_name, name,
                        incoming, current)
                    return {"refused": True}
                meta["holder"] = payload.get("holder")
                if incoming:
                    meta["epoch"] = incoming
            decision = payload.get("decision")
            if decision:
                # a proactive control-plane move, not a failure/ring event
                log.info("%s: holder of %s/%s -> %s (control decision %s)",
                         self.name, vhost_name, name,
                         payload.get("holder"), decision)
            if any(key[0] == vhost_name and key[1] == name
                   for key in self._remote_consumers):
                # a queue this node consumes from moved: re-register the
                # consumer on the new holder without waiting for the next
                # membership event
                asyncio.get_event_loop().create_task(
                    self._reconcile_consumers())
            return {}
        if kind == "queue.deleted":
            name = str(payload["name"])
            self.queue_metas.pop((vhost_name, name), None)
            # the reference broadcasts QueueDeleted so exchanges drop binds
            for exchange in vhost.exchanges.values():
                exchange.matcher.unbind_queue(name)
            queue = vhost.queues.get(name)
            if queue is not None:
                queue.deleted = True
                queue.gauges_detach()
                del vhost.queues[name]
            return {}
        return {}

    # ------------------------------------------------------------------
    # node-id lease (snowflake worker ids)
    # ------------------------------------------------------------------

    async def _h_node_id(self, payload: dict) -> dict:
        """Leader hands out monotonically increasing worker ids keyed by
        caller uuid (reference: GlobalNodeIdService.AskNodeId). The counter
        lives in the shared durable store, so ids never repeat even across
        leader failovers."""
        if not hasattr(self, "_lease_map"):
            self._lease_map: dict[str, int] = {}
        uuid = str(payload.get("uuid", ""))
        if uuid not in self._lease_map:
            self._lease_map[uuid] = await self.broker.store.allocate_worker_id()
        return {"worker_id": self._lease_map[uuid]}

    async def acquire_worker_id(self, uuid: str) -> int:
        assert self.membership is not None
        leader = self.membership.leader()
        if leader == self.name:
            return (await self._h_node_id({"uuid": uuid}))["worker_id"]
        reply = await self._call(leader, "cluster.node-id", {"uuid": uuid})
        return int(reply["worker_id"])

    # ------------------------------------------------------------------
    # owner-side queue op handlers
    # ------------------------------------------------------------------

    async def _local_queue(self, vhost: str, name: str) -> "Queue":
        queue = await self.broker.activate_queue(vhost, name)
        if queue is None:
            raise RpcError("not_found", f"no queue '{name}' in '{vhost}'")
        return queue

    async def _h_queue_declare(self, payload: dict) -> dict:
        queue = await self.broker.declare_queue(
            str(payload["vhost"]), str(payload["name"]),
            durable=bool(payload.get("durable")),
            auto_delete=bool(payload.get("auto_delete")),
            arguments=payload.get("arguments") or {},
        )
        return {"message_count": queue.message_count,
                "consumer_count": queue.consumer_count}

    async def _h_queue_activate(self, payload: dict) -> dict:
        vhost = str(payload["vhost"])
        name = str(payload["name"])
        if self.draining and self.broker.vhosts.get(vhost) is not None \
                and name not in self.broker.vhosts[vhost].queues:
            # a draining node takes no NEW holdership: refuse the cold
            # activation so the caller re-resolves against the ring
            raise RpcError("draining", f"{self.name} is draining")
        if payload.get("handoff") and self.replication is not None:
            # graceful handoff: the source synced our replica copy to its
            # log head before moving holdership — materialize it (private
            # stores have no other path to the message bodies)
            await self.replication.materialize_copy(vhost, name)
        queue = await self.broker.activate_queue(vhost, name)
        return {"active": queue is not None}

    async def _h_queue_delete(self, payload: dict) -> dict:
        count = await self.broker.delete_queue(
            str(payload["vhost"]), str(payload["name"]),
            if_unused=bool(payload.get("if_unused")),
            if_empty=bool(payload.get("if_empty")))
        return {"message_count": count}

    async def _h_queue_purge(self, payload: dict) -> dict:
        queue = await self._local_queue(str(payload["vhost"]), str(payload["name"]))
        return {"message_count": queue.purge()}

    async def _h_queue_stats(self, payload: dict) -> dict:
        queue = await self._local_queue(str(payload["vhost"]), str(payload["name"]))
        return {"message_count": queue.message_count,
                "consumer_count": queue.consumer_count}

    def _push_fenced(self, vhost: str, name: str) -> bool:
        """True when a push for this queue must be refused: this node is
        draining/left and the replicated meta says someone else holds the
        queue — accepting the write would re-claim a queue the drain just
        evacuated (the split-brain the fencing epochs exist to prevent)."""
        if not self.draining:
            return False
        meta = self.queue_metas.get((vhost, name))
        if meta is None:
            return True  # unknown queue: a drainer takes nothing new
        holder = meta.get("holder")
        if holder == self.name:
            return False  # still ours (drain hasn't reached it yet)
        self.broker.metrics.lifecycle_stale_epoch_refused += 1
        return True

    async def _resolve_push_queues(
        self, vhost: str, queue_names: list[str], body_len: int
    ) -> tuple[list, bool]:
        queues = []
        had_consumer = False
        for name in queue_names:
            if self._push_fenced(vhost, name):
                continue
            queue = await self.broker.activate_queue(vhost, name)
            if queue is not None:
                queues.append(queue)
                if any(c.can_take(body_len) for c in queue.consumers):
                    had_consumer = True
        return queues, had_consumer

    async def _h_queue_push(self, payload: dict) -> dict:
        """Accept routed messages for locally-owned queues (the reference's
        QueueEntity.Push ask, QueueEntity.scala:271-316)."""
        vhost = str(payload["vhost"])
        queue_names = [str(q) for q in payload.get("queues") or []]
        _, _, props = BasicProperties.decode_header(bytes(payload["props_raw"]))
        check_consumers = bool(payload.get("check_consumers"))
        body = bytes(payload["body"])
        queues, had_consumer = await self._resolve_push_queues(
            vhost, queue_names, len(body))
        if bool(payload.get("check_only")):
            return {"pushed": False, "had_consumer": had_consumer}
        if check_consumers and not had_consumer:
            return {"pushed": False, "had_consumer": False}
        tr = None
        rt = trace.ACTIVE
        raw_tr = payload.get("_trace")
        if raw_tr is not None and rt is not None:
            tr = rt.adopt(trace.Trace.from_blob(bytes(raw_tr)))
            self.broker.metrics.trace_ctx_recv += 1
        if queues:
            marks: list[tuple[int, int]] = []
            if tr is not None:
                rt.current = tr
                t_apply = time.perf_counter_ns()
            message = self.broker.push_local(
                queues, props, body,
                str(payload["exchange"]), str(payload["routing_key"]),
                bytes(payload["props_raw"]), marks)
            if tr is not None:
                tr.span(trace.REMOTE_APPLY, t_apply,
                        time.perf_counter_ns(), self.name)
                rt.current = None
            if message.persisted:
                # the reply releases the origin's confirm: barrier on the
                # group commit covering the blob + queue-log rows above
                # (attributed to just this push's enqueue window)
                await self.broker.store.flush(marks)
                if self.replication is not None and self.replication.sync:
                    await self.replication.sync_barrier()
        return {"pushed": bool(queues), "had_consumer": had_consumer}

    async def _h_queue_push_many(self, payload: dict) -> dict:
        """Batched queue.push: one RPC carries a whole read batch of plain
        pipelined publishes from one origin connection (order within the
        RPC == publish order; the origin serializes batches at its confirm
        barrier). One store flush covers every persistent push, so the
        owner group-commits the batch exactly like local publishes."""
        await self._flow_stall()
        marks: list[tuple[int, int]] = []
        any_persisted = False
        for push in payload.get("pushes") or []:
            vhost = str(push["vhost"])
            names = [str(q) for q in push.get("queues") or []]
            body = bytes(push["body"])
            queues, _ = await self._resolve_push_queues(vhost, names, len(body))
            if not queues:
                continue
            _, _, props = BasicProperties.decode_header(bytes(push["props_raw"]))
            message = self.broker.push_local(
                queues, props, body,
                str(push["exchange"]), str(push["routing_key"]),
                bytes(push["props_raw"]), marks)
            any_persisted = any_persisted or message.persisted
        if any_persisted:
            await self.broker.store.flush(marks)
            if self.replication is not None and self.replication.sync:
                await self.replication.sync_barrier()
        return {"ok": True}

    async def _flow_stall(self) -> None:
        """Owner-side pushback (flow ladder stage 3): a pressured owner
        delays accepting a push batch for one bounded wait, which holds the
        batch's reply, fills the origin's per-stream in-flight window, and
        ultimately slows the origin's publishers — the cross-hop analogue
        of parking a local publisher. Bounded, never a refusal: at worst a
        batch lands one stall late."""
        flow = self.broker.flow
        if flow is not None and flow.stage >= STAGE_CLUSTER:
            self.broker.metrics.flow_cluster_stalls += 1
            await flow.cluster_stall()

    # ------------------------------------------------------------------
    # data-plane handlers (binary fast path; see cluster/dataplane.py)
    # ------------------------------------------------------------------

    async def _hb_push_many(self, view: memoryview) -> None:
        """Binary queue.push_many: bodies and property headers land as
        memoryview slices of the RPC read buffer and go into Message.body
        uncopied. Same partial-failure contract as the table handler: a
        missing/deleted queue skips ITS push, the rest of the batch lands;
        one store flush group-commits every persistent push. The reply
        releases the origin's confirm barrier. Per-record hot path:
        resolved queues and decoded property headers memoize (origins
        re-send identical routes and props for streams of publishes)."""
        await self._flow_stall()
        self.broker.metrics.rpc_data_bytes_recv += len(view)
        marks: list[tuple[int, int]] = []
        any_persisted = False
        rcache = self.resolve_cache
        rt = trace.ACTIVE
        tctx = trace.decode_trailer(view) if rt is not None else None
        if tctx:
            self.broker.metrics.trace_ctx_recv += len(tctx)
        ridx = -1
        for vhost, names, exchange, routing_key, props_raw, body in \
                dp.decode_push_many(view):
            ridx += 1
            queues = []
            for name in names:
                if self._push_fenced(vhost, name):
                    continue
                queue = rcache.get((vhost, name))
                if queue is None:
                    # slow path activates from the store; misses (unknown
                    # queue) stay uncached so a later declare is seen
                    queue = await self.broker.activate_queue(vhost, name)
                    if queue is None:
                        continue
                    rcache[(vhost, name)] = queue
                queues.append(queue)
            if not queues:
                continue
            props = _props_memo(props_raw)
            tr = tctx.get(ridx) if tctx else None
            if tr is not None:
                tr = rt.adopt(tr)
                rt.current = tr
                t_apply = time.perf_counter_ns()
            message = self.broker.push_local(
                queues, props, body, exchange, routing_key, props_raw, marks)
            if tr is not None:
                tr.span(trace.REMOTE_APPLY, t_apply,
                        time.perf_counter_ns(), self.name)
                rt.current = None
            any_persisted = any_persisted or message.persisted
        if any_persisted:
            await self.broker.store.flush(marks)
            if self.replication is not None and self.replication.sync:
                await self.replication.sync_barrier()
        return None

    async def _hb_settle_many(self, view: memoryview) -> None:
        """Binary queue.settle_many: one frame settles offsets across any
        number of (queue, op, tag) groups coalesced inside the origin's
        flush window. Application order follows frame order, so an ack
        buffered before a requeue of the same consumer applies first."""
        self.broker.metrics.rpc_data_bytes_recv += len(view)
        rt = trace.ACTIVE
        if rt is not None:
            tctx = trace.decode_trailer(view)
            if tctx:
                # merge origin-side deliver/settle spans into the owner's
                # parked copies; the owner's queue.ack below finalizes its
                # own view via message.trace
                self.broker.metrics.trace_ctx_recv += len(tctx)
                for wire_tr in tctx.values():
                    rt.adopt(wire_tr)
        for vhost_name, queue_name, op, tag, credit, offsets in \
                dp.decode_settle_many(view):
            vhost = self.broker.vhosts.get(vhost_name)
            queue = vhost.queues.get(queue_name) if vhost else None
            if queue is None:
                continue
            for offset in offsets:
                delivery = queue.outstanding.get(offset)
                if delivery is None:
                    continue
                if op == "ack":
                    queue.ack(delivery)
                elif op == "drop":
                    queue.drop(delivery)
                else:
                    queue.requeue(delivery)
            if tag and credit:
                for consumer in queue.consumers:
                    if isinstance(consumer, RemoteConsumer) \
                            and consumer.tag == tag:
                        consumer.credit += credit
                        for offset in offsets:
                            consumer.outstanding_offsets.discard(offset)
            queue.schedule_dispatch()
        return None

    async def _hb_deliver_many(self, view: memoryview) -> None:
        """Binary consumer.deliver_many (origin side): every record renders
        to the client synchronously BEFORE any await, so two pipelined
        batches for one consumer can never interleave; credit replenishes
        once per batch."""
        self.broker.metrics.rpc_data_bytes_recv += len(view)
        vhost, queue, tag, records = dp.decode_deliver_many(view)
        key = (vhost, queue, tag)
        info = self._remote_consumers.get(key)
        if info is None:
            return None
        stub = info["stub"]
        channel: "ServerChannel" = info["channel"]
        if channel.closed:
            return None
        from ..broker.entities import Message, QueuedMessage

        rt = trace.ACTIVE
        tctx = trace.decode_trailer(view) if rt is not None else None
        if tctx:
            self.broker.metrics.trace_ctx_recv += len(tctx)
        applied = 0
        for (offset, redelivered, msg_id, expire_at_ms, exchange,
                routing_key, props_raw, body) in records:
            props = _props_memo(props_raw)
            message = Message(
                msg_id, props, body, exchange, routing_key,
                header_raw=props_raw)
            if tctx:
                wire_tr = tctx.get(applied)
                if wire_tr is not None:
                    # stitch: the parked origin half (ingress/route/
                    # cluster-push) merges with the owner-side spans the
                    # trailer carried; deliver/settle stamp below
                    message.trace = rt.adopt(wire_tr)
            qm = QueuedMessage(message, offset, expire_at_ms)
            qm.redelivered = redelivered
            channel.deliver(stub, stub.queue, qm)
            applied += 1
        if info["no_ack"] and applied:
            # replenish credit as we render (owner decremented on send)
            info["pending_credit"] = info.get("pending_credit", 0) + applied
            if info["pending_credit"] >= 32:
                credit = info["pending_credit"]
                info["pending_credit"] = 0
                await self._event(info["owner"], "consumer.credit", {
                    "vhost": vhost, "queue": queue, "tag": tag,
                    "credit": credit})
        return None

    async def _h_queue_get(self, payload: dict) -> dict:
        queue = await self._local_queue(str(payload["vhost"]), str(payload["queue"]))
        qm = await queue.basic_get()
        if qm is None:
            return {"empty": True, "message_count": queue.message_count}
        msg = qm.message
        out = {
            "empty": False,
            "offset": qm.offset,
            "redelivered": qm.redelivered,
            "exchange": msg.exchange,
            "routing_key": msg.routing_key,
            "props_raw": msg.properties.encode_header(len(msg.body)),
            "body": msg.body,
            "msg_id": msg.id,
            "expire_at_ms": qm.expire_at_ms,
            "message_count": queue.message_count,
        }
        if bool(payload.get("no_ack")):
            self.broker.unrefer(msg)
        else:
            from ..broker.entities import Delivery

            delivery = Delivery(qm, queue, None, "", 0, no_ack=False)  # type: ignore[arg-type]
            queue.outstanding[qm.offset] = delivery
            if queue._counted:
                self.broker.queue_unacked += 1
            if queue.durable and msg.persisted:
                self.broker.store_bg(self.broker.store.insert_queue_unacks(
                    queue.vhost, queue.name,
                    [(msg.id, qm.offset, qm.body_size, qm.expire_at_ms)]))
                if queue.repl is not None:
                    queue.repl.append("unacks", {"rows": [
                        [msg.id, qm.offset, qm.body_size, qm.expire_at_ms]]})
        return out

    async def _h_queue_consume(self, payload: dict) -> dict:
        queue = await self._local_queue(str(payload["vhost"]), str(payload["queue"]))
        tag = str(payload["tag"])
        origin = str(payload["origin"])
        # idempotent re-register: replace any previous incarnation
        for consumer in list(queue.consumers):
            if isinstance(consumer, RemoteConsumer) and consumer.tag == tag \
                    and consumer.origin == origin:
                queue.consumers.remove(consumer)
                if queue._counted:
                    self.broker.queue_consumers -= 1
        consumer = RemoteConsumer(
            self, tag, queue, bool(payload.get("no_ack")), origin,
            int(payload.get("credit", DEFAULT_CREDIT)),
            priority=int(payload.get("priority", 0)))
        queue.add_consumer(consumer)
        return {"ok": True}

    async def _h_queue_cancel(self, payload: dict) -> dict:
        vhost = self.broker.vhosts.get(str(payload["vhost"]))
        queue = vhost.queues.get(str(payload["queue"])) if vhost else None
        if queue is None:
            return {"ok": False}
        tag = str(payload["tag"])
        origin = str(payload["origin"])
        for consumer in list(queue.consumers):
            if isinstance(consumer, RemoteConsumer) and consumer.tag == tag \
                    and consumer.origin == origin:
                if bool(payload.get("requeue_outstanding", True)):
                    consumer.requeue_outstanding()
                auto_deleted = queue.remove_consumer(consumer)
                if auto_deleted:
                    self.broker.schedule_queue_delete(queue.vhost, queue.name)
        return {"ok": True}

    async def _h_queue_settle(self, payload: dict) -> dict:
        """Ack/drop/requeue outstanding deliveries by offset (origin -> owner);
        also replenishes the remote consumer's credit."""
        vhost = self.broker.vhosts.get(str(payload["vhost"]))
        queue = vhost.queues.get(str(payload["queue"])) if vhost else None
        if queue is None:
            return {"ok": False}
        op = str(payload.get("op", "ack"))
        offsets = [int(o) for o in payload.get("offsets") or []]
        for offset in offsets:
            delivery = queue.outstanding.get(offset)
            if delivery is None:
                continue
            if op == "ack":
                queue.ack(delivery)
            elif op == "drop":
                queue.drop(delivery)
            else:
                queue.requeue(delivery)
        tag = str(payload.get("tag", ""))
        credit = int(payload.get("credit", 0))
        if tag and credit:
            for consumer in queue.consumers:
                if isinstance(consumer, RemoteConsumer) and consumer.tag == tag:
                    consumer.credit += credit
                    for offset in offsets:
                        consumer.outstanding_offsets.discard(offset)
        queue.schedule_dispatch()
        return {"ok": True}

    async def _h_consumer_credit(self, payload: dict) -> dict:
        vhost = self.broker.vhosts.get(str(payload["vhost"]))
        queue = vhost.queues.get(str(payload["queue"])) if vhost else None
        if queue is None:
            return {"ok": False}
        tag = str(payload["tag"])
        for consumer in queue.consumers:
            if isinstance(consumer, RemoteConsumer) and consumer.tag == tag:
                consumer.credit += int(payload.get("credit", 0))
        queue.schedule_dispatch()
        return {"ok": True}

    # ------------------------------------------------------------------
    # origin-side: deliveries arriving from owners
    # ------------------------------------------------------------------

    async def _apply_remote_delivery(
        self, key: tuple, info: dict, payload: dict
    ) -> bool:
        from ..broker.entities import Message, QueuedMessage

        stub = info["stub"]
        channel: "ServerChannel" = info["channel"]
        if channel.closed:
            return False
        props_raw = bytes(payload["props_raw"])
        _, _, props = BasicProperties.decode_header(props_raw)
        message = Message(
            int(payload["msg_id"]), props, bytes(payload["body"]),
            str(payload["exchange"]), str(payload["routing_key"]),
            header_raw=props_raw)
        qm = QueuedMessage(message, int(payload["offset"]), payload.get("expire_at_ms"))
        qm.redelivered = bool(payload.get("redelivered"))
        channel.deliver(stub, stub.queue, qm)
        if info["no_ack"]:
            # replenish credit as we render (owner decremented on send)
            info["pending_credit"] = info.get("pending_credit", 0) + 1
            if info["pending_credit"] >= 32:
                credit = info["pending_credit"]
                info["pending_credit"] = 0
                await self._event(info["owner"], "consumer.credit", {
                    "vhost": key[0], "queue": key[1], "tag": key[2],
                    "credit": credit})
        return True

    async def _h_consumer_deliver(self, payload: dict) -> dict:
        key = (str(payload["vhost"]), str(payload["queue"]), str(payload["tag"]))
        info = self._remote_consumers.get(key)
        if info is None:
            return {"ok": False}
        return {"ok": await self._apply_remote_delivery(key, info, payload)}

    async def _h_consumer_deliver_many(self, payload: dict) -> dict:
        """One coalesced dispatch pass from an owner: apply every delivery
        in order (credit replenishment accumulates across the batch)."""
        key = (str(payload["vhost"]), str(payload["queue"]), str(payload["tag"]))
        info = self._remote_consumers.get(key)
        if info is None:
            return {"ok": False}
        for delivery in payload.get("deliveries") or []:
            await self._apply_remote_delivery(key, info, delivery)
        return {"ok": True}

    # ------------------------------------------------------------------
    # origin-side proxy API (used by broker/connection)
    # ------------------------------------------------------------------

    async def remote_declare(self, vhost: str, name: str, **kwargs: Any) -> dict:
        owner = self.queue_owner(vhost, name)
        return await self._call(owner, "queue.declare",
                                {"vhost": vhost, "name": name, **kwargs})

    async def remote_delete(self, vhost: str, name: str, *,
                            if_unused: bool = False, if_empty: bool = False) -> int:
        owner = self.queue_owner(vhost, name)
        reply = await self._call(owner, "queue.delete", {
            "vhost": vhost, "name": name,
            "if_unused": if_unused, "if_empty": if_empty})
        return int(reply.get("message_count", 0))

    async def remote_purge(self, vhost: str, name: str) -> int:
        owner = self.queue_owner(vhost, name)
        reply = await self._call(owner, "queue.purge", {"vhost": vhost, "name": name})
        return int(reply.get("message_count", 0))

    async def remote_stats(self, vhost: str, name: str) -> tuple[int, int]:
        owner = self.queue_owner(vhost, name)
        reply = await self._call(owner, "queue.stats", {"vhost": vhost, "name": name})
        return int(reply.get("message_count", 0)), int(reply.get("consumer_count", 0))

    def submit_batch(self, records: list) -> set[asyncio.Future]:
        """Submit a read batch of pipelined publishes to the data plane
        (records: (owner, (vhost, queues, exchange, routing_key, props_raw,
        body)) in publish order) and demand-flush the covering micro-
        batches onto their streams. Synchronous: the RPCs are on the wire
        (or queued behind a stream window) when this returns, so callers
        can keep submitting later batches while earlier ones fly. Bodies
        ride by reference into the binary frames — no copies."""
        futures: set[asyncio.Future] = set()
        planes: dict[str, PeerDataPlane] = {}
        for owner, rec in records:
            plane = planes.get(owner)
            if plane is None:
                planes[owner] = plane = self.dataplane(owner)
            futures.add(plane.submit_push(*rec))
        # demand-flush: this caller's barrier must not wait out the window
        # timer (other connections' pushes may still coalesce in behind)
        for plane in planes.values():
            plane.flush_all(demand=True)
        return futures

    @staticmethod
    async def await_batch(futures: set[asyncio.Future]) -> list[BaseException]:
        """Barrier on submit_batch futures. Returns failures instead of
        raising — the caller's barrier decides strictness (confirm mode:
        connection error; best-effort: logged)."""
        results = await asyncio.gather(*futures, return_exceptions=True)
        return [r for r in results if isinstance(r, BaseException)]

    async def push_batch(self, records: list) -> list[BaseException]:
        """submit_batch + await_batch in one step (synchronous callers)."""
        return await self.await_batch(self.submit_batch(records))

    async def remote_push(
        self, owner: str, vhost: str, queues: list[str], props_raw: bytes,
        body: bytes, exchange: str, routing_key: str, check_consumers: bool,
        check_only: bool = False, tr=None,
    ) -> tuple[bool, bool]:
        payload = {
            "vhost": vhost, "queues": queues, "props_raw": props_raw,
            "body": body, "exchange": exchange, "routing_key": routing_key,
            "check_consumers": check_consumers, "check_only": check_only,
        }
        if tr is not None and not check_only:
            # control-plane trace propagation (the slow mandatory/immediate
            # path); the data plane carries it as the payload trailer
            payload["_trace"] = tr.to_blob()
            rt = trace.ACTIVE
            if rt is not None:
                rt.park(tr)
            self.broker.metrics.trace_ctx_sent += 1
        reply = await self._call(owner, "queue.push", payload)
        return bool(reply.get("pushed")), bool(reply.get("had_consumer"))

    async def remote_get(self, vhost: str, name: str, no_ack: bool) -> dict:
        owner = self.queue_owner(vhost, name)
        return await self._call(owner, "queue.get", {
            "vhost": vhost, "queue": name, "no_ack": no_ack})

    async def remote_consume(
        self, channel: "ServerChannel", vhost: str, name: str, tag: str,
        no_ack: bool, credit: int = 0, priority: int = 0,
    ) -> "RemoteQueueRef":
        # default window: chana.mq.cluster.consume-credit — sized so
        # pipelined deliveries stream ahead of the settle round trip
        credit = credit or self.consume_credit
        owner = self.queue_owner(vhost, name)
        ref = RemoteQueueRef(self, vhost, name)
        from ..broker.channel import Consumer

        stub = Consumer(tag, channel, ref, no_ack, False)  # type: ignore[arg-type]
        self._remote_consumers[(vhost, name, tag)] = {
            "channel": channel, "stub": stub, "no_ack": no_ack,
            "priority": priority,
            "credit": credit, "owner": owner, "pending_credit": 0,
        }
        try:
            await self._call(owner, "queue.consume", {
                "vhost": vhost, "queue": name, "tag": tag,
                "no_ack": no_ack, "origin": self.name, "credit": credit,
                "priority": priority})
        except Exception:
            self._remote_consumers.pop((vhost, name, tag), None)
            raise
        channel.consumers[tag] = stub
        return ref

    def notify_remote_cancel_bg(
        self, origin: str, vhost: str, name: str, tag: str
    ) -> None:
        """Fire-and-forget consumer-cancelled event toward the origin node
        (owner-side queue death under a remote consumer)."""

        async def _notify() -> None:
            try:
                await self._event(origin, "consumer.cancelled", {
                    "vhost": vhost, "queue": name, "tag": tag})
            except Exception:
                log.debug("consumer.cancelled to %s dropped", origin)

        asyncio.get_event_loop().create_task(_notify())

    async def _h_consumer_cancelled(self, payload: dict) -> dict:
        """Origin-side: the owner cancelled our remote consumer (its queue
        died). Deregister the stub and notify the client."""
        key = (str(payload["vhost"]), str(payload["queue"]),
               str(payload["tag"]))
        info = self._remote_consumers.pop(key, None)
        if info is not None:
            channel = info["channel"]
            channel.consumers.pop(key[2], None)
            channel.connection.notify_consumer_cancel(channel, key[2])
        return {}

    async def _h_telemetry_pull(self, payload: dict) -> dict:
        """Serve this node's telemetry snapshot to a peer aggregating the
        cluster view (any node's /admin/timeseries|health|alerts)."""
        svc = self.broker.telemetry
        if svc is None:
            return {"node": self.name, "error": "telemetry disabled"}
        window = max(1, min(int(payload.get("window", 60)), 4096))
        top = max(0, int(payload.get("top", 0)))
        return svc.local_payload(window, top)

    async def _h_slo_pull(self, payload: dict) -> dict:
        """Serve this node's SLO snapshot to a peer aggregating the
        cluster view (any node's GET /admin/slo?scope=cluster)."""
        svc = self.broker.telemetry
        if svc is None or svc.slo is None:
            return {"node": self.name, "error": "slo disabled"}
        return {"node": self.name, **svc.slo.snapshot()}

    async def _h_control_load(self, payload: dict) -> dict:
        """Serve this node's inflow-load figure (bytes/s EWMA) to a peer's
        control plane evaluating a rebalance decision."""
        control = getattr(self.broker, "control", None)
        return {"node": self.name,
                "load": float(control.load_rate) if control is not None
                else 0.0}

    async def remote_cancel(self, vhost: str, name: str, tag: str) -> None:
        info = self._remote_consumers.pop((vhost, name, tag), None)
        if info is None:
            return
        try:
            await self._call(info["owner"], "queue.cancel", {
                "vhost": vhost, "queue": name, "tag": tag, "origin": self.name})
        except (RpcError, OSError):
            pass

    def settle_bg(self, vhost: str, name: str, op: str, offsets: list[int],
                  tag: str = "", credit: int = 0, tr=None) -> None:
        """Fire-and-forget settle (ack/drop/requeue) toward the queue
        owner via the data plane. Settles coalesce per (owner, queue, op,
        tag) inside the peer's flush window — a consumer acking a whole
        read batch (or several consumers across channels) costs one binary
        settle_many frame, not one RPC per message."""
        owner = self.queue_owner(vhost, name)
        self.dataplane(owner).submit_settle(
            vhost, name, op, offsets, tag, credit, tr=tr)

    async def _drain_settles(self) -> None:
        """Flush + await every in-flight settle batch on every peer — the
        data/control-plane ordering fence. The planes ride separate
        connections from the control RPCs, so a settle enqueued before a
        cancel / delete / purge is only guaranteed applied on the owner
        because _call awaits this first (ack-then-cancel in one read batch
        must not requeue the acked message)."""
        for plane in list(self._dataplanes.values()):
            await plane.drain_settles()


class RemoteConsumer:
    """Owner-side representation of a consumer living on another node.
    Implements the Consumer dispatch interface (can_take / deliver / detach)."""

    __slots__ = ("cluster", "tag", "queue", "no_ack", "origin", "credit",
                 "exclusive", "priority", "outstanding_offsets", "_buf",
                 "_buf_count", "_flush_scheduled", "_traces")

    def __init__(self, cluster: ClusterNode, tag: str, queue: "Queue",
                 no_ack: bool, origin: str, credit: int,
                 priority: int = 0) -> None:
        self.cluster = cluster
        self.tag = tag
        self.queue = queue
        self.no_ack = no_ack
        # x-priority forwarded from the origin's basic.consume: the owner's
        # dispatch honors it like a local consumer's
        self.priority = priority
        self.origin = origin
        self.credit = credit
        self.exclusive = False
        self.outstanding_offsets: set[int] = set()
        # per-tick delivery coalescing: every deliver() of one dispatch
        # pass rides a single binary deliver_many event (same pattern as
        # the store's group-commit kick); flat [meta, body, ...] buffers
        self._buf: list = []
        self._buf_count = 0
        self._flush_scheduled = False
        # (record_idx, Trace) entries riding the next deliver_many trailer
        self._traces: list = []

    def can_take(self, next_size: int) -> bool:
        if self.credit <= 0:
            return False
        membership = self.cluster.membership
        return membership is None or membership.is_alive(self.origin)

    def deliver(self, queue: "Queue", qm: "QueuedMessage") -> Optional["Delivery"]:
        from ..broker.entities import Delivery

        self.credit -= 1
        msg = qm.message
        # encode inline: two small buffers per record (meta + body-by-ref),
        # the body is never copied between the queue and the socket
        self._buf.extend(dp.encode_deliver_record(
            qm.offset, qm.redelivered, msg.id, qm.expire_at_ms,
            msg.exchange, msg.routing_key, msg.header_payload(), msg.body))
        if trace.ACTIVE is not None and msg.trace is not None:
            self._traces.append((self._buf_count, msg.trace))
        self._buf_count += 1
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)
        if self.no_ack:
            return None
        self.outstanding_offsets.add(qm.offset)
        return Delivery(qm, queue, None, self.tag, 0, no_ack=False)  # type: ignore[arg-type]

    # keep each deliver_many event frame comfortably under rpc.MAX_FRAME
    # (64 MB): big-bodied backlogs split into multiple ordered events
    _FLUSH_BYTES = 8 * 1024 * 1024

    def _flush(self) -> None:
        """Ship the coalesced dispatch pass as binary deliver_many events
        (one per size-capped chunk, all striped onto the same data stream
        so they render in order on the origin)."""
        self._flush_scheduled = False
        if not self._buf:
            return
        records, self._buf = self._buf, []
        count, self._buf_count = self._buf_count, 0
        traces, self._traces = self._traces, []
        plane = self.cluster.dataplane(self.origin)
        chunk: list = []
        chunk_count = 0
        size = 0
        base = 0  # first record index of the current chunk
        # records is a flat [meta, body, meta, body, ...] buffer list
        for i in range(0, len(records), 2):
            chunk.append(records[i])
            chunk.append(records[i + 1])
            chunk_count += 1
            size += len(records[i]) + len(records[i + 1])
            if size >= self._FLUSH_BYTES:
                plane.send_deliver_many(
                    self.queue.vhost, self.queue.name, self.tag,
                    chunk, chunk_count,
                    traces=[(ri - base, t) for ri, t in traces
                            if base <= ri < base + chunk_count]
                    if traces else None)
                base += chunk_count
                chunk, chunk_count, size = [], 0, 0
        if chunk:
            plane.send_deliver_many(
                self.queue.vhost, self.queue.name, self.tag,
                chunk, chunk_count,
                traces=[(ri - base, t) for ri, t in traces if ri >= base]
                if traces else None)

    def detach(self) -> None:
        """The owner's queue died under this remote consumer: tell the
        origin node so it can deregister the stub and send the client a
        Basic.Cancel (consumer_cancel_notify)."""
        self.cluster.notify_remote_cancel_bg(
            self.origin, self.queue.vhost, self.queue.name, self.tag)

    def requeue_outstanding(self) -> None:
        for offset in sorted(self.outstanding_offsets):
            delivery = self.queue.outstanding.get(offset)
            if delivery is not None:
                self.queue.requeue(delivery)
        self.outstanding_offsets.clear()


class RemoteQueueRef:
    """Origin-side facade standing in for a remotely-owned queue in the
    channel bookkeeping (ack/requeue/drop route over RPC)."""

    __slots__ = ("cluster", "vhost", "name")

    def __init__(self, cluster: ClusterNode, vhost: str, name: str) -> None:
        self.cluster = cluster
        self.vhost = vhost
        self.name = name

    # channel bookkeeping hooks ------------------------------------------

    def ack(self, delivery: "Delivery") -> None:
        tr = None
        if trace.ACTIVE is not None:
            tr = delivery.queued.message.trace
            if tr is not None:
                trace.ACTIVE.on_settle(tr, self.cluster.broker.trace_node)
        self.cluster.settle_bg(
            self.vhost, self.name, "ack", [delivery.queued.offset],
            tag=delivery.consumer_tag, credit=1, tr=tr)

    def drop(self, delivery: "Delivery") -> None:
        tr = None
        if trace.ACTIVE is not None:
            tr = delivery.queued.message.trace
            if tr is not None:
                trace.ACTIVE.on_settle(tr, self.cluster.broker.trace_node)
        self.cluster.settle_bg(
            self.vhost, self.name, "drop", [delivery.queued.offset],
            tag=delivery.consumer_tag, credit=1, tr=tr)

    def requeue(self, delivery: "Delivery") -> None:
        self.cluster.settle_bg(
            self.vhost, self.name, "requeue", [delivery.queued.offset],
            tag=delivery.consumer_tag, credit=1)

    def schedule_dispatch(self) -> None:
        pass

    def remove_consumer(self, consumer: Any) -> bool:
        asyncio.get_event_loop().create_task(
            self.cluster.remote_cancel(self.vhost, self.name, consumer.tag))
        return False

    @property
    def consumers(self) -> list:
        return []

    def has_exclusive_consumer(self) -> bool:
        return False
